"""FaultPlan: a seeded, deterministic schedule of induced faults.

The soak harness's core bargain is *replayability*: a fault campaign
that cannot be re-run bit-for-bit is a flake generator, not a test. A
:class:`FaultPlan` is generated from one integer seed by a private
``random.Random`` — same seed, same schedule, down to the corruption
rectangles — and round-trips through JSON so the soak report carries
the exact plan it executed.

Fault kinds span the failure modes the obs/resilience stack claims to
survive (SURVEY.md §6, ISSUE 11):

- ``corrupt_region`` / ``drop_region`` — state gone bad, via the
  ``utils/fault.py`` injectors (rectangles stored as grid *fractions*
  so one plan applies to any shape);
- ``corrupt_shard`` / ``drop_shard`` — one device's buffer lost in
  flight (falls back to the region form when the engine has no mesh or
  a representation the shard injectors refuse);
- ``stall`` — a subscriber that sleeps past the watchdog deadline
  inside the watched tick, so the StallWatchdog + flight recorder path
  fires for real;
- ``retrace`` — a guaranteed real XLA compile after warmup (a fresh
  ``tracked_jit`` instance around a salt-constant function no cache can
  have seen), so the RetraceSentinel attribution path fires for real;
- ``kill`` — SIGKILL of the worker process. Never applied in-process:
  the fleet driver (scripts/soak.py) owns it, the worker only sees the
  resume.
- ``process_kill`` / ``process_preempt`` / ``checkpoint_corrupt`` —
  the *distributed* fault kinds (ISSUE 14), also driver-level: SIGKILL
  a named peer of a multi-host fleet, SIGTERM it with a grace window
  (finish chunk, checkpoint, exit "preempted"), or flip bytes in a
  shard of the newest committed sharded checkpoint so the next restore
  must refuse it and fall back a generation. The elastic fleet driver
  (resilience/distributed.py, scripts/chaos_multihost.py) consumes
  them; in-process appliers refuse them by construction.

Faults address workers by index and fire at a generation threshold, so
the schedule is defined in simulation time, not wall time — the only
clock that replays.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import time
from typing import List, Optional, Sequence

# in-process kinds the worker applies between supervised chunks; driver
# kinds belong to the fleet driver (a process can hardly
# SIGKILL-and-resume itself, and checkpoint corruption must land while
# nobody is mid-write)
STATE_KINDS = ("corrupt_region", "drop_region", "corrupt_shard",
               "drop_shard")
PROCESS_KINDS = ("stall", "retrace", "kill")
DRIVER_KINDS = ("kill", "process_kill", "process_preempt",
                "checkpoint_corrupt")
ALL_KINDS = STATE_KINDS + PROCESS_KINDS + DRIVER_KINDS[1:]


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: fire against ``worker`` once its simulation
    reaches ``at_gen``. ``params`` is kind-specific (fractional rect for
    region faults, shard fraction for shard faults, rng seed for the
    corruptors) and JSON-plain by construction."""

    worker: int
    at_gen: int
    kind: str
    params: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        return cls(worker=int(d["worker"]), at_gen=int(d["at_gen"]),
                   kind=str(d["kind"]), params=dict(d.get("params", {})))


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An ordered fault schedule plus the seed that regenerates it."""

    seed: int
    events: tuple

    def for_worker(self, worker: int, *,
                   kinds: Optional[Sequence[str]] = None) -> List[FaultEvent]:
        out = [e for e in self.events if e.worker == worker]
        if kinds is not None:
            out = [e for e in out if e.kind in kinds]
        return out

    def kinds(self) -> List[str]:
        return sorted({e.kind for e in self.events})

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "events": [e.to_dict() for e in self.events]})

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls(seed=int(d["seed"]),
                   events=tuple(FaultEvent.from_dict(e)
                                for e in d["events"]))

    @classmethod
    def generate(cls, seed: int, *, workers: int, horizon: int,
                 faults_per_worker: int = 3,
                 kinds: Sequence[str] = ALL_KINDS,
                 ensure_kinds: Sequence[str] = (),
                 kill_workers: Sequence[int] = ()) -> "FaultPlan":
        """Deterministically schedule ``faults_per_worker`` state/process
        faults per worker across generations ``[horizon//4, 3·horizon//4]``
        (never at the very start — warmup must finish — nor so late the
        recovery has no generations left to prove itself in), plus one
        ``kill`` for each index in ``kill_workers``. ``ensure_kinds``
        adds one extra event per listed kind the random draw happened to
        miss — how the soak driver guarantees its coverage floor without
        giving up seeded randomness. Same seed, same plan: the only
        entropy source is one ``random.Random(seed)``."""
        if workers <= 0:
            raise ValueError(f"workers must be positive, got {workers}")
        if horizon < 8:
            raise ValueError(f"horizon too short to schedule into: {horizon}")
        rng = random.Random(seed)
        # the per-worker random draw stays in-process-only: driver kinds
        # are scheduled deliberately (ensure_kinds / kill_workers), not
        # sprayed — a random process_kill of every worker is an outage,
        # not a campaign
        injectable = [k for k in kinds if k not in DRIVER_KINDS]
        if faults_per_worker > 0 and not injectable:
            raise ValueError(
                f"no in-process fault kinds in {tuple(kinds)}; pass "
                "faults_per_worker=0 and schedule driver kinds via "
                "ensure_kinds")
        lo, hi = max(1, horizon // 4), max(2, (3 * horizon) // 4)
        events: List[FaultEvent] = []
        for w in range(workers):
            for _ in range(faults_per_worker):
                kind = rng.choice(injectable)
                events.append(FaultEvent(
                    worker=w, at_gen=rng.randint(lo, hi), kind=kind,
                    params=_draw_params(rng, kind)))
        for kind in ensure_kinds:
            if kind != "kill" and not any(e.kind == kind for e in events):
                events.append(FaultEvent(
                    worker=rng.randrange(workers),
                    at_gen=rng.randint(lo, hi), kind=kind,
                    params=_draw_params(rng, kind)))
        for w in kill_workers:
            events.append(FaultEvent(worker=int(w),
                                     at_gen=rng.randint(lo, hi),
                                     kind="kill"))
        events.sort(key=lambda e: (e.worker, e.at_gen, e.kind))
        return cls(seed=seed, events=tuple(events))


def _draw_params(rng: random.Random, kind: str) -> dict:
    if kind in ("corrupt_region", "drop_region"):
        p = {
            "top_f": round(rng.uniform(0.0, 0.6), 4),
            "left_f": round(rng.uniform(0.0, 0.6), 4),
            "h_f": round(rng.uniform(0.1, 0.4), 4),
            "w_f": round(rng.uniform(0.1, 0.4), 4),
        }
        if kind == "corrupt_region":
            p["seed"] = rng.randrange(2 ** 31)
        return p
    if kind in ("corrupt_shard", "drop_shard"):
        p = {"shard_f": round(rng.uniform(0.0, 0.999), 4)}
        if kind == "corrupt_shard":
            p["seed"] = rng.randrange(2 ** 31)
        return p
    if kind == "process_preempt":
        # grace window the driver allows between SIGTERM and SIGKILL
        # escalation — long enough to finish a chunk and checkpoint
        return {"grace_seconds": round(rng.uniform(5.0, 15.0), 2)}
    if kind == "checkpoint_corrupt":
        return {"seed": rng.randrange(2 ** 31)}
    return {}


# -- in-process application ---------------------------------------------------

def induce_retrace() -> None:
    """Pay one guaranteed-real XLA compile, visible to the process
    compile log as a ``cache_miss``.

    Guaranteed because nothing can have cached it: the function is a
    fresh ``tracked_jit`` instance (no in-process jit-cache hit) whose
    body folds a pid+monotonic-clock salt in as an HLO constant (no
    persistent-compile-cache hit — the HLO hash is new every time). This
    models the production failure the RetraceSentinel exists for: a
    shape/dtype/donation drift silently recompiling a warmed engine.
    """
    import jax.numpy as jnp

    from ..ops._jit import tracked_jit

    salt = ((os.getpid() << 20) ^ time.perf_counter_ns()) & 0x7FFFFFFF

    @tracked_jit(runner="resilience.induced_retrace")
    def _poke(x):
        return x + jnp.int32(salt)

    _poke(jnp.zeros((), jnp.int32)).block_until_ready()


def induce_stall(coordinator, sleep_seconds: float) -> None:
    """Arm a one-shot subscriber that sleeps ``sleep_seconds`` inside the
    next tick's notify phase — inside the watchdog's watch scope, so the
    monitor thread flags a real StallEvent (and the flight recorder
    chained on it dumps) while the tick is genuinely stuck."""
    unsubscribe_box = []

    def _sleeper(frame) -> None:
        unsubscribe_box[0]()  # one-shot: the replayed chunk must be clean
        time.sleep(sleep_seconds)

    unsubscribe_box.append(coordinator.subscribe(_sleeper))


def apply_fault(supervisor, event: FaultEvent, *,
                stall_seconds: float = 1.0) -> str:
    """Fire one in-process fault against a supervised coordinator,
    routed through :meth:`Supervisor.inject` so the supervisor knows a
    *detected* fault is pending and will restore at the chunk boundary.
    Returns the kind actually applied (shard faults degrade to their
    region form on engines the shard injectors refuse — unsharded or
    sparse — keeping one plan valid across every worker flavor)."""
    from ..utils import fault as fault_lib

    engine = supervisor.coordinator.engine
    kind, p = event.kind, event.params
    if kind in ("corrupt_shard", "drop_shard"):
        shards = getattr(engine.state, "addressable_shards", None)
        packed_words = (engine.state.ndim == 2
                        and engine.state.dtype == "uint32")
        if (engine.mesh is None or engine.backend == "sparse"
                or not shards
                or (kind == "corrupt_shard" and not packed_words)):
            kind = ("corrupt_region" if kind == "corrupt_shard"
                    else "drop_region")
            p = {"top_f": p.get("shard_f", 0.0) * 0.5, "left_f": 0.0,
                 "h_f": 0.25, "w_f": 0.25, "seed": p.get("seed", 0)}
        else:
            idx = min(int(p["shard_f"] * len(shards)), len(shards) - 1)
            if kind == "drop_shard":
                supervisor.inject(
                    kind, lambda e: fault_lib.drop_shard(e, idx))
            else:
                supervisor.inject(
                    kind, lambda e: fault_lib.corrupt_shard(
                        e, idx, seed=p.get("seed", 0)))
            return kind
    if kind in ("corrupt_region", "drop_region"):
        h, w = engine.shape
        top, left = int(p["top_f"] * h), int(p["left_f"] * w)
        rh = max(1, int(p["h_f"] * h))
        rw = max(1, int(p["w_f"] * w))
        rh, rw = min(rh, h - top), min(rw, w - left)
        if kind == "corrupt_region":
            supervisor.inject(
                kind, lambda e: fault_lib.corrupt_region(
                    e, top, left, rh, rw, seed=p.get("seed", 0)))
        else:
            supervisor.inject(
                kind, lambda e: fault_lib.drop_region(e, top, left, rh, rw))
        return kind
    if kind == "stall":
        supervisor.inject(
            kind, lambda e: induce_stall(supervisor.coordinator,
                                         stall_seconds))
        return kind
    if kind == "retrace":
        supervisor.inject(kind, lambda e: induce_retrace())
        return kind
    raise ValueError(
        f"fault kind {kind!r} is not applicable in-process"
        + (" (driver kinds belong to the fleet driver — scripts/soak.py "
           "or resilience/distributed.py)" if kind in DRIVER_KINDS else ""))
