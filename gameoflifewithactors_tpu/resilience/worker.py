"""Soak worker: one supervised coordinator process, driven by a spec.

``python -m gameoflifewithactors_tpu.resilience.worker --spec spec.json``
builds the coordinator flavor the spec names (packed, dense, sparse,
LtL, or an ensemble of supervised members), arms the full obs stack
(StallWatchdog + FlightRecorder + MetricsServer with a /healthz
progress probe), and runs the spec's generations under a
:class:`~.supervisor.Supervisor`, applying the spec's FaultPlan slice
at chunk boundaries through the supervisor's detected-fault channel.

Driver protocol (scripts/soak.py):

- stdout line 1: ``METRICS_PORT <port>`` — the driver scrapes
  ``/healthz`` for live generation/restart counts and ``/metrics`` for
  the counters;
- the driver may SIGKILL this process at any moment (that *is* the
  ``kill`` fault kind) and relaunch with ``--resume``: the worker
  reloads the last atomic checkpoint and skips plan events already
  consumed before the checkpointed generation;
- on completion the worker writes ``final.npy`` (the exact grid — the
  driver diffs it against the unfaulted oracle's) and ``report.json``
  (supervisor stats + fault accounting), then exits 0. Exit 2 = the
  supervisor gave up (circuit open / unexplained retrace); exit 1 =
  spec or harness error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import List, Optional

import numpy as np

# flavor -> (rule, backend) — the mixed fleet the soak exercises; the
# ensemble flavor runs `ensemble_size` supervised members sequentially
# in one process (member m seeds from rng_seed + m)
FLAVORS = {
    "packed": ("B3/S23", "packed"),
    "dense": ("B3/S23", "dense"),
    "sparse": ("B3/S23", "sparse"),
    "ltl": ("majority", "dense"),
    "ensemble": ("B3/S23", "packed"),
}


def _checkpoint_path(workdir: Path, member: int) -> Path:
    return workdir / f"checkpoint-m{member}.npz"


def _build_coordinator(spec: dict, member: int, resume: bool):
    """(coordinator, resumed_generation) for one ensemble member."""
    from ..coordinator import GridCoordinator
    from ..utils import checkpoint as ckpt_lib

    rule, backend = FLAVORS[spec["flavor"]]
    ckpt = _checkpoint_path(Path(spec["workdir"]), member)
    if resume and ckpt.exists():
        engine = ckpt_lib.load_engine(ckpt, backend=backend)
        return GridCoordinator.from_engine(engine), engine.generation
    coordinator = GridCoordinator(
        tuple(spec["shape"]), rule,
        random_fill=spec.get("random_fill", 0.33),
        rng_seed=int(spec.get("rng_seed", 0)) + member,
        backend=backend)
    return coordinator, 0


def _run_member(spec: dict, member: int, resume: bool,
                health: dict, health_lock: threading.Lock) -> dict:
    """One supervised member run; returns its report entry."""
    from ..obs import spans as obs_spans
    from ..resilience import faultplan as plan_lib
    from ..resilience.supervisor import RestartPolicy, Supervisor

    coordinator, resumed_gen = _build_coordinator(spec, member, resume)
    deadline = float(spec.get("watchdog_deadline", 6.0))
    stall_seconds = float(spec.get("stall_seconds", deadline * 1.5))
    # plan events target the worker, and the plan exercises member 0 of
    # an ensemble (members 1.. are the fault-free control group); events
    # already consumed before the checkpointed generation stay consumed
    events = [plan_lib.FaultEvent.from_dict(e)
              for e in spec.get("events", [])] if member == 0 else []
    events = [e for e in events
              if e.kind not in plan_lib.DRIVER_KINDS
              and e.at_gen >= resumed_gen]
    applied: List[dict] = []

    supervisor = Supervisor(
        coordinator,
        checkpoint_path=str(_checkpoint_path(Path(spec["workdir"]), member)),
        checkpoint_every=int(spec.get("checkpoint_every", 40)),
        validators=(),
        policy=RestartPolicy(
            max_restarts=int(spec.get("max_restarts", 8)),
            backoff_initial_seconds=0.02, backoff_max_seconds=0.5),
    )

    # the driver paces chunks so its kill events can land mid-run — a
    # CPU Life grid would otherwise finish between two healthz polls
    chunk_sleep = float(spec.get("chunk_sleep_seconds", 0.0))

    def before_chunk(gen: int) -> None:
        due = [e for e in events if e.at_gen <= gen]
        for ev in due:
            events.remove(ev)
            kind = plan_lib.apply_fault(supervisor, ev,
                                        stall_seconds=stall_seconds)
            applied.append({"kind": kind, "scheduled": ev.kind,
                            "at_gen": ev.at_gen, "applied_at_gen": gen})
        with health_lock:
            health["generation"] = gen
            health["member"] = member
        if chunk_sleep > 0:
            time.sleep(chunk_sleep)

    supervisor.before_chunk = before_chunk
    with health_lock:
        health["supervisor"] = supervisor
    target = int(spec["generations"])
    # ambient trace (GOLTPU_TRACE from the soak driver) makes this span
    # a child of the driver's on the merged fleet timeline
    with obs_spans.span("soak.member", member=member,
                        flavor=spec["flavor"], target_gens=target):
        stats = supervisor.run(max(0, target - coordinator.generation))
    return {
        "member": member,
        "resumed_generation": resumed_gen,
        "final_generation": coordinator.generation,
        "population": coordinator.population(),
        "faults_applied": applied,
        "supervisor": stats,
    }


def run_spec(spec: dict, *, resume: bool = False,
             announce=print) -> int:
    """The worker body; returns the process exit code."""
    from ..obs import exporter as obs_exporter
    from ..obs import flight as obs_flight
    from ..obs import watchdog as obs_watchdog
    from ..resilience.supervisor import CircuitOpenError

    workdir = Path(spec["workdir"])
    workdir.mkdir(parents=True, exist_ok=True)
    deadline = float(spec.get("watchdog_deadline", 6.0))

    health: dict = {"generation": 0, "member": 0, "done": False}
    health_lock = threading.Lock()

    def health_info() -> dict:
        with health_lock:
            sup = health.get("supervisor")
            out = {"generation": health["generation"],
                   "member": health["member"], "done": health["done"]}
        if sup is not None:
            out.update(sup.stats())
        return out

    wd = obs_watchdog.arm(obs_watchdog.StallWatchdog(deadline))
    # install() with the watchdog BEFORE arm(): arm's own install() is a
    # no-op on an installed recorder, and installing without the
    # watchdog would silently drop the dump-on-stall chain
    fr = obs_flight.FlightRecorder(str(workdir / "flight.jsonl"))
    fr.install(watchdog=wd)
    obs_flight.arm(fr)
    # a driver SIGTERM is external: chain a tape note IN FRONT of the
    # recorder's dump-then-die handler (chain_signal_handler — never raw
    # signal.signal, which would silently drop the dump hook; the serve
    # loop follows the same rule). No lock in the note: the handler runs
    # on the main thread and must not wait on health_lock mid-signal.
    import signal as signal_lib

    unchain = obs_flight.chain_signal_handler(
        signal_lib.SIGTERM,
        lambda signum, frame: fr.note(
            "worker_sigterm", {"member": health.get("member", 0)}))
    server = obs_exporter.serve_metrics(
        int(spec.get("metrics_port", 0)),
        host=spec.get("metrics_host", "127.0.0.1"),
        health_info=health_info)
    announce(f"METRICS_PORT {server.port}", flush=True)

    members = (int(spec.get("ensemble_size", 2))
               if spec["flavor"] == "ensemble" else 1)
    report: dict = {"name": spec.get("name", "worker"),
                    "flavor": spec["flavor"], "resume": resume,
                    "pid": os.getpid(), "ok": False, "members": []}
    code = 0
    try:
        grids = []
        for m in range(members):
            entry = _run_member(spec, m, resume, health, health_lock)
            report["members"].append(entry)
            grids.append(_final_grid(spec, m))
        final = grids[0] if members == 1 else np.stack(grids)
        np.save(workdir / "final.npy", final)
        report["ok"] = True
    except (CircuitOpenError, AssertionError) as exc:
        report["error"] = f"{type(exc).__name__}: {exc}"
        code = 2
    finally:
        with health_lock:
            health["done"] = True
        report["stalls_detected"] = len(wd.events_since(0))
        report["flight_dumps"] = fr.dumps
        report["last_dump_reason"] = fr.last_dump_reason
        tmp = workdir / f"report.json.tmp{os.getpid()}"
        tmp.write_text(json.dumps(report, indent=2))
        os.replace(tmp, workdir / "report.json")
        # always leave a tape (after the report so report["flight_dumps"]
        # still counts only in-run dumps): the driver's merged timeline
        # needs spans from clean workers too
        fr.dump(f"end of run (exit code {code})")
        server.stop()
        unchain()
        obs_flight.disarm()
        obs_watchdog.disarm()
    return code


def _final_grid(spec: dict, member: int) -> np.ndarray:
    """Reload the member's final state from its own last checkpoint —
    the grid the driver diffs is the one that survived the atomic-save
    discipline, which is exactly the recovery contract under test."""
    from ..utils import checkpoint as ckpt_lib

    grid, _meta = ckpt_lib.load_grid(
        _checkpoint_path(Path(spec["workdir"]), member))
    return grid


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="soak worker (one supervised coordinator process)")
    parser.add_argument("--spec", required=True,
                        help="path to the worker spec JSON")
    parser.add_argument("--resume", action="store_true",
                        help="resume from the last checkpoint in workdir")
    args = parser.parse_args(argv)
    spec = json.loads(Path(args.spec).read_text())
    if spec.get("flavor") not in FLAVORS:
        sys.stderr.write(f"unknown flavor {spec.get('flavor')!r} "
                         f"(known: {sorted(FLAVORS)})\n")
        return 1
    return run_spec(spec, resume=args.resume)


if __name__ == "__main__":
    sys.exit(main())
