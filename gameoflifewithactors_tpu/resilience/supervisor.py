"""Supervisor: restart-with-rollback over a GridCoordinator.

The reference framework's one real claim is supervision — the Akka.NET
coordinator keeps the simulation alive when something under it
misbehaves. Its restart semantics, though, silently re-initialize the
failed actor's state [RECON]. This supervisor keeps the *policy* shape
(detect, restart with backoff, give up after too many) but makes the
restart honest: state comes back from the last validated checkpoint and
the lost generations are replayed, so a recovered run is bit-identical
to one that never faulted — the property the soak harness asserts
end-to-end.

The loop runs in checkpoint-sized chunks. After each chunk the
supervisor decides *clean or faulted*, in a fixed order:

1. an exception escaped ``tick`` (engine errors surface at sync time);
2. the armed StallWatchdog flagged the tick (``events_since``);
3. a fault was injected through :meth:`Supervisor.inject` since the
   last boundary — the "detected failure" channel the fault plan uses;
4. a state validator (``utils/fault.py`` validators) rejected the grid.

Clean chunks checkpoint (atomically — utils/checkpoint.py) and reset
the failure streak; faulted chunks restore the last checkpoint, sleep a
capped exponential backoff, and retry, until ``max_restarts``
consecutive failures open the circuit breaker. Checkpoints are only
ever written after a clean verdict, so every restore point is valid by
construction.

Retrace faults are the exception to rollback: an induced recompile
corrupts no state, so it is *attributed* — the supervisor's
RetraceSentinel (armed after warmup) must have seen the miss, both
sentinels are reset, and the run continues. Any miss still unexplained
when :meth:`run` finishes raises ``RetraceError``: that is the
no-post-warm-retrace invariant with teeth.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence

from ..analysis import sanitizers as _sanitizers
from ..coordinator import GridCoordinator
from ..obs import flight as obs_flight
from ..obs import spans as obs_spans
from ..obs import watchdog as obs_watchdog
from ..obs.registry import REGISTRY
from ..utils import checkpoint as ckpt_lib
from ..utils.fault import Validator


class CircuitOpenError(RuntimeError):
    """Too many consecutive failed restarts — the supervisor gave up."""


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """How hard to try before declaring the run dead.

    ``max_restarts`` counts *consecutive* failures: any clean chunk
    resets the streak (the Akka "maxNrOfRetries within a window" knob,
    with the window measured in progress instead of wall time —
    deterministic under replay). Backoff is capped exponential:
    ``min(initial * factor**n, max)`` seconds before restart ``n``."""

    max_restarts: int = 5
    backoff_initial_seconds: float = 0.05
    backoff_max_seconds: float = 2.0
    backoff_factor: float = 2.0

    def backoff(self, consecutive_failures: int) -> float:
        n = max(0, consecutive_failures - 1)
        return min(self.backoff_initial_seconds * self.backoff_factor ** n,
                   self.backoff_max_seconds)


class Supervisor:
    """``Supervisor(coordinator, checkpoint_path=...).run(n)``.

    ``sleep_fn`` is injectable so tests assert the backoff schedule
    without paying it. ``validators`` are consulted on every chunk
    boundary; ``on_restart`` (if given) is called with
    ``(cause, restored_generation, attempt)`` after each restore."""

    def __init__(
        self,
        coordinator: GridCoordinator,
        *,
        checkpoint_path: str,
        checkpoint_every: int = 100,
        validators: Sequence[Validator] = (),
        policy: Optional[RestartPolicy] = None,
        sleep_fn: Callable[[float], None] = time.sleep,
        on_restart: Optional[Callable[[str, int, int], None]] = None,
        before_chunk: Optional[Callable[[int], None]] = None,
    ):
        if checkpoint_every <= 0:
            raise ValueError(
                f"checkpoint_every must be positive, got {checkpoint_every}")
        self.coordinator = coordinator
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = int(checkpoint_every)
        self.validators = list(validators)
        self.policy = policy or RestartPolicy()
        self._sleep = sleep_fn
        self._on_restart = on_restart
        # public on purpose: the worker builds its fault applier around
        # the constructed supervisor, then hangs it here
        self.before_chunk = before_chunk
        # injected-fault channel + stats are read from other threads (the
        # metrics server's health_info hook, the driver's scrapes), so all
        # mutable shared state lives under one lock — the GOL004 rule,
        # applied outside obs/ because the hazard is the same
        self._lock = threading.Lock()
        self._pending_fault: Optional[str] = None
        self._restarts = 0
        self._restarts_by_cause: dict = {}
        self._checkpoints = 0
        self._retraces_attributed = 0
        self._stalls_detected = 0
        self._validator_trips = 0
        self._checkpoint_fallbacks = 0
        self._circuit_open = False
        self._sentinel = _sanitizers.RetraceSentinel(
            context="supervised run (post-warm)")

    # -- the detected-failure channel ----------------------------------------

    def inject(self, kind: str, fn: Callable) -> None:
        """Apply a fault ``fn(engine)`` now and mark it pending, so the
        next chunk boundary treats the state as failed and restores —
        the *detected* half of the fault model (an exception or
        validator trip is the undetected half; both end in the same
        rollback). ``retrace`` faults are attributed on the spot instead:
        no state was harmed, but the sentinel must have seen the miss."""
        with self._lock:
            if kind != "retrace":
                self._pending_fault = kind
        obs_flight.note_event("supervisor_inject",
                              {"fault": kind,
                               "at_gen": self.coordinator.generation})
        fn(self.coordinator.engine)
        if kind == "retrace":
            self._attribute_retrace()

    def _attribute_retrace(self) -> None:
        if not self._sentinel.misses():
            raise AssertionError(
                "induced retrace produced no cache_miss — the injection "
                "is broken, not the sentinel")
        self._reset_sentinels()
        REGISTRY.counter("supervisor_faults_detected_total",
                         "faults the supervisor detected, by cause"
                         ).inc(cause="retrace")
        obs_flight.note_event("retrace_attributed",
                              {"at_gen": self.coordinator.generation})
        with self._lock:
            self._retraces_attributed += 1

    def _reset_sentinels(self) -> None:
        """Forget taped compile misses on both the supervisor's sentinel
        and the engine's own (GOLTPU_SANITIZE warm-engine sentinel):
        after an attributed retrace or a restore (whose set_grid path may
        legitimately compile pack/device_put helpers on first use), taped
        misses are explained — leaving them would fail every subsequent
        step forever."""
        self._sentinel.reset()
        eng_sentinel = getattr(self.coordinator.engine,
                               "_retrace_sentinel", None)
        if eng_sentinel is not None:
            eng_sentinel.reset()

    # -- observability --------------------------------------------------------

    @property
    def generation(self) -> int:
        return self.coordinator.generation

    def stats(self) -> dict:
        """A snapshot for /healthz, reports, and tests."""
        with self._lock:
            return {
                "generation": self.coordinator.generation,
                "restarts": self._restarts,
                "restarts_by_cause": dict(self._restarts_by_cause),
                "checkpoints": self._checkpoints,
                "retraces_attributed": self._retraces_attributed,
                "stalls_detected": self._stalls_detected,
                "validator_trips": self._validator_trips,
                "checkpoint_fallbacks": self._checkpoint_fallbacks,
                "circuit_open": self._circuit_open,
            }

    # -- the supervised loop ---------------------------------------------------

    def run(self, generations: int) -> dict:
        """Advance ``generations`` generations under supervision; returns
        :meth:`stats`. Raises :class:`CircuitOpenError` after
        ``policy.max_restarts`` consecutive failed chunks, and
        ``RetraceError`` if any post-warm compile miss is left
        unattributed at the end."""
        target = self.coordinator.generation + generations
        # gen-0 restore point: the first chunk must have somewhere to
        # roll back to
        self._save_checkpoint()
        consecutive = 0
        warmed = False
        while self.coordinator.generation < target:
            if self.before_chunk is not None:
                self.before_chunk(self.coordinator.generation)
            chunk = min(self.checkpoint_every,
                        target - self.coordinator.generation)
            cause = self._run_chunk(chunk)
            if cause is None:
                self._save_checkpoint()
                consecutive = 0
                if not warmed:
                    # warmup compiles are legit; from here on a real
                    # compile must be an attributed injection
                    warmed = True
                    self._sentinel.arm()
                continue
            consecutive += 1
            self._restart(cause, consecutive)
        self._sentinel.disarm()
        self._sentinel.check()  # unattributed post-warm retrace -> raise
        return self.stats()

    def _run_chunk(self, chunk: int) -> Optional[str]:
        """One chunk; returns None when clean, else the failure cause."""
        wd = obs_watchdog.active_watchdog()
        wd_mark = len(wd.events) if wd is not None else 0
        exc: Optional[BaseException] = None
        try:
            with obs_spans.span("supervisor.chunk", generations=chunk,
                                start_gen=self.coordinator.generation):
                self.coordinator.tick(chunk)
        except Exception as e:  # noqa: BLE001 — the whole point is retry
            exc = e
        with self._lock:
            pending, self._pending_fault = self._pending_fault, None
        stalls = wd.events_since(wd_mark) if wd is not None else []
        if stalls:
            with self._lock:
                self._stalls_detected += len(stalls)
        if exc is not None:
            if pending is not None:
                # the injected fault is what blew up the tick (a
                # corrupted sparse map, a poisoned buffer): one fault,
                # one restart, attributed to the injection
                return f"fault:{pending}"
            return "exception"
        if stalls:
            return "stall" if pending != "stall" else "fault:stall"
        if pending is not None:
            return f"fault:{pending}"
        for validator in self.validators:
            if not validator(self.coordinator.engine):
                REGISTRY.counter(
                    "validator_trips_total",
                    "state-validator rejections (guard + supervisor)"
                ).inc(where="supervisor")
                obs_flight.note_event(
                    "validator_trip",
                    {"where": "supervisor",
                     "at_gen": self.coordinator.generation})
                with self._lock:
                    self._validator_trips += 1
                return "validator"
        return None

    def _save_checkpoint(self) -> None:
        # keep the outgoing checkpoint reachable as <path>.prev: if the
        # new file later turns out truncated/corrupt (bitrot, torn
        # write outside our atomic-replace discipline), restore falls
        # back to it instead of crashing the run
        ckpt_lib.rotate_previous(self.checkpoint_path)
        ckpt_lib.save(self.coordinator.engine, self.checkpoint_path)
        REGISTRY.counter("supervisor_checkpoints_total",
                         "clean-chunk checkpoints written").inc()
        with self._lock:
            self._checkpoints += 1
        REGISTRY.gauge("supervisor_generation",
                       "last checkpointed generation"
                       ).set(self.coordinator.generation)

    def _load_restore_point(self):
        """The last checkpoint, or — when it turns out corrupt or
        missing — the ``.prev`` generation :meth:`_save_checkpoint`
        rotated aside. A corrupt checkpoint is a *detected durability
        fault*, not a crash: the fallback is counted, taped, and the
        (older) restore point's replay still converges bit-exactly."""
        try:
            return ckpt_lib.load_grid(self.checkpoint_path)
        except (ckpt_lib.CheckpointCorruptError, FileNotFoundError) as exc:
            prev = str(self.checkpoint_path) + ".prev"
            REGISTRY.counter(
                "supervisor_checkpoint_fallbacks_total",
                "restores that fell back to the .prev checkpoint "
                "because the newest one was corrupt/missing").inc()
            obs_flight.note_event(
                "checkpoint_fallback",
                {"path": str(self.checkpoint_path),
                 "error": f"{type(exc).__name__}: {exc}"})
            with self._lock:
                self._checkpoint_fallbacks += 1
            return ckpt_lib.load_grid(prev)

    def _restart(self, cause: str, consecutive: int) -> None:
        REGISTRY.counter("supervisor_faults_detected_total",
                         "faults the supervisor detected, by cause"
                         ).inc(cause=cause)
        if consecutive > self.policy.max_restarts:
            with self._lock:
                self._circuit_open = True
            REGISTRY.gauge("supervisor_circuit_open",
                           "1 when the restart circuit breaker tripped"
                           ).set(1)
            obs_flight.note_event("supervisor_circuit_open",
                                  {"cause": cause,
                                   "failures": consecutive})
            raise CircuitOpenError(
                f"{consecutive} consecutive failed chunks (last cause: "
                f"{cause}) exceeded max_restarts="
                f"{self.policy.max_restarts}; circuit open at generation "
                f"{self.coordinator.generation}")
        delay = self.policy.backoff(consecutive)
        if delay > 0:
            self._sleep(delay)
        with obs_spans.span("supervisor.restart", cause=cause,
                            attempt=consecutive):
            grid, meta = self._load_restore_point()
            self.coordinator.engine.set_grid(grid,
                                             generation=meta["generation"])
        self._reset_sentinels()
        REGISTRY.counter("supervisor_restarts_total",
                         "checkpoint-restore restarts, by cause"
                         ).inc(cause=cause)
        obs_flight.note_event(
            "supervisor_restart",
            {"cause": cause, "to_gen": self.coordinator.generation,
             "attempt": consecutive, "backoff_seconds": delay})
        with self._lock:
            self._restarts += 1
            self._restarts_by_cause[cause] = \
                self._restarts_by_cause.get(cause, 0) + 1
        if self._on_restart is not None:
            self._on_restart(cause, self.coordinator.generation,
                             consecutive)
        # renderers and other subscribers see the rolled-back state
        # instead of a silent generation jump
        self.coordinator.notify_now()
