"""Elastic multi-host runtime: preemption-tolerant sharded runs.

PR 11's :class:`~.supervisor.Supervisor` restarts a *coordinator inside
one process*; this module extends the same policy shape — detect,
restore from a verified checkpoint, replay, give up after too many —
across an entire ``parallel/multihost.py`` fleet, where the failure
mode is harsher: one SIGKILLed process wedges every survivor inside a
collective forever, and a single-file checkpoint cannot even be
written (no host holds the grid). Three pieces, mirroring ISSUE 14:

**Failure detection, bounded.** Every worker beats a per-process
heartbeat file on the shared rundir (the control plane — a filesystem,
deliberately not a collective: it must keep working exactly when the
collectives don't) and each compute chunk is bracketed by
deadline-bounded :func:`barrier` rendezvous. The two detectors are
complementary: a *dead* peer (SIGKILL) stops beating and every
survivor's :class:`PeerMonitor` notices within ``heartbeat_deadline``
— even while the survivor's main thread is wedged inside a collective,
because the monitor is a daemon thread and XLA releases the GIL — and
the survivor exits ``EXIT_PEER_LOST`` instead of hanging; a *stalled*
peer (alive, beating, not progressing) never reaches the barrier and
trips ``barrier_deadline`` instead. Heartbeat staleness is judged by
*local* clock elapsed since the file's mtime last changed — no
cross-host clock comparison, no wall-clock reads.

**Sharded, verified checkpoints.** After every chunk each process
writes only its own shards plus per-shard CRC32s
(``utils/checkpoint.py`` sharded v2), a barrier proves all shards
durable, and process 0 publishes the manifest with one atomic rename —
the only commit point. Restore verifies every checksum and falls back
generation by generation past torn or corrupt ones
(``load_latest_verified``), so a byte-flipped shard costs one
generation of replay, never a wrong grid.

**Elastic recovery.** On peer loss the survivors exit in bounded time;
the :class:`ElasticFleet` driver tears the epoch down, rebuilds the
mesh over the remaining (or replacement) process set, re-places the
restored grid with ``put_global_grid``, and replays from the last
verified generation. On SIGTERM preemption a worker finishes its
chunk, checkpoints, flags its peers through the control plane, and
exits with the distinct ``EXIT_PREEMPTED`` status; the fleet re-forms
without it. Replay is pure function re-execution, so the final grid is
bit-identical to an unfaulted single-device run — the invariant
``scripts/chaos_multihost.py`` proves end to end.

Heartbeat misses, barrier timeouts, checkpoint fallbacks, and fleet
recovery latency all land in the ``obs`` registry and the per-worker
flight recorder, so a chaos run leaves the same post-mortem trail a
production incident would.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..obs import flight as obs_flight
from ..obs import spans as obs_spans
from ..obs.registry import REGISTRY
from ..utils import checkpoint as ckpt_lib
from ..utils.checkpoint import CheckpointCorruptError

# distinct exit statuses — the driver's classification signal
EXIT_DONE = 0
EXIT_PREEMPTED = 17  # got SIGTERM, finished chunk, checkpointed, left
EXIT_PEER_LOST = 18  # detected a dead/stalled/preempted peer; fleet must rebuild

TERMINAL_STATUSES = ("done", "preempted", "peer_lost", "error")


class PeerLostError(RuntimeError):
    """A peer failed to show up within the deadline."""

    def __init__(self, missing: Sequence[int], where: str,
                 deadline_seconds: float):
        self.missing = tuple(sorted(missing))
        self.where = where
        self.deadline_seconds = deadline_seconds
        super().__init__(
            f"peers {list(self.missing)} missing at {where!r} after "
            f"{deadline_seconds:.1f}s deadline")


# -- control-plane layout (everything under one shared rundir) ----------------

def _hb_path(rundir: Path, epoch: int, process_id: int) -> Path:
    return Path(rundir) / "hb" / f"e{epoch:03d}" / f"p{process_id:04d}.json"


def _status_path(rundir: Path, epoch: int, process_id: int) -> Path:
    return Path(rundir) / "status" / f"e{epoch:03d}-p{process_id:04d}.json"


def _preempt_flag(rundir: Path, epoch: int, process_id: int) -> Path:
    return Path(rundir) / "control" / f"e{epoch:03d}-preempt-p{process_id:04d}"


def _barrier_dir(rundir: Path, epoch: int, name: str) -> Path:
    return Path(rundir) / "barrier" / f"e{epoch:03d}-{name}"


def _write_json(path: Path, payload: dict) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(f"{path.name}.tmp{os.getpid()}")
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, path)


def _read_json(path: Path) -> Optional[dict]:
    try:
        return json.loads(path.read_text())
    except (FileNotFoundError, ValueError, OSError):
        return None


def write_status(rundir: Path, epoch: int, process_id: int, status: str,
                 generation: int, detail: Optional[str] = None) -> None:
    """Publish this worker's terminal verdict for the epoch (atomic)."""
    _write_json(_status_path(rundir, epoch, process_id), {
        "process_id": process_id, "epoch": epoch, "status": status,
        "generation": int(generation), "detail": detail,
        "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    })


def read_status(rundir: Path, epoch: int, process_id: int) -> Optional[dict]:
    return _read_json(_status_path(rundir, epoch, process_id))


def request_preempt(rundir: Path, epoch: int, process_id: int) -> None:
    """Mark ``process_id`` as preempting — visible to every peer at the
    next chunk boundary, so the whole fleet re-forms without waiting
    for a barrier timeout. Touched (not JSON) so it is safe from a
    signal handler."""
    flag = _preempt_flag(rundir, epoch, process_id)
    flag.parent.mkdir(parents=True, exist_ok=True)
    flag.touch()


def preempts_requested(rundir: Path, epoch: int,
                       num_processes: int) -> Set[int]:
    return {p for p in range(num_processes)
            if _preempt_flag(rundir, epoch, p).exists()}


def read_heartbeat(rundir: Path, epoch: int,
                   process_id: int) -> Optional[dict]:
    return _read_json(_hb_path(rundir, epoch, process_id))


class Heartbeat:
    """Daemon thread beating this process's liveness file.

    Each beat rewrites ``hb/e<epoch>/p<id>.json`` atomically; liveness
    is carried by the mtime *changing*, the payload (generation, beat
    sequence) is for the driver's progress view and post-mortems."""

    def __init__(self, rundir: Path, epoch: int, process_id: int,
                 interval_seconds: float = 0.25):
        self._path = _hb_path(rundir, epoch, process_id)
        self._process_id = process_id
        self._epoch = epoch
        self._interval = interval_seconds
        self._lock = threading.Lock()
        self._generation = 0
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def set_generation(self, generation: int) -> None:
        with self._lock:
            self._generation = int(generation)

    def beat(self) -> None:
        with self._lock:
            self._seq += 1
            payload = {"process_id": self._process_id,
                       "epoch": self._epoch, "pid": os.getpid(),
                       "generation": self._generation, "seq": self._seq}
        _write_json(self._path, payload)

    def start(self) -> "Heartbeat":
        self.beat()  # visible before the first interval elapses
        self._thread = threading.Thread(
            target=self._loop, name="elastic-heartbeat", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()


class PeerMonitor:
    """Daemon thread flagging peers whose heartbeat went stale.

    Staleness is ``perf_counter() - (local time the file's mtime last
    changed)`` — each process judges peers against its *own* monotonic
    clock, so clock skew between hosts cannot fake (or hide) a death.
    Fires ``on_peer_lost({peer: stale_seconds})`` at most once, from
    the monitor thread; workers use it to exit in bounded time even
    while the main thread is wedged inside a collective."""

    def __init__(self, rundir: Path, epoch: int, process_id: int,
                 num_processes: int, deadline_seconds: float,
                 on_peer_lost: Callable[[Dict[int, float]], None],
                 poll_seconds: Optional[float] = None):
        self._paths = {p: _hb_path(rundir, epoch, p)
                       for p in range(num_processes) if p != process_id}
        self._deadline = deadline_seconds
        self._on_peer_lost = on_peer_lost
        self._poll = poll_seconds or max(0.05, deadline_seconds / 10.0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PeerMonitor":
        self._thread = threading.Thread(
            target=self._loop, name="elastic-peer-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        last_change: Dict[int, Tuple[Optional[int], float]] = {
            p: (None, time.perf_counter()) for p in self._paths}
        while not self._stop.wait(self._poll):
            now = time.perf_counter()
            stale: Dict[int, float] = {}
            for p, path in self._paths.items():
                try:
                    mtime = os.stat(path).st_mtime_ns
                except OSError:
                    mtime = None
                prev_mtime, prev_t = last_change[p]
                if mtime is not None and mtime != prev_mtime:
                    last_change[p] = (mtime, now)
                elif now - prev_t > self._deadline:
                    stale[p] = now - prev_t
            if stale and not self._stop.is_set():
                self._stop.set()
                self._on_peer_lost(stale)
                return


def barrier(rundir: Path, epoch: int, name: str, process_id: int,
            num_processes: int, deadline_seconds: float,
            poll_seconds: float = 0.01) -> None:
    """Deadline-bounded rendezvous: touch our marker, wait for all
    ``num_processes`` markers. Raises :class:`PeerLostError` naming the
    absentees when the deadline passes — or immediately once a missing
    peer has published a *terminal* status for this epoch (it will
    never arrive; waiting out the deadline would only slow recovery).

    This is what keeps a stalled-but-alive peer from wedging the fleet:
    its heartbeat stays fresh, but it never reaches the barrier, and
    every healthy peer gives up after exactly ``deadline_seconds``."""
    d = _barrier_dir(rundir, epoch, name)
    d.mkdir(parents=True, exist_ok=True)
    (d / f"p{process_id:04d}").touch()
    t0 = time.perf_counter()
    while True:
        missing = [p for p in range(num_processes)
                   if not (d / f"p{p:04d}").exists()]
        if not missing:
            return
        for p in missing:
            st = read_status(rundir, epoch, p)
            if st is not None and st.get("status") in TERMINAL_STATUSES:
                raise PeerLostError(
                    [p], f"{name} (peer already terminal: "
                    f"{st.get('status')})", time.perf_counter() - t0)
        if time.perf_counter() - t0 > deadline_seconds:
            raise PeerLostError(missing, name, deadline_seconds)
        time.sleep(poll_seconds)


# -- the worker ----------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ElasticSpec:
    """One fleet's simulation + failure-model knobs (JSON-plain)."""

    shape: Tuple[int, int] = (96, 64)
    rule: str = "B3/S23"
    topology: str = "torus"
    target_gens: int = 120
    chunk: int = 20
    rng_seed: int = 0
    random_fill: float = 0.33
    devices_per_process: int = 1
    # preferred 2D tile decomposition (mesh rows, mesh cols) for the
    # ghost-zone pipeline; None = lock-step (n, 1) bands. A preferred
    # shape the surviving roster cannot host (device count, divisibility,
    # tile capacity) degrades deterministically on every controller —
    # parallel/multihost.global_mesh_for_grid is the one decision point.
    mesh_shape: Optional[Tuple[int, int]] = None
    # halo exchange once per k generations (width-k ghost zones); tiles
    # too small for the pipeline fall back to lock-step per-gen exchange
    gens_per_exchange: int = 1
    heartbeat_interval_seconds: float = 0.25
    heartbeat_deadline_seconds: float = 3.0
    barrier_deadline_seconds: float = 10.0
    chunk_sleep_seconds: float = 0.0
    ckpt_keep: int = 2

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["shape"] = list(self.shape)
        if self.mesh_shape is not None:
            d["mesh_shape"] = list(self.mesh_shape)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ElasticSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in d.items() if k in known}
        kwargs["shape"] = tuple(d.get("shape", cls.shape))
        if d.get("mesh_shape") is not None:
            kwargs["mesh_shape"] = tuple(d["mesh_shape"])
        return cls(**kwargs)


def initial_grid(spec: ElasticSpec):
    """The deterministic genesis grid — same seed, same grid, on every
    process and in the driver's oracle."""
    import numpy as np

    rng = np.random.default_rng(spec.rng_seed)
    return (rng.random(spec.shape) < spec.random_fill).astype(np.uint8)


def _die(code: int) -> None:
    """Terminal exit for a fleet worker: skip interpreter teardown
    entirely. Normal exit would run jax's atexit distributed-client
    shutdown, which can block on a coordinator that no longer exists —
    the exact hang this module exists to bound. Everything durable
    (status, checkpoint, flight dump) is already on disk by the time
    this is called."""
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def run_worker(rundir: "str | Path", spec: ElasticSpec, *, epoch: int,
               process_id: int, num_processes: int, port: int) -> int:
    """One elastic worker: join the fleet, resume from the last verified
    sharded checkpoint, run chunk/checkpoint/barrier rounds to
    ``target_gens``. Never returns through a wedged collective: every
    abnormal path funnels through :func:`_die` with a distinct exit
    status after publishing its verdict to the control plane."""
    import jax
    import numpy as np

    from ..models.generations import parse_any
    from ..ops import bitpack
    from ..ops.stencil import Topology
    from ..parallel import multihost, sharded

    rundir = Path(rundir)
    hbd = spec.heartbeat_deadline_seconds
    rule = parse_any(spec.rule)
    topology = Topology(spec.topology)

    multihost.initialize(f"localhost:{port}", num_processes, process_id,
                         initialization_timeout=120)
    # packed grid dims decide the tiling; every process computes the
    # same mesh from the same global roster + spec (the 2D re-tiling
    # after a shrink/replace epoch is THIS call, nothing stateful)
    grid_rows = spec.shape[0]
    grid_words = -(-spec.shape[1] // 32)  # ops/bitpack.py WORD
    kpe = max(1, int(spec.gens_per_exchange))
    if spec.mesh_shape is not None or kpe > 1:
        mesh = multihost.global_mesh_for_grid(
            (grid_rows, grid_words), spec.mesh_shape,
            gens_per_exchange=kpe)
    else:
        mesh = multihost.global_mesh((len(jax.devices()), 1))

    flight_dir = rundir / "flight"
    flight_dir.mkdir(parents=True, exist_ok=True)
    fr = obs_flight.FlightRecorder(
        str(flight_dir / f"e{epoch:03d}-p{process_id:04d}.jsonl"))
    fr.install(signals=False)  # SIGTERM means preempt here, not die
    obs_flight.arm(fr)
    # the driver passes GOLTPU_TRACE through the env (spans.py reads it
    # at import), so every span below nests under the fleet driver's
    # trace; nothing else to do here — but tape a breadcrumb so the
    # merged timeline shows when this worker joined
    fr.note("worker_start", {"process_id": process_id, "epoch": epoch,
                             "num_processes": num_processes})

    preempted = threading.Event()

    def _on_sigterm(signum, frame) -> None:
        # graceful preemption: flag it fleet-wide, finish the chunk,
        # checkpoint, exit with the distinct status — never die mid-step
        preempted.set()
        request_preempt(rundir, epoch, process_id)
        fr.note("preempt_requested", {"process_id": process_id})

    unchain = obs_flight.chain_signal_handler(
        signal.SIGTERM, _on_sigterm, propagate=False)

    # -- resume: newest generation that verifies clean ------------------------
    ckroot = rundir / "ckpt"
    gen = 0
    state_np = None
    skipped: List[Tuple[Path, str]] = []
    if ckpt_lib.list_generations(ckroot):
        try:
            state_np, meta, gen_dir, skipped = \
                ckpt_lib.load_latest_verified(ckroot)
            gen = int(meta["generation"])
        except CheckpointCorruptError as exc:
            # every generation refused: genesis replay is the honest
            # floor — deterministic, so still bit-exact, just slower
            obs_flight.note_event(
                "checkpoint_genesis_fallback", {"error": str(exc)})
            skipped = []
    for gen_dir_skipped, why in skipped:
        REGISTRY.counter(
            "elastic_checkpoint_fallbacks_total",
            "sharded-checkpoint generations refused at restore "
            "(corrupt/torn), causing fallback to an older one"
        ).inc()
        obs_flight.note_event(
            "checkpoint_generation_refused",
            {"dir": str(gen_dir_skipped), "why": why[:500]})
    if state_np is None:
        state_np = bitpack.pack_np(initial_grid(spec))
    state_np = np.asarray(state_np, dtype=np.uint32)
    state = multihost.put_global_grid(state_np, mesh)
    from ..parallel import mesh as mesh_lib
    nx = mesh.shape[mesh_lib.ROW_AXIS]
    ny = mesh.shape[mesh_lib.COL_AXIS]
    pergen = sharded.make_multi_step_packed(mesh, rule, topology)
    use_ghost = kpe > 1 and mesh_lib.ghost_fits(
        state_np.shape[0] // nx, state_np.shape[1] // ny, kpe)
    if use_ghost:
        # the ghost-zone pipeline is the compute core; n % k remainder
        # generations (shrunk final chunks, odd resume points) take the
        # per-gen runner so any chunk size stays bit-exact
        ghost = sharded.make_multi_step_packed_ghost(
            mesh, rule, topology, gens_per_exchange=kpe)

        def runner(s, n):
            blocks, rem = divmod(int(n), kpe)
            if blocks:
                s = ghost(s, blocks)
            if rem:
                s = pergen(s, rem)
            return s
    else:
        runner = pergen
    # durable restore record: the chaos driver (and a human post-mortem)
    # can see exactly which generations each worker refused and why —
    # and where this epoch re-placed the 2D tiles — even when the worker
    # goes on to finish cleanly (flight-recorder notes only reach disk
    # on a dump)
    _write_json(rundir / "restore" / f"e{epoch:03d}-p{process_id:04d}.json",
                {"resumed_generation": gen,
                 "mesh": [nx, ny],
                 "runner": "ghost" if use_ghost else "lockstep",
                 "gens_per_exchange": kpe if use_ghost else 1,
                 "skipped": [[str(d), why[:300]] for d, why in skipped]})

    hb = Heartbeat(rundir, epoch, process_id,
                   spec.heartbeat_interval_seconds)
    hb.set_generation(gen)
    hb.start()

    def _peer_lost_hard(stale: Dict[int, float]) -> None:
        # monitor-thread path: main thread may be wedged in a
        # collective whose peer is gone — record, dump, die bounded
        for peer, seconds in stale.items():
            REGISTRY.counter(
                "elastic_heartbeat_misses_total",
                "peers declared dead after a stale heartbeat"
            ).inc(peer=str(peer))
        obs_flight.note_event(
            "heartbeat_miss",
            {"stale": {str(k): round(v, 3) for k, v in stale.items()},
             "deadline_seconds": hbd, "at_gen": gen})
        write_status(rundir, epoch, process_id, "peer_lost", gen,
                     detail=f"heartbeat stale: {sorted(stale)}")
        fr.dump(f"peer lost (heartbeat): {sorted(stale)}")
        _die(EXIT_PEER_LOST)

    monitor = PeerMonitor(rundir, epoch, process_id, num_processes,
                          hbd, _peer_lost_hard)
    monitor.start()

    def _sync(name: str) -> None:
        barrier(rundir, epoch, name, process_id, num_processes,
                spec.barrier_deadline_seconds)

    try:
        while gen < spec.target_gens:
            _sync(f"c{gen:08d}-pre")
            k = min(spec.chunk, spec.target_gens - gen)
            with obs_spans.span("elastic.chunk", epoch=epoch,
                                process_id=process_id,
                                start_gen=gen, generations=k):
                state = runner(state, k)
                jax.block_until_ready(state)
            gen += k
            hb.set_generation(gen)
            # sharded checkpoint: shards → barrier → manifest → barrier
            gd = ckpt_lib.generation_dir(ckroot, gen)
            ckpt_lib.write_shards(
                gd, process_id, multihost.local_shards(state),
                global_shape=state.shape, dtype=np.uint32)
            _sync(f"c{gen:08d}-shards")
            if process_id == 0:
                ckpt_lib.commit_manifest(
                    gd, num_processes=num_processes,
                    meta={"rule": rule.notation,
                          "topology": topology.value,
                          "generation": gen,
                          "shape": list(spec.shape),
                          "layout": "packed32"})
                ckpt_lib.prune_sharded(ckroot, keep=spec.ckpt_keep)
            _sync(f"c{gen:08d}-commit")
            # preemption boundary: the checkpoint just committed is the
            # hand-off point for whoever leaves the fleet here
            requested = preempts_requested(rundir, epoch, num_processes)
            if preempted.is_set() or process_id in requested:
                monitor.stop()
                write_status(rundir, epoch, process_id, "preempted", gen)
                fr.dump(f"preempted at generation {gen}")
                _die(EXIT_PREEMPTED)
            if requested:
                monitor.stop()
                obs_flight.note_event(
                    "peer_preempted",
                    {"peers": sorted(requested), "at_gen": gen})
                write_status(rundir, epoch, process_id, "peer_lost", gen,
                             detail=f"peers preempted: {sorted(requested)}")
                fr.dump(f"peers preempted: {sorted(requested)}")
                _die(EXIT_PEER_LOST)
            if spec.chunk_sleep_seconds > 0:
                time.sleep(spec.chunk_sleep_seconds)
        # done: one allgather so process 0 can persist the full grid the
        # driver diffs against the single-device oracle
        gathered = multihost.gather_global(state)
        monitor.stop()
        if process_id == 0:
            final = bitpack.unpack_np(gathered)[:, :spec.shape[1]]
            tmp = rundir / f"final.npy.tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.save(f, final)
            os.replace(tmp, rundir / "final.npy")
            _write_json(rundir / "final.json",
                        {"generation": gen, "epoch": epoch,
                         "num_processes": num_processes})
        write_status(rundir, epoch, process_id, "done", gen)
        # clean exits leave a tape too: the fleet-wide merged timeline
        # needs every worker's spans, not just the ones that died
        fr.dump(f"done at generation {gen}")
        _die(EXIT_DONE)
    except PeerLostError as exc:
        monitor.stop()
        REGISTRY.counter(
            "elastic_barrier_timeouts_total",
            "barriers abandoned after the deadline (peer lost/stalled)"
        ).inc(where=exc.where.split(" ")[0])
        obs_flight.note_event(
            "peer_lost", {"missing": list(exc.missing),
                          "where": exc.where, "at_gen": gen})
        write_status(rundir, epoch, process_id, "peer_lost", gen,
                     detail=str(exc))
        fr.dump(f"peer lost (barrier): {exc}")
        _die(EXIT_PEER_LOST)
    except Exception as exc:  # noqa: BLE001 — verdict must reach the driver
        monitor.stop()
        write_status(rundir, epoch, process_id, "error", gen,
                     detail=f"{type(exc).__name__}: {exc}")
        fr.dump(f"worker error: {type(exc).__name__}: {exc}")
        raise
    finally:
        hb.stop()
        unchain()
        obs_flight.disarm()
    return 1  # unreachable; _die never returns


# -- the fleet driver ----------------------------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class _Fired:
    """One driver-side fault actually executed."""

    kind: str
    worker: int
    at_gen: int
    fired_at_gen: int
    epoch: int
    t: float  # driver perf_counter at firing
    detail: Optional[str] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("t")
        return d


class ElasticFleet:
    """Localhost fleet driver: launch N workers, execute driver-side
    faults (``process_kill`` / ``process_preempt`` /
    ``checkpoint_corrupt`` FaultEvents), and rebuild the fleet over the
    remaining or replacement process set until the run completes.

    The driver is deliberately dumb about simulation state: workers own
    resume (``load_latest_verified``), the driver only owns the process
    set. Preempted workers leave the roster permanently (the fleet
    shrinks — "remaining"); killed workers are replaced by fresh
    processes when ``replace_killed`` (the default — "replacement"),
    exercising both elastic paths. Recovery latency (fault fired →
    first heartbeat of the rebuilt epoch) lands in this process's
    ``obs`` registry and the per-epoch report."""

    def __init__(self, rundir: "str | Path", spec: ElasticSpec, *,
                 num_processes: int, env: Optional[dict] = None,
                 max_epochs: int = 8, replace_killed: bool = True,
                 startup_deadline_seconds: float = 180.0,
                 poll_seconds: float = 0.05):
        if num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {num_processes}")
        h = spec.shape[0]
        wp = -(-spec.shape[1] // 32)  # packed words (ops/bitpack.py)
        ndev = num_processes * spec.devices_per_process
        if spec.mesh_shape is not None:
            mx, my = spec.mesh_shape
            if mx * my != ndev:
                raise ValueError(
                    f"mesh_shape {spec.mesh_shape} needs {mx * my} devices, "
                    f"fleet has {num_processes} processes x "
                    f"{spec.devices_per_process} devices")
            if h % mx or wp % my:
                raise ValueError(
                    f"packed grid ({h}, {wp}) words not divisible by "
                    f"mesh_shape {spec.mesh_shape}")
            if spec.gens_per_exchange > 1:
                from ..parallel.mesh import ghost_fits
                if not ghost_fits(h // mx, wp // my,
                                  spec.gens_per_exchange):
                    raise ValueError(
                        f"gens_per_exchange={spec.gens_per_exchange} does "
                        f"not fit ({h // mx}, {wp // my})-word tiles of "
                        f"mesh_shape {spec.mesh_shape}; ghost zones need "
                        "2k rows and 2*ceil(k/32) words per tile")
        elif h % ndev:
            raise ValueError(
                f"grid rows {h} not divisible over {num_processes} "
                f"processes x {spec.devices_per_process} devices")
        self.rundir = Path(rundir)
        self.spec = spec
        self.num_processes = num_processes
        self.max_epochs = max_epochs
        self.replace_killed = replace_killed
        self.startup_deadline = startup_deadline_seconds
        self.poll_seconds = poll_seconds
        self._env = dict(env if env is not None else os.environ)
        self._env.setdefault("JAX_PLATFORMS", "cpu")
        self._env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count="
            f"{spec.devices_per_process}")
        # workers run `python -m gameoflifewithactors_tpu...` from an
        # arbitrary cwd: make the package importable regardless
        repo_root = str(Path(__file__).resolve().parents[2])
        parts = [p for p in
                 self._env.get("PYTHONPATH", "").split(os.pathsep) if p]
        if repo_root not in parts:
            self._env["PYTHONPATH"] = os.pathsep.join([repo_root] + parts)
        # fleet-wide trace: the driver mints (or inherits) the trace id
        # and hands workers its own span id as their parent via the env,
        # so worker spans nest under the driver on the merged timeline
        ambient = obs_spans.current_trace()
        self.trace = obs_spans.TraceContext(
            trace_id=(ambient.trace_id if ambient is not None
                      else obs_spans.new_trace_id()),
            span_id=obs_spans.new_span_id())
        obs_spans.set_process_context(self.trace)
        self._env.update(self.trace.child_env())
        self.rundir.mkdir(parents=True, exist_ok=True)
        _write_json(self.rundir / "spec.json", spec.to_dict())

    # -- one epoch -------------------------------------------------------------

    def _spawn(self, epoch: int, n: int, port: int) -> List[subprocess.Popen]:
        logdir = self.rundir / "logs"
        logdir.mkdir(parents=True, exist_ok=True)
        procs = []
        for p in range(n):
            log = open(logdir / f"e{epoch:03d}-p{p:04d}.log", "ab")
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "gameoflifewithactors_tpu.resilience.distributed",
                 "--rundir", str(self.rundir),
                 "--spec", str(self.rundir / "spec.json"),
                 "--epoch", str(epoch), "--process-id", str(p),
                 "--num-processes", str(n), "--port", str(port)],
                env=self._env, stdout=log, stderr=log))
            log.close()  # the child holds its own descriptor
        return procs

    def _fire(self, ev, procs: List[subprocess.Popen], epoch: int,
              fired_gen: int) -> _Fired:
        from ..utils import fault as fault_lib

        rec = _Fired(kind=ev.kind, worker=ev.worker, at_gen=ev.at_gen,
                     fired_at_gen=fired_gen, epoch=epoch,
                     t=time.perf_counter())
        target = procs[ev.worker]
        if ev.kind == "process_kill":
            os.kill(target.pid, signal.SIGKILL)
        elif ev.kind == "process_preempt":
            os.kill(target.pid, signal.SIGTERM)
        elif ev.kind == "checkpoint_corrupt":
            # SIGKILL first, corrupt after the target is confirmed dead:
            # with a peer gone no barrier can pass, so no *newer* clean
            # generation can commit and the corrupted one is guaranteed
            # to be the newest at rebuild — the restore MUST refuse it
            # and fall back a generation
            os.kill(target.pid, signal.SIGKILL)
            target.wait(timeout=30)
            committed = [d for _g, d in
                         ckpt_lib.list_generations(self.rundir / "ckpt")
                         if (d / ckpt_lib.MANIFEST_NAME).exists()]
            if committed:
                # corrupt process 0's shard: present in every roster size
                victim = committed[-1] / "shard-p0000.npz"
                fault_lib.corrupt_checkpoint_file(
                    victim, seed=int(ev.params.get("seed", 0)))
                rec.detail = f"corrupted {victim}"
            else:
                rec.detail = "no committed generation yet; kill only"
        else:
            raise ValueError(f"not a driver fault kind: {ev.kind!r}")
        REGISTRY.counter("elastic_driver_faults_total",
                         "driver-side faults executed, by kind"
                         ).inc(kind=ev.kind)
        # instant event on the driver tape: kill/preempt/corrupt must be
        # visible on the merged fleet timeline, not just in the report
        obs_flight.note_event(
            "driver_fault",
            {"fault": ev.kind, "worker": ev.worker, "epoch": epoch,
             "fired_at_gen": fired_gen, "detail": rec.detail})
        return rec

    def _epoch_deadline(self) -> float:
        spec = self.spec
        chunks = max(1, -(-spec.target_gens // spec.chunk))
        return (self.startup_deadline
                + chunks * (spec.chunk_sleep_seconds + 5.0)
                + spec.barrier_deadline_seconds
                + spec.heartbeat_deadline_seconds + 60.0)

    def run(self, events: Sequence = ()) -> dict:
        """Drive the fleet to ``target_gens`` through every scheduled
        fault; returns the report (never raises on worker failure —
        ``report["ok"]`` carries the verdict)."""
        pending = sorted(events, key=lambda e: e.at_gen)
        fired: List[_Fired] = []
        epochs: List[dict] = []
        n = self.num_processes
        ok = False
        for epoch in range(self.max_epochs):
            with obs_spans.span("elastic.epoch", epoch=epoch,
                                num_processes=n):
                info = self._run_epoch(epoch, n, pending, fired)
            epochs.append(info)
            if info["completed"]:
                ok = True
                break
            n = self._next_roster(n, info)
            if n < 1:
                info["note"] = "roster empty; giving up"
                break
        final_meta = _read_json(self.rundir / "final.json") or {}
        report = {
            "trace_id": self.trace.trace_id,
            "spec": self.spec.to_dict(),
            "num_processes_initial": self.num_processes,
            "epochs": epochs,
            "faults_fired": [f.to_dict() for f in fired],
            "faults_unfired": [getattr(e, "to_dict", lambda: e)()
                               for e in pending],
            "final": final_meta,
            "final_grid": (str(self.rundir / "final.npy")
                           if (self.rundir / "final.npy").exists() else None),
            "ok": bool(ok and final_meta
                       and final_meta.get("generation")
                       == self.spec.target_gens),
            "registry": {
                k: v for k, v in REGISTRY.snapshot().items()
                if k.startswith("elastic_") or k.startswith("faults_")},
        }
        _write_json(self.rundir / "chaos_report.json", report)
        return report

    def _run_epoch(self, epoch: int, n: int, pending: list,
                   fired: List[_Fired]) -> dict:
        port = _free_port()
        t0 = time.perf_counter()
        procs = self._spawn(epoch, n, port)
        info: dict = {"epoch": epoch, "num_processes": n, "port": port,
                      "fired": [], "wedged": False, "completed": False}
        # recovery latency: fault fired (previous epoch) → first
        # heartbeat of this rebuilt epoch
        prev_fault_t = fired[-1].t if fired else None
        seen_heartbeat = False
        deadline = t0 + self._epoch_deadline()
        fired_this_epoch: List[_Fired] = []
        escalate_at: Dict[int, float] = {}
        while True:
            now = time.perf_counter()
            rcs = [p.poll() for p in procs]
            if all(rc is not None for rc in rcs):
                break
            if now > deadline:
                # the elastic promise failed — nothing may hang forever,
                # including the driver's patience
                info["wedged"] = True
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait()
                break
            if not seen_heartbeat:
                hb = read_heartbeat(self.rundir, epoch, 0)
                if hb is not None:
                    seen_heartbeat = True
                    info["startup_seconds"] = round(now - t0, 3)
                    if prev_fault_t is not None:
                        recovery = now - prev_fault_t
                        info["recovery_seconds"] = round(recovery, 3)
                        REGISTRY.histogram(
                            "elastic_recovery_seconds",
                            "fault fired -> rebuilt fleet heartbeating"
                        ).observe(recovery)
            # escalate preempts whose grace window ran out
            for idx, t_esc in list(escalate_at.items()):
                if now > t_esc and procs[idx].poll() is None:
                    procs[idx].kill()
                    escalate_at.pop(idx)
            # fire at most one fault per poll, only on a healthy fleet
            if (pending and not fired_this_epoch
                    and all(rc is None for rc in rcs)):
                ev = pending[0]
                if ev.worker < n:
                    hb = read_heartbeat(self.rundir, epoch, ev.worker)
                    g = (hb or {}).get("generation", 0)
                    if hb is not None and g >= ev.at_gen:
                        pending.pop(0)
                        rec = self._fire(ev, procs, epoch, g)
                        fired.append(rec)
                        fired_this_epoch.append(rec)
                        info["fired"].append(rec.to_dict())
                        if ev.kind == "process_preempt":
                            grace = float(ev.params.get("grace_seconds", 10.0))
                            escalate_at[ev.worker] = rec.t + grace
                else:
                    pending.pop(0)  # roster shrank past the target
            time.sleep(self.poll_seconds)
        rcs = [p.poll() for p in procs]
        info["exit_codes"] = rcs
        info["statuses"] = [read_status(self.rundir, epoch, p)
                            for p in range(n)]
        info["wall_seconds"] = round(time.perf_counter() - t0, 3)
        if fired_this_epoch:
            # detection latency: fault fired → every worker exited (all
            # survivors self-detected and left; nothing hung)
            info["detection_seconds"] = round(
                time.perf_counter() - fired_this_epoch[0].t, 3)
        info["completed"] = all(rc == EXIT_DONE for rc in rcs)
        if not info["completed"]:
            REGISTRY.counter(
                "elastic_fleet_rebuilds_total",
                "fleet teardown+relaunch cycles, by trigger").inc(
                    cause=(fired_this_epoch[0].kind if fired_this_epoch
                           else "peer_lost"))
        return info

    def _next_roster(self, n: int, info: dict) -> int:
        preempted = sum(1 for rc in info["exit_codes"]
                        if rc == EXIT_PREEMPTED)
        killed_like = sum(1 for rc in info["exit_codes"]
                          if rc not in (EXIT_DONE, EXIT_PREEMPTED,
                                        EXIT_PEER_LOST))
        n_next = n - preempted
        if not self.replace_killed:
            n_next -= killed_like
        # the mesh over the shrunk roster must still tile the grid the
        # same way the workers will choose it (multihost.
        # global_mesh_for_grid); if it can't, keep the old size
        # (replacements instead)
        while n_next >= 1 and not self._roster_tiles(n_next):
            n_next += 1
        return min(n_next, n) if n_next >= 1 else n

    def _roster_tiles(self, n_procs: int) -> bool:
        """Whether ``n_procs`` processes can host SOME valid mesh for the
        spec's packed grid — mirroring the workers' deterministic mesh
        choice, 2D factorizations included."""
        spec = self.spec
        h = spec.shape[0]
        ndev = n_procs * spec.devices_per_process
        if spec.mesh_shape is None and spec.gens_per_exchange <= 1:
            return h % ndev == 0  # legacy lock-step (n, 1) bands
        from ..parallel.mesh import best_mesh_shape
        wp = -(-spec.shape[1] // 32)
        if (spec.gens_per_exchange > 1
                and best_mesh_shape(ndev, h, wp,
                                    gens_per_exchange=spec.gens_per_exchange)):
            return True
        if best_mesh_shape(ndev, h, wp, gens_per_exchange=0):
            return True
        return h % ndev == 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="elastic multi-host worker (one fleet process)")
    parser.add_argument("--rundir", required=True)
    parser.add_argument("--spec", required=True)
    parser.add_argument("--epoch", type=int, required=True)
    parser.add_argument("--process-id", type=int, required=True)
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument("--port", type=int, required=True)
    args = parser.parse_args(argv)

    # the tunneled-TPU plugin ignores the JAX_PLATFORMS env var; pin the
    # config before the first backend query (same as tests/conftest.py)
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        jax.config.update("jax_platforms", platforms)

    spec = ElasticSpec.from_dict(json.loads(Path(args.spec).read_text()))
    return run_worker(args.rundir, spec, epoch=args.epoch,
                      process_id=args.process_id,
                      num_processes=args.num_processes, port=args.port)


if __name__ == "__main__":
    sys.exit(main())
