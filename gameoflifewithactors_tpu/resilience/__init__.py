"""Resilience layer: supervision, deterministic fault plans, soak workers.

The reference framework's claim is that supervision keeps the grid
alive through misbehaving actors; this package is that claim made
checkable (ROADMAP item 5). :class:`Supervisor` wraps a
GridCoordinator with checkpoint-restore restart semantics (bit-exact,
unlike Akka's state-losing restart), :class:`FaultPlan` makes fault
campaigns seeded and replayable, and ``worker.py`` is the subprocess
body the fleet driver (``scripts/soak.py``) launches, kills, and
resumes.

``distributed.py`` extends the same contract across processes (ISSUE
14): an elastic fleet of multi-controller JAX workers with heartbeat +
barrier failure detection, sharded verified checkpoints, and
teardown-rebuild-replay recovery — driven by ``scripts/chaos_multihost.py``.
It is deliberately not imported here: workers re-exec this package and
must not pay for (or wedge on) anything they don't use.
"""

from .faultplan import (ALL_KINDS, DRIVER_KINDS, FaultEvent, FaultPlan,
                        apply_fault, induce_retrace, induce_stall)
from .supervisor import CircuitOpenError, RestartPolicy, Supervisor

__all__ = [
    "ALL_KINDS",
    "DRIVER_KINDS",
    "CircuitOpenError",
    "FaultEvent",
    "FaultPlan",
    "RestartPolicy",
    "Supervisor",
    "apply_fault",
    "induce_retrace",
    "induce_stall",
]
