"""gameoflifewithactors_tpu — a TPU-native cellular-automata framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of
rikace/GameOfLifeWithActors (actor-per-cell Conway's Game of Life on
Akka.NET): the per-cell actor mailbox update becomes a fused bit-packed
stencil kernel, neighbor actor Tell messages become ``lax.ppermute`` halo
exchange over a 2D device mesh, and the GridCoordinator/tick/renderer
boundary survives as a host-side façade (see SURVEY.md for the capability
contract and the provenance note — the reference mount was empty at survey
time, so component names come from BASELINE.json's north_star).
"""

from .models.rules import (  # noqa: F401
    CONWAY,
    DAY_AND_NIGHT,
    HIGHLIFE,
    RULE_REGISTRY,
    Rule,
    parse_rule,
)
from .models import seeds  # noqa: F401
from .models.generations import (  # noqa: F401
    BRIANS_BRAIN,
    GENERATIONS_REGISTRY,
    GenRule,
    STAR_WARS,
    parse_any,
    parse_generations,
)
from .models.ltl import BOSCO, LTL_REGISTRY, LtLRule, parse_ltl  # noqa: F401
from .models.elementary import (  # noqa: F401
    RULE_30,
    RULE_90,
    RULE_110,
    ElementaryRule,
    parse_elementary,
)
from .ops.elementary import (  # noqa: F401
    evolve_spacetime,
    multi_step_elementary,
    step_elementary,
)
from .ops.generations import multi_step_generations, step_generations  # noqa: F401
from .ops.ltl import multi_step_ltl, step_ltl  # noqa: F401
from .ops.stencil import Topology, step, multi_step  # noqa: F401
from .ops.bitpack import pack, unpack, population  # noqa: F401
from .ops.packed import step_packed, multi_step_packed  # noqa: F401
from .engine import Engine  # noqa: F401
from .coordinator import GridCoordinator, RenderFrame  # noqa: F401
from .scheduler import TickScheduler  # noqa: F401
from .config import SimulationConfig  # noqa: F401
from .aot import (  # noqa: F401  (warm start: cache + AOT registry + warmup)
    EngineSpec,
    ensure_persistent_cache,
    warmup_specs,
)

__version__ = "0.1.0"
