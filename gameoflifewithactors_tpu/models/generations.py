"""Generations rule family — multi-state cellular automata.

The reference implements exactly one rule (Conway B3/S23) hardcoded in its
CellActor [SURVEY.md §3]; this framework treats the rule as a value. The
Generations family extends life-like B/S rules with refractory states:
state 1 is *alive* (the only state neighbors count), a live cell that
fails survival starts *dying* through states 2..C-1 (it occupies space but
no longer excites neighbors), and only from state C-1 does it return to
dead 0. C=2 degenerates to plain life-like, so C >= 3 here.

Notation: "B2/S/C3" (Brian's Brain) — also accepted with G instead of C,
and as Golly's "survive/born/states" digit form ("2/3/3" ≡ B3/S2/C3 is
what Golly writes in RLE headers; counts are single digits 0..8, so the
form is unambiguous — only the trailing states field is multi-digit).
"""

from __future__ import annotations

import dataclasses
import re
from typing import FrozenSet

from .rules import Rule, parse_rule


@dataclasses.dataclass(frozen=True)
class GenRule:
    """An outer-totalistic Generations rule: born/survive count sets + the
    number of cell states C (0 = dead, 1 = alive, 2..C-1 = dying)."""

    born: FrozenSet[int]
    survive: FrozenSet[int]
    states: int

    def __post_init__(self):
        object.__setattr__(self, "born", frozenset(self.born))
        object.__setattr__(self, "survive", frozenset(self.survive))
        if not all(0 <= n <= 8 for n in self.born | self.survive):
            raise ValueError(f"neighbor counts must be 0..8: {self}")
        if not 3 <= self.states <= 256:
            raise ValueError(
                f"Generations needs 3..256 states (C=2 is plain life-like; "
                f"use Rule), got {self.states}"
            )

    @property
    def birth_mask(self) -> int:
        m = 0
        for n in self.born:
            m |= 1 << n
        return m

    @property
    def survive_mask(self) -> int:
        m = 0
        for n in self.survive:
            m |= 1 << n
        return m

    @property
    def notation(self) -> str:
        return (
            "B" + "".join(str(n) for n in sorted(self.born))
            + "/S" + "".join(str(n) for n in sorted(self.survive))
            + f"/C{self.states}"
        )

    def __str__(self) -> str:
        return self.notation


_GEN_RE = re.compile(
    r"^B(?P<b>[0-8]*)/S(?P<s>[0-8]*)/[CG](?P<c>\d+)$", re.IGNORECASE
)
# Golly's RLE-header form: survive/born/states ("2/3/3" = Brian's Brain)
_GOLLY_GEN_RE = re.compile(r"^(?P<s>[0-8]*)/(?P<b>[0-8]*)/(?P<c>\d+)$")

GENERATIONS_REGISTRY = {}


def _mk(b: str, s: str, c: int, name: str) -> GenRule:
    r = GenRule(frozenset(int(x) for x in b), frozenset(int(x) for x in s), c)
    GENERATIONS_REGISTRY[name] = r
    return r


BRIANS_BRAIN = _mk("2", "", 3, "brain")
STAR_WARS = _mk("2", "345", 4, "starwars")
FROGS = _mk("34", "12", 3, "frogs")
BELZHAB = _mk("23", "23", 8, "belzhab")


def parse_generations(spec: "str | GenRule") -> GenRule:
    """Parse "B2/S/C3"-style notation or a registered name."""
    if isinstance(spec, GenRule):
        return spec
    key = spec.strip().lower().replace(" ", "").replace("'", "")
    if key in GENERATIONS_REGISTRY:
        return GENERATIONS_REGISTRY[key]
    # match the space-stripped key, so 'B2 / S / C3' parses
    m = _GEN_RE.match(key) or _GOLLY_GEN_RE.match(key)
    if not m:
        raise ValueError(
            f"not a Generations rule: {spec!r} (want 'B…/S…/C<n>', Golly's "
            f"'survive/born/states', or one of {sorted(GENERATIONS_REGISTRY)})"
        )
    return GenRule(
        frozenset(int(x) for x in m.group("b")),
        frozenset(int(x) for x in m.group("s")),
        int(m.group("c")),
    )


def parse_any(spec):
    """Life-like, Generations, or Larger-than-Life, decided by the *shape*
    of the spec — a string matching a family's form dispatches to that
    family's parser so validation errors (e.g. a bad state count) surface
    verbatim instead of degrading to 'unrecognized rule'."""
    from .elementary import _ELEM_RE, ElementaryRule, parse_elementary
    from .ltl import _LTL_RE, LTL_REGISTRY, LtLRule, parse_ltl

    if isinstance(spec, (Rule, GenRule, LtLRule, ElementaryRule)):
        return spec
    key = spec.strip().lower().replace(" ", "").replace("'", "")
    if (key in GENERATIONS_REGISTRY or _GEN_RE.match(key)
            or _GOLLY_GEN_RE.match(key)):
        return parse_generations(spec)
    if key in LTL_REGISTRY or _LTL_RE.match(key):
        return parse_ltl(spec)
    if _ELEM_RE.match(key):
        return parse_elementary(spec)
    return parse_rule(spec)
