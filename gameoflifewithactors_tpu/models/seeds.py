"""Seed patterns and grid initialisation.

The reference seeds its 64×64 actor grid with a glider (BASELINE.json config
#1); this module generalises that into a pattern library: classic still
lifes/oscillators/spaceships as plaintext art, a standard RLE decoder, a
Bernoulli random fill, and placement helpers. All constructors are host-side
(NumPy) — seeding is init-time work; only the stepped grid lives on device.
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_ALIVE_CHARS = frozenset("XxOo*1")


def from_plaintext(text: str) -> np.ndarray:
    """Parse ASCII art ('X'/'O'/'*' alive, '.'/space dead) into uint8 (h, w)."""
    lines = [ln.rstrip() for ln in text.strip("\n").splitlines()]
    width = max(len(ln) for ln in lines)
    grid = np.zeros((len(lines), width), dtype=np.uint8)
    for r, ln in enumerate(lines):
        for c, ch in enumerate(ln):
            if ch in _ALIVE_CHARS:
                grid[r, c] = 1
    return grid


_RLE_HEADER = re.compile(r"^\s*x\s*=\s*(\d+)\s*,\s*y\s*=\s*(\d+)", re.IGNORECASE)


_RLE_RULE = re.compile(r"rule\s*=\s*([^\n]+)", re.IGNORECASE)


def _header_states(text: str) -> int:
    """Cell-state count from an RLE header's ``rule =`` clause (2 when the
    rule is binary, absent, or unparseable — the legacy decoder then
    applies)."""
    m = _RLE_RULE.search(text)
    if not m:
        return 2
    try:
        from .generations import parse_any

        return getattr(parse_any(m.group(1).strip()), "states", 2)
    except Exception:
        return 2


def from_rle(text: str, states: int | None = None) -> np.ndarray:
    """Decode Game-of-Life RLE (``b``=dead, ``o``=alive, ``$``=EOL,
    ``!``=end, ``#``-comment lines, optional ``x=,y=,rule=`` header).

    Golly's EXTENDED multi-state encoding is applied when the header's
    rule (or an explicit ``states=``) has more than 2 states: ``.`` is
    state 0, ``A``..``X`` are 1..24, and a ``p``..``y`` prefix adds
    24·k (``pA``=25 … ``yO``=255) — the format Golly writes for
    Generations and multi-state Larger-than-Life patterns. Binary RLE
    keeps the legacy case-insensitive ``b``/``o`` reading."""
    if states is None:
        states = _header_states(text)
    multistate = states > 2
    width = height = None
    body_parts = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        m = _RLE_HEADER.match(ln)
        if m:
            width, height = int(m.group(1)), int(m.group(2))
            continue
        body_parts.append(ln)
    body = "".join(body_parts)
    rows: list[list[int]] = [[]]
    run = ""
    prefix = 0                      # 24·k from a pending p..y prefix char
    for ch in body:
        if ch.isdigit():
            run += ch
            continue
        if multistate and "p" <= ch <= "y":
            if prefix:
                raise ValueError(f"double state prefix before {ch!r}")
            prefix = 24 * (ord(ch) - ord("o"))
            continue
        n = int(run) if run else 1
        run = ""
        if prefix and not ("A" <= ch <= "X"):
            raise ValueError(f"state prefix must be followed by A..X, got {ch!r}")
        if multistate and "A" <= ch <= "X":
            rows[-1].extend([prefix + ord(ch) - ord("A") + 1] * n)
            prefix = 0
        elif ch in ("b", "B") or (multistate and ch == "."):
            rows[-1].extend([0] * n)
        elif ch in ("o", "O"):
            rows[-1].extend([1] * n)
        elif ch == "$":
            for _ in range(n - 1):
                rows.append([])
            rows.append([])
        elif ch == "!":
            break
        elif ch.isspace():
            continue
        else:
            raise ValueError(f"unexpected RLE char {ch!r}")
    w = width if width is not None else max((len(r) for r in rows), default=0)
    h = height if height is not None else len(rows)
    grid = np.zeros((h, w), dtype=np.uint8)
    for r, row in enumerate(rows[:h]):
        grid[r, : len(row)] = row[:w]
    return grid


def _rle_token(state: int) -> str:
    """Golly cell token: ``.`` / ``A``..``X`` / prefixed ``pA``..``yO``."""
    if state == 0:
        return "."
    if state > 255:
        raise ValueError(f"RLE encodes states 0..255, got {state}")
    k, rem = divmod(state - 1, 24)
    return (chr(ord("o") + k) if k else "") + chr(ord("A") + rem)


def to_rle(grid: np.ndarray, rule: str = "B3/S23") -> str:
    """Encode a uint8 grid as RLE (round-trips with from_rle). Grids with
    cells beyond 1 use Golly's extended multi-state tokens; pass the
    matching multi-state ``rule`` string so decoders (including ours)
    pick the extended reading from the header."""
    h, w = grid.shape
    multistate = int(grid.max(initial=0)) > 1
    out = [f"x = {w}, y = {h}, rule = {rule}"]
    lines = []
    for r in range(h):
        runs = []
        row = grid[r]
        c = 0
        while c < w:
            v = int(row[c])
            n = 1
            while c + n < w and row[c + n] == v:
                n += 1
            if multistate:
                tok = _rle_token(v)
            else:
                tok = "o" if v else "b"
            runs.append((n, tok))
            c += n
        if runs and runs[-1][1] in ("b", "."):
            runs.pop()  # trailing dead cells are implicit
        lines.append("".join((str(n) if n > 1 else "") + t for n, t in runs))
    out.append("$".join(lines) + "!")
    return "\n".join(out)


# --- classic patterns (plaintext keeps them reviewable) ---------------------

PATTERNS: Dict[str, np.ndarray] = {}


def _register(name: str, art: str) -> None:
    PATTERNS[name] = from_plaintext(art)


_register("block", "XX\nXX")
_register("blinker", "XXX")
_register("toad", ".XXX\nXXX.")
_register("beacon", "XX..\nXX..\n..XX\n..XX")
_register("glider", ".X.\n..X\nXXX")
_register("lwss", ".X..X\nX....\nX...X\nXXXX.")
_register("r_pentomino", ".XX\nXX.\n.X.")
_register("acorn", ".X.....\n...X...\nXX..XXX")
_register("diehard", "......X.\nXX......\n.X...XXX")       # vanishes at gen 130
_register("pentadecathlon", "..X....X..\nXX.XXXX.XX\n..X....X..")  # period 15
_register("pulsar", """
..XXX...XXX..
.............
X....X.X....X
X....X.X....X
X....X.X....X
..XXX...XXX..
.............
..XXX...XXX..
X....X.X....X
X....X.X....X
X....X.X....X
.............
..XXX...XXX..
""")
_register("gosper_gun", """
........................X...........
......................X.X...........
............XX......XX............XX
...........X...X....XX............XX
XX........X.....X...XX..............
XX........X...X.XX....X.X...........
..........X.....X.......X...........
...........X...X....................
............XX......................
""")


def pattern(name: str) -> np.ndarray:
    try:
        return PATTERNS[name].copy()
    except KeyError:
        raise KeyError(f"unknown pattern {name!r}; known: {sorted(PATTERNS)}") from None


def empty(shape: Tuple[int, int]) -> np.ndarray:
    return np.zeros(shape, dtype=np.uint8)


def place(grid: np.ndarray, pat: "np.ndarray | str", top: int, left: int) -> np.ndarray:
    """Stamp a pattern onto a grid at (top, left); returns the grid."""
    if isinstance(pat, str):
        pat = pattern(pat)
    ph, pw = pat.shape
    if top < 0 or left < 0 or top + ph > grid.shape[0] or left + pw > grid.shape[1]:
        raise ValueError(
            f"pattern {pat.shape} at ({top},{left}) exceeds grid {grid.shape}"
        )
    grid[top : top + ph, left : left + pw] |= pat
    return grid


def seeded(shape: Tuple[int, int], pat: "np.ndarray | str", top: int = 0, left: int = 0) -> np.ndarray:
    """A fresh grid of ``shape`` with ``pat`` stamped at (top, left)."""
    return place(empty(shape), pat, top, left)


def seeded_packed(shape: Tuple[int, int], pat: "np.ndarray | str",
                  top: int = 0, left_word: int = 0) -> np.ndarray:
    """A fresh bit-packed (H, W/32 uint32) grid with ``pat`` stamped at row
    ``top``, word column ``left_word`` — O(pattern) host work regardless of
    grid size, so a 65536² field (512 MB packed; 4.3 GB dense) seeds without
    ever materialising the dense grid. Placement is word-aligned: cell
    column = 32·left_word."""
    from ..ops import bitpack

    if isinstance(pat, str):
        pat = pattern(pat)
    h, w = shape
    if w % bitpack.WORD:
        raise ValueError(f"width {w} not a multiple of {bitpack.WORD}")
    ph, pw = pat.shape
    patch = np.zeros((ph, -(-pw // bitpack.WORD) * bitpack.WORD), dtype=np.uint8)
    patch[:, :pw] = pat
    pp = bitpack.pack_np(patch)
    words = w // bitpack.WORD
    if top < 0 or left_word < 0 or top + ph > h or left_word + pp.shape[1] > words:
        raise ValueError(
            f"pattern {pat.shape} at (row {top}, word {left_word}) exceeds "
            f"packed grid ({h}, {words})")
    grid = np.zeros((h, words), dtype=np.uint32)
    grid[top:top + ph, left_word:left_word + pp.shape[1]] = pp
    return grid


def bernoulli(key: jax.Array, shape: Tuple[int, int], p: float = 0.5) -> jax.Array:
    """Random fill: each cell alive with probability ``p`` (device-side)."""
    return jax.random.bernoulli(key, p, shape).astype(jnp.uint8)
