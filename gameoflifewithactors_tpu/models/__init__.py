"""models subpackage."""
