"""Elementary (Wolfram) 1D cellular automata: rules 0..255.

A fourth rule family beyond the reference's Conway demo (SURVEY.md §1
"parametrized rules" row; CAX — PAPERS.md — treats 1D CA as a core family,
so a framework claiming CA breadth should too). The rule number's bit k
gives the next state for the 3-cell pattern k = (left << 2) | (center << 1)
| right — rule 110 is Turing-complete, rule 90 is the Sierpinski XOR, rule
30 is Wolfram's chaos/PRNG rule.

Stepping lives in ops/elementary.py (bit-packed SWAR over 32-cell words);
this module is the rule algebra only.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

_ELEM_RE = re.compile(r"^(?:w|rule)(?P<n>\d{1,3})$")


@dataclass(frozen=True)
class ElementaryRule:
    """One of the 256 elementary rules, by Wolfram number."""

    number: int

    def __post_init__(self):
        if not 0 <= self.number <= 255:
            raise ValueError(
                f"elementary rule number must be 0..255, got {self.number}")

    @property
    def notation(self) -> str:
        return f"W{self.number}"

    def __str__(self) -> str:
        return self.notation

    def pattern_bit(self, left: int, center: int, right: int) -> int:
        """Next state for a (left, center, right) neighborhood."""
        return (self.number >> ((left << 2) | (center << 1) | right)) & 1


RULE_110 = ElementaryRule(110)
RULE_90 = ElementaryRule(90)
RULE_30 = ElementaryRule(30)


def parse_elementary(spec: "str | ElementaryRule") -> ElementaryRule:
    """Parse "W110" / "rule110" (case-insensitive) or pass through."""
    if isinstance(spec, ElementaryRule):
        return spec
    m = _ELEM_RE.match(str(spec).strip().lower().replace(" ", ""))
    if not m:
        raise ValueError(
            f"not an elementary rule spec: {spec!r} (want 'W<0..255>' or "
            f"'rule<0..255>', e.g. 'W110')")
    return ElementaryRule(int(m.group("n")))
