"""Life-like cellular-automaton rules.

The reference (rikace/GameOfLifeWithActors) hard-codes Conway's B3/S23 inside
each ``CellActor``'s message handler (SURVEY.md §3 — the reference mount was
empty at survey time, so no file:line citation is possible; component names
come from BASELINE.json's north_star). Here the rule is a first-class value: a
parsed birth/survive set pair that compiles into branch-free bitmask lookups
usable both by the dense stencil and the bit-packed SWAR kernel.

A rule is written in standard B/S notation, e.g. ``"B3/S23"``: a dead cell
with a live-neighbor count in B is born; a live cell with a count in S
survives; everything else dies. Counts range over 0..8 (Moore neighborhood).
"""

from __future__ import annotations

import dataclasses
import re
from typing import FrozenSet

_VALID_COUNTS = frozenset(range(9))


@dataclasses.dataclass(frozen=True)
class Rule:
    """A life-like CA rule (outer-totalistic, 2-state, Moore neighborhood)."""

    born: FrozenSet[int]
    survive: FrozenSet[int]
    name: str = ""

    def __post_init__(self):
        if not self.born <= _VALID_COUNTS or not self.survive <= _VALID_COUNTS:
            raise ValueError(
                f"neighbor counts must be in 0..8, got B{sorted(self.born)}"
                f"/S{sorted(self.survive)}"
            )

    @property
    def birth_mask(self) -> int:
        """9-bit mask: bit n set iff a dead cell with n live neighbors is born."""
        m = 0
        for n in self.born:
            m |= 1 << n
        return m

    @property
    def survive_mask(self) -> int:
        """9-bit mask: bit n set iff a live cell with n live neighbors survives."""
        m = 0
        for n in self.survive:
            m |= 1 << n
        return m

    @property
    def notation(self) -> str:
        return (
            "B" + "".join(str(n) for n in sorted(self.born))
            + "/S" + "".join(str(n) for n in sorted(self.survive))
        )

    def next_state(self, alive: int, count: int) -> int:
        """Scalar oracle: pure-Python next state (used by tests)."""
        if alive:
            return 1 if count in self.survive else 0
        return 1 if count in self.born else 0

    def __str__(self) -> str:
        return self.name or self.notation


_BS_RE = re.compile(r"^B(?P<b>[0-8]*)/?S(?P<s>[0-8]*)$", re.IGNORECASE)
_SB_RE = re.compile(r"^(?P<s>[0-8]*)/(?P<b>[0-8]*)$")  # classic "23/3" S/B form


def parse_rule(spec: "str | Rule") -> Rule:
    """Parse ``"B3/S23"`` (or classic ``"23/3"`` S/B form, or a named rule).

    Accepts a :class:`Rule` unchanged, a registry name like ``"highlife"``, or
    B/S notation in either order with case-insensitive letters.
    """
    if isinstance(spec, Rule):
        return spec
    text = spec.strip()
    key = text.lower().replace(" ", "").replace("&", "and").replace("'", "")
    if key in RULE_REGISTRY:
        return RULE_REGISTRY[key]
    compact = text.replace(" ", "")
    m = _BS_RE.match(compact)
    if m is None:
        # classic S/B form is typo-prone ('23/' for '23/3'), so unlike the
        # explicit lettered form it must name both digit groups
        m = _SB_RE.match(compact)
        if m is not None and not (m.group("b") and m.group("s")):
            m = None
    if m is not None and not (m.group("b") or m.group("s")):
        m = None  # bare 'B/S' or '/': nothing specified
    if m is None:
        raise ValueError(
            f"unrecognized rule {spec!r}; expected B/S notation like 'B3/S23' "
            f"or one of {sorted(RULE_REGISTRY)}"
        )
    born = frozenset(int(c) for c in m.group("b"))
    survive = frozenset(int(c) for c in m.group("s"))
    name = ""
    for r in RULE_REGISTRY.values():
        if r.born == born and r.survive == survive:
            name = r.name
            break
    return Rule(born=born, survive=survive, name=name)


def _mk(b: str, s: str, name: str) -> Rule:
    return Rule(frozenset(int(c) for c in b), frozenset(int(c) for c in s), name)


# Well-known life-like rules. Conway is the reference's only rule [META];
# the rest cover BASELINE.json config #4 (HighLife, Day & Night) and beyond.
CONWAY = _mk("3", "23", "Conway's Life")
HIGHLIFE = _mk("36", "23", "HighLife")
DAY_AND_NIGHT = _mk("3678", "34678", "Day & Night")
SEEDS = _mk("2", "", "Seeds")
LIFE_WITHOUT_DEATH = _mk("3", "012345678", "Life without Death")
REPLICATOR = _mk("1357", "1357", "Replicator")
DIAMOEBA = _mk("35678", "5678", "Diamoeba")
MORLEY = _mk("368", "245", "Morley")
ANNEAL = _mk("4678", "35678", "Anneal")
TWO_BY_TWO = _mk("36", "125", "2x2")
MAZE = _mk("3", "12345", "Maze")
CORAL = _mk("3", "45678", "Coral")

RULE_REGISTRY = {
    "conway": CONWAY,
    "conwayslife": CONWAY,
    "life": CONWAY,
    "b3/s23": CONWAY,
    "highlife": HIGHLIFE,
    "dayandnight": DAY_AND_NIGHT,
    "seeds": SEEDS,
    "lifewithoutdeath": LIFE_WITHOUT_DEATH,
    "replicator": REPLICATOR,
    "diamoeba": DIAMOEBA,
    "morley": MORLEY,
    "anneal": ANNEAL,
    "2x2": TWO_BY_TWO,
    "maze": MAZE,
    "coral": CORAL,
}
