"""Larger-than-Life rule family — radius-r Moore neighborhoods.

Life-like rules look at 8 neighbors; Larger-than-Life (Evans) counts live
cells in a (2r+1)² box and births/survives on *intervals*. The box count
is a separable pair of log-tree sliding-window sums in int32 on the VPU
(ops/ltl.py — a conv-based MXU design was measured ~50x slower on chip
and replaced), alongside the bitwise SWAR path the 3×3 rules use.

Notation (Golly's LtL form): ``R5,C0,M1,S34..58,B34..45`` —
radius R, states C (C0/C2 = binary; C>=3 adds Generations-style dying
states: alive cells failing survival decay through 2..C-1 instead of
dying outright, and dying cells neither excite neighbors nor take
births), M1 counts the center cell itself in the survival window (M0
excludes it), S/B are inclusive count intervals. Named rules: "bosco"
(the classic), "bugs", "majority" (radius-4 majority vote).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Tuple

MAX_RADIUS = 7  # policy cap (int32 tree is exact at any radius): keeps
# halo-exchange depth and window shapes modest on sharded meshes


@dataclasses.dataclass(frozen=True)
class LtLRule:
    """Larger-than-Life: interval birth/survival over a radius-r
    neighborhood — Moore box ("M", Golly's NM) or von Neumann diamond
    ("N", Golly's NN, |dx|+|dy| <= r). ``states == 2`` is the classic
    binary family; ``states >= 3`` adds Generations-style decay (state 1
    alive, 2..states-1 dying and non-exciting)."""

    radius: int
    born: Tuple[int, int]       # inclusive [lo, hi]
    survive: Tuple[int, int]    # inclusive [lo, hi]
    middle: bool = True         # M1: a live cell counts itself in its window
    neighborhood: str = "M"     # "M" box | "N" von Neumann diamond
    states: int = 2             # 2 = binary; >= 3 = dying states 2..C-1

    def __post_init__(self):
        if not 1 <= self.radius <= MAX_RADIUS:
            raise ValueError(
                f"radius must be 1..{MAX_RADIUS} (bf16-exact window sums), "
                f"got {self.radius}"
            )
        if self.neighborhood not in ("M", "N"):
            raise ValueError(
                f"neighborhood must be 'M' (Moore box) or 'N' (von Neumann "
                f"diamond), got {self.neighborhood!r}")
        if not 2 <= self.states <= 256:
            raise ValueError(
                f"states must be 2..256 (uint8 cells), got {self.states}")
        full = self.window_size
        for name, (lo, hi) in (("born", self.born), ("survive", self.survive)):
            if not (0 <= lo <= hi <= full):
                raise ValueError(
                    f"{name} interval {lo}..{hi} outside 0..{full} "
                    f"for radius {self.radius} neighborhood {self.neighborhood}"
                )

    @property
    def window_size(self) -> int:
        """Cells in the neighborhood window (center included)."""
        r = self.radius
        return (2 * r + 1) ** 2 if self.neighborhood == "M" else (
            2 * r * (r + 1) + 1)

    @property
    def notation(self) -> str:
        return (
            f"R{self.radius},C{0 if self.states == 2 else self.states},"
            f"M{int(self.middle)},"
            f"S{self.survive[0]}..{self.survive[1]},"
            f"B{self.born[0]}..{self.born[1]}"
            + ("" if self.neighborhood == "M" else ",NN")
        )

    def __str__(self) -> str:
        return self.notation


_LTL_RE = re.compile(
    r"^R(?P<r>\d+),C(?P<c>\d+),M(?P<m>[01]),"
    r"S(?P<s1>\d+)\.\.(?P<s2>\d+),B(?P<b1>\d+)\.\.(?P<b2>\d+)"
    r"(?:,N(?P<n>[MN]))?$",
    re.IGNORECASE,
)

LTL_REGISTRY = {}


def _mk(spec: str, name: str) -> LtLRule:
    r = parse_ltl(spec)
    LTL_REGISTRY[name] = r
    return r


def parse_ltl(spec: "str | LtLRule") -> LtLRule:
    if isinstance(spec, LtLRule):
        return spec
    key = spec.strip().lower().replace(" ", "")
    if key in LTL_REGISTRY:
        return LTL_REGISTRY[key]
    # match the space-stripped key, so 'R5, C0, M1, S34..58, B34..45' parses
    m = _LTL_RE.match(key)
    if not m:
        raise ValueError(
            f"not a Larger-than-Life rule: {spec!r} (want "
            f"'R5,C0,M1,S34..58,B34..45' or one of {sorted(LTL_REGISTRY)})"
        )
    c = int(m.group("c"))
    return LtLRule(
        radius=int(m.group("r")),
        born=(int(m.group("b1")), int(m.group("b2"))),
        survive=(int(m.group("s1")), int(m.group("s2"))),
        middle=m.group("m") == "1",
        neighborhood=(m.group("n") or "m").upper(),
        states=2 if c in (0, 1, 2) else c,  # Golly: C0/C1/C2 all binary
    )


BOSCO = _mk("R5,C0,M1,S34..58,B34..45", "bosco")
BUGS = _mk("R5,C0,M1,S34..58,B34..45", "bugs")  # alias: Bosco's rule IS "Bugs"
MAJORITY = _mk("R4,C0,M1,S41..81,B41..81", "majority")
