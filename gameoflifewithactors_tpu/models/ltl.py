"""Larger-than-Life rule family — radius-r Moore neighborhoods.

Life-like rules look at 8 neighbors; Larger-than-Life (Evans) counts live
cells in a (2r+1)² box and births/survives on *intervals*. The box count
is a separable pair of log-tree sliding-window sums in int32 on the VPU
(ops/ltl.py — a conv-based MXU design was measured ~50x slower on chip
and replaced), alongside the bitwise SWAR path the 3×3 rules use.

Notation (Golly's LtL form): ``R5,C0,M1,S34..58,B34..45`` —
radius R, states C (C0/C2 = binary; C>=3 adds Generations-style dying
states: alive cells failing survival decay through 2..C-1 instead of
dying outright, and dying cells neither excite neighbors nor take
births), M1 counts the center cell itself in the survival window (M0
excludes it), S/B are inclusive count intervals. Named rules: "bosco"
(the classic), "bugs", "majority" (radius-4 majority vote).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Tuple

MAX_RADIUS = 7  # policy cap (int32 tree is exact at any radius): keeps
# halo-exchange depth and window shapes modest on sharded meshes


def _as_intervals(name, value) -> Tuple[Tuple[int, int], ...]:
    """Normalize born/survive: a bare (lo, hi) int pair -> ((lo, hi),); a
    tuple of pairs passes through; an empty tuple means 'never' (Golly
    allows e.g. an empty survival list in HROT rules)."""
    if isinstance(value, tuple) and not value:
        return ()
    if (isinstance(value, tuple) and len(value) == 2
            and all(isinstance(v, int) for v in value)):
        return (value,)
    if (isinstance(value, tuple) and value
            and all(isinstance(iv, tuple) and len(iv) == 2
                    and all(isinstance(v, int) for v in iv) for iv in value)):
        return value
    raise ValueError(
        f"{name} must be an inclusive (lo, hi) pair, a tuple of such "
        f"intervals, or () for 'never', got {value!r}")


@dataclasses.dataclass(frozen=True)
class LtLRule:
    """Larger-than-Life: interval birth/survival over a radius-r
    neighborhood — Moore box ("M", Golly's NM) or von Neumann diamond
    ("N", Golly's NN, |dx|+|dy| <= r). ``states == 2`` is the classic
    binary family; ``states >= 3`` adds Generations-style decay (state 1
    alive, 2..states-1 dying and non-exciting)."""

    radius: int
    born: Tuple[int, int]       # (lo, hi) — or a tuple of such intervals
    survive: Tuple[int, int]    # (lo, hi) — or a tuple of such intervals
    middle: bool = True         # M1: a live cell counts itself in its window
    neighborhood: str = "M"     # "M" box | "N" von Neumann diamond
    states: int = 2             # 2 = binary; >= 3 = dying states 2..C-1

    def __post_init__(self):
        if not 1 <= self.radius <= MAX_RADIUS:
            raise ValueError(
                f"radius must be 1..{MAX_RADIUS} (bf16-exact window sums), "
                f"got {self.radius}"
            )
        if self.neighborhood not in ("M", "N"):
            raise ValueError(
                f"neighborhood must be 'M' (Moore box) or 'N' (von Neumann "
                f"diamond), got {self.neighborhood!r}")
        if not 2 <= self.states <= 256:
            raise ValueError(
                f"states must be 2..256 (uint8 cells), got {self.states}")
        full = self.window_size
        for name in ("born", "survive"):
            ivs = _as_intervals(name, getattr(self, name))
            # canonicalize storage (bare pair when single, interval tuple
            # otherwise — what the parser produces), so equal rules
            # compare/hash equal no matter how they were constructed
            object.__setattr__(self, name, ivs[0] if len(ivs) == 1 else ivs)
            prev_hi = -2
            for lo, hi in ivs:
                if not (0 <= lo <= hi <= full):
                    raise ValueError(
                        f"{name} interval {lo}..{hi} outside 0..{full} "
                        f"for radius {self.radius} neighborhood "
                        f"{self.neighborhood}")
                if lo <= prev_hi + 1:
                    raise ValueError(
                        f"{name} intervals must be sorted and disjoint "
                        f"(non-adjacent), got {ivs}")
                prev_hi = hi

    @property
    def born_intervals(self) -> Tuple[Tuple[int, int], ...]:
        """``born`` as a tuple of inclusive (lo, hi) intervals — a single
        pair (the classic LtL form) normalizes to a 1-tuple; HROT lists
        pass through."""
        return _as_intervals("born", self.born)

    @property
    def survive_intervals(self) -> Tuple[Tuple[int, int], ...]:
        return _as_intervals("survive", self.survive)

    @property
    def window_size(self) -> int:
        """Cells in the neighborhood window (center included)."""
        r = self.radius
        return (2 * r + 1) ** 2 if self.neighborhood == "M" else (
            2 * r * (r + 1) + 1)

    @property
    def notation(self) -> str:
        def ivs(vals) -> str:
            return ",".join(f"{lo}..{hi}" for lo, hi in _as_intervals("", vals))

        return (
            f"R{self.radius},C{0 if self.states == 2 else self.states},"
            f"M{int(self.middle)},"
            f"S{ivs(self.survive)},"
            f"B{ivs(self.born)}"
            + ("" if self.neighborhood == "M" else ",NN")
        )

    def __str__(self) -> str:
        return self.notation


_VALUE_RE = re.compile(r"^(\d+)(?:(?:\.\.|-)(\d+))?$")
# shape sentinel for models.generations.parse_any dispatch: anything
# starting "R<d>,C<d>," is this family's (classic LtL or HROT form);
# full validation happens in parse_ltl
_LTL_RE = re.compile(r"^r\d+,c\d+,", re.IGNORECASE)

LTL_REGISTRY = {}


def _mk(spec: str, name: str) -> LtLRule:
    r = parse_ltl(spec)
    LTL_REGISTRY[name] = r
    return r


def parse_ltl(spec: "str | LtLRule") -> LtLRule:
    """Parse the classic LtL form (``R5,C0,M1,S34..58,B34..45[,NN]``) or
    Golly's HROT list form (``R2,C2,S6-9,B7-8[,NM]``) — S/B take
    comma-separated values or inclusive ranges (``a``, ``a-b``, ``a..b``),
    and an absent M token means M0 (HROT is outer-totalistic)."""
    if isinstance(spec, LtLRule):
        return spec
    key = spec.strip().lower().replace(" ", "")
    if key in LTL_REGISTRY:
        return LTL_REGISTRY[key]

    def fail(why: str) -> ValueError:
        return ValueError(
            f"not a Larger-than-Life/HROT rule: {spec!r} ({why}; want "
            f"'R5,C0,M1,S34..58,B34..45', 'R2,C2,S6-9,B7-8', or one of "
            f"{sorted(LTL_REGISTRY)})")

    tokens = key.split(",")
    if len(tokens) < 4 or not tokens[0].startswith("r") \
            or not tokens[1].startswith("c"):
        raise fail("expected R...,C...,[M...,]S...,B...")
    try:
        radius = int(tokens[0][1:])
        c = int(tokens[1][1:])
    except ValueError:
        raise fail("R and C take integers") from None
    i = 2
    middle = False  # HROT default: outer-totalistic (no M token)
    if tokens[i].startswith("m"):
        if tokens[i] not in ("m0", "m1"):
            raise fail("M takes 0 or 1")
        middle = tokens[i] == "m1"
        i += 1

    def values(lead: str, i: int):
        """Collect the comma-separated interval list opened by token
        ``lead`` + following bare-value tokens. A bare section token
        (e.g. 'S' straight before 'B...') is Golly's empty list."""
        if i >= len(tokens) or not tokens[i].startswith(lead):
            raise fail(f"expected {lead.upper()} section")
        ivs, first = [], tokens[i][1:]
        i += 1
        items = [first] if first else []
        while i < len(tokens) and _VALUE_RE.match(tokens[i]):
            items.append(tokens[i])
            i += 1
        for item in items:
            m = _VALUE_RE.match(item)
            if not m:
                raise fail(f"bad {lead.upper()} value {item!r}")
            lo = int(m.group(1))
            ivs.append((lo, int(m.group(2)) if m.group(2) else lo))
        return tuple(ivs), i

    survive, i = values("s", i)
    born, i = values("b", i)
    neighborhood = "M"
    if i < len(tokens):
        if tokens[i] in ("nm", "nn"):
            neighborhood = tokens[i][1].upper()
            i += 1
    if i != len(tokens):
        raise fail(f"unexpected trailing tokens {tokens[i:]}")
    return LtLRule(
        radius=radius,
        born=born,          # __post_init__ canonicalizes single intervals
        survive=survive,
        middle=middle,
        neighborhood=neighborhood,
        states=2 if c in (0, 1, 2) else c,  # Golly: C0/C1/C2 all binary
    )


BOSCO = _mk("R5,C0,M1,S34..58,B34..45", "bosco")
BUGS = _mk("R5,C0,M1,S34..58,B34..45", "bugs")  # alias: Bosco's rule IS "Bugs"
MAJORITY = _mk("R4,C0,M1,S41..81,B41..81", "majority")
