"""Device-side simulation engine: owns the grid buffer and steps it.

The engine is the layer the reference does not have as a separate thing —
there, grid state lives scattered across N·M actor mailboxes and a
generation is ~9·N·M messages (SURVEY.md §4b). Here state is one device
array (bit-packed by default) stepped by fused XLA kernels, optionally
sharded 2D over a mesh. Everything host-facing (rendering, scheduling,
checkpointing) talks to the engine through :meth:`snapshot`/:meth:`step`,
keeping device round-trips off the hot loop: ``step`` only *dispatches*
work (JAX async dispatch pipelines generations); data comes back only when
snapshot/population are explicitly asked for. (Exception: the sparse
backend fetches one scalar per step() call — see Engine.step.)
"""

from __future__ import annotations

import warnings
from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .analysis import sanitizers as _sanitizers
from .models.generations import GenRule, parse_any
from .models.ltl import LtLRule
from .models.rules import Rule
from .obs import profiler as obs_profiler
from .obs import spans as obs_spans
from .ops import bitpack
from .ops.packed import multi_step_packed
from .ops import pallas_stencil
from .ops.pallas_stencil import multi_step_pallas
from .ops.stencil import Topology, multi_step
from .parallel import mesh as mesh_lib
from .parallel import sharded

BACKENDS = ("packed", "dense", "pallas", "sparse", "paged")


@lru_cache(maxsize=1)
def _ltl_planes_tpu_rates() -> Optional[dict]:
    """On-chip planes-vs-dense rates from the ``ltl_planes`` worklist
    record (results/tpu_worklist.json, captured by scripts/tpu_worklist.py
    child_ltl_planes), or None when no usable capture exists. This is the
    evidence that routes C >= 3 LtL on TPU (VERDICT r4 #5): the engine
    consults the measurement at construction instead of hardcoding a
    choice, mirroring how binary LtL is routed per-platform. Cached per
    process — routing is decided at Engine construction and a mid-process
    recapture changing the verdict would make identical constructors
    disagree."""
    import json
    import os

    from .utils import provenance

    try:
        with open(os.path.join(provenance.repo_root(), "results",
                               "tpu_worklist.json")) as f:
            rec = json.load(f).get("ltl_planes") or {}
        if rec.get("ok") and rec.get("platform") == "tpu":
            got = rec.get("cell_updates_per_sec") or {}
            if isinstance(got.get("planes"), (int, float)) \
                    and isinstance(got.get("dense"), (int, float)):
                return {"planes": float(got["planes"]),
                        "dense": float(got["dense"])}
    except (OSError, json.JSONDecodeError, AttributeError):
        pass
    return None


def _chunked(bulk, pergen, g: int):
    """(state, n) runner advancing n = chunks*g + rem generations: bulk
    chunks through a g-generations-per-call runner, the remainder through a
    per-generation runner. Both runners donate their input, so the
    intermediate hand-off between them is safe by construction."""
    def _run(s, n):
        chunks, rem = divmod(int(n), g)
        if chunks:
            s = bulk(s, chunks)
        if rem:
            s = pergen(s, rem)
        return s
    return _run


class Engine:
    """Steps a Game-of-Life grid on device.

    Parameters
    ----------
    grid: (H, W) uint8 array-like in {0, 1} — the initial universe.
    rule: a Rule or rule string ("B3/S23", "highlife", ...).
    topology: TORUS (wrap) or DEAD (all-dead boundary).
    mesh: optional jax Mesh for 2D sharding; None = single device.
    backend: "auto" (default: the fastest correct path — on TPU that is
        the "pallas" kernel for 3x3 binary rules single-device and on
        any mesh whose flattened band decomposition the kernel supports
        (2D meshes flatten into nx·ny full-width row bands), either
        topology, "packed" otherwise), "packed" (32 cells/word SWAR fast
        path), "dense" (1 byte/cell, debug path), "pallas"
        (temporal-blocked Mosaic kernel advancing several generations per
        HBM round-trip; serves 3x3 binary rules, Generations, and LtL
        rules, single-device and on meshes via flattened row bands — DEAD
        vertical closure rides a per-device SMEM edge code), or "sparse"
        (activity-tiled: compute
        scales with changed area, for huge mostly-empty universes;
        3x3 binary bitboards and, single-device, Generations bit-plane
        stacks; both topologies on one device — torus refreshes the halo
        ring with wrapped edges each generation — and with a mesh the
        binary form shards with per-device activity skipping), or
        "paged" (page-table grids over a fixed tile pool, memory/ —
        tiles exist only where live structure does, so footprint scales
        with activity, not area; single device, both topologies, any
        rule without birth-from-nothing).
    gens_per_exchange: sharded packed and pallas backends — G > 1
        exchanges a depth-G halo once per G generations
        (communication-avoiding) instead of a 1-deep halo every
        generation; bit-exact for G <= 32 on the packed 2D-tile runner,
        uncapped on the pallas row-band runners.
    """

    def __init__(
        self,
        grid,
        rule: "Rule | str",
        *,
        topology: Topology = Topology.TORUS,
        mesh: Optional[Mesh] = None,
        backend: str = "auto",
        sparse_opts: Optional[dict] = None,
        gens_per_exchange: int = 1,
    ):
        if backend not in BACKENDS and backend != "auto":
            raise ValueError(
                f"backend must be 'auto' or one of {BACKENDS}, got {backend!r}")
        # warm start (aot/): every compile below this point round-trips
        # through the persistent disk cache (GOLTPU_CACHE_DIR; default
        # ~/.cache/gameoflifewithactors_tpu/) — idempotent, a few µs warm
        from .aot import cache as aot_cache

        aot_cache.ensure_persistent_cache()
        self.rule = parse_any(rule)
        from .models.elementary import ElementaryRule

        if isinstance(self.rule, ElementaryRule):
            raise ValueError(
                f"{self.rule.notation} is a 1D (elementary) rule; the Engine "
                "drives 2D grids. Use the CLI spacetime route "
                f"(python -m gameoflifewithactors_tpu --rule {self.rule.notation} "
                "--render final), or ops.elementary directly: "
                "multi_step_elementary / evolve_spacetime on a packed row "
                "(see examples/wolfram.py)")
        self._generations = isinstance(self.rule, GenRule)
        self._ltl = isinstance(self.rule, LtLRule)
        explicit_packed = backend == "packed"  # vs auto-resolved below
        if backend == "auto":
            backend = self._resolve_auto(grid, mesh, topology, gens_per_exchange)
        if gens_per_exchange < 1:
            raise ValueError(
                f"gens_per_exchange must be >= 1, got {gens_per_exchange}")
        if gens_per_exchange != 1 and not (
                mesh is not None
                and ((backend in ("packed", "pallas")
                      and not (self._generations or self._ltl))
                     or (backend == "pallas"
                         and (self._generations or self._ltl)))):
            raise ValueError(
                "gens_per_exchange applies to the sharded packed and pallas "
                "backends only (mesh + backend='packed'/'pallas'/'auto' for "
                "3x3 binary rules, mesh + backend='pallas' for Generations "
                "and LtL)")
        self.topology = topology
        self.mesh = mesh
        self.backend = backend
        self.gens_per_exchange = gens_per_exchange
        np_grid = np.asarray(grid, dtype=np.uint8)
        self._validate_states(np_grid)
        # copy=True: the CPU backend zero-copies host numpy buffers, and
        # the donated step chain then writes through the caller's memory
        # for the engine's whole lifetime — freed-seed heap corruption the
        # moment the caller drops their array (resilience soak found this)
        grid = jnp.array(np_grid, copy=True)
        if grid.ndim != 2:
            raise ValueError(f"grid must be 2D, got shape {grid.shape}")
        self.shape: Tuple[int, int] = tuple(grid.shape)
        self.generation = 0

        # LtL with the packed backend: the state is a plain binary
        # bitboard stepped by bit-sliced box sums (ops/packed_ltl.py), so
        # it shares all the _packed machinery (snapshot/population/
        # checkpoint); sharded tiles exchange r-row + 1-word halos
        _ny = mesh.shape[mesh_lib.COL_AXIS] if mesh is not None else 1
        # the band-kernel runners flatten the mesh into full-width row
        # bands (parallel/sharded.py), so the pallas path never shards the
        # width: packing only needs whole 32-cell words
        _band_path = mesh is not None and backend == "pallas"
        self._banded = False  # finalized in the mesh block below
        _pack_cols = 1 if _band_path else _ny
        _packs = self.shape[1] % (bitpack.WORD * _pack_cols) == 0  # words shard whole
        # sparse LtL rides the same bit-sliced packed windows and the
        # pallas LtL kernel the same packed layout, so all three share the
        # packed gate (word-divisible width and binary states; both
        # neighborhoods — the diamond sum is per-row separable,
        # ops/packed_ltl.py; multi-state C>=3 decays on the byte path)
        self._ltl_packed = (self._ltl
                            and backend in ("packed", "sparse", "pallas",
                                            "paged")
                            and _packs and self.rule.states == 2)
        # multi-state (C >= 3) LtL: bit-plane stack (the Generations
        # layout driven by radius-r interval counts, ops/packed_ltl.py
        # step_ltl_planes) — the packed/sparse face of the decay family
        # the dense byte path serves
        self._ltl_planes = (self._ltl and self.rule.states >= 3
                            and backend in ("packed", "sparse", "paged")
                            and _packs)
        if self._ltl and backend in ("sparse", "paged") and not (
                self._ltl_packed or self._ltl_planes):
            # an explicit sparse/paged request that the packed layouts
            # cannot serve must not silently become a dense run
            raise ValueError(
                f"{backend} LtL needs a width divisible by "
                f"{bitpack.WORD * _pack_cols} (32-cell words must shard "
                f"whole over {_ny} mesh column(s)), got "
                f"{self.rule.notation} on {self.shape}; use backend='dense'")
        if (self._ltl and backend in ("packed", "pallas")
                and not (self._ltl_packed or self._ltl_planes)):
            # the bit-sliced/kernel paths can't serve this shape (width
            # not sharding into whole words): fall back to the byte path;
            # self.backend reports what actually runs either way, but only
            # an EXPLICIT packed/pallas request warns — the auto
            # resolver's fallback is by design
            if gens_per_exchange != 1:
                # the dense fallback has no communication-avoiding runner:
                # dropping the requested exchange depth silently would be
                # a contract violation (same rule as the Generations twin)
                raise ValueError(
                    f"gens_per_exchange={gens_per_exchange} needs the LtL "
                    f"band kernel, but {self.rule.notation} on {self.shape} "
                    "cannot take the packed path (binary C0/C2 rules with "
                    "word-divisible widths only)")
            if explicit_packed or backend == "pallas":
                warnings.warn(
                    f"packed/pallas LtL unavailable for {self.rule.notation} "
                    f"on {self.shape} over {_ny} mesh column(s) (binary "
                    "C0/C2 rules with word-divisible shard widths only); "
                    "running the dense byte path",
                    stacklevel=3,
                )
            self.backend = backend = "dense"
        self._packed = (backend in ("packed", "pallas", "sparse", "paged")
                        and not (self._generations or self._ltl)
                        ) or self._ltl_packed
        # Generations with the packed backend: bit-plane stack
        # (ops/packed_generations.py), ~4x less HBM traffic than the byte
        # layout; shards as P(None, x, y) with per-plane halo exchange.
        # Multi-state LtL shares the layout (and thus the pack/unpack/
        # population/checkpoint machinery) — only the stepper differs.
        self._gen_packed = (self._generations
                            and backend in ("packed", "pallas", "sparse",
                                            "paged")
                            and _packs) or self._ltl_planes
        if self._generations and backend in ("sparse", "paged") \
                and not self._gen_packed:
            # the sparse/paged engines' Generations layout IS the plane
            # stack; there is no byte-layout path to fall back to
            raise ValueError(
                f"the {backend} backend stores Generations universes as "
                f"bit-plane stacks: width {self.shape[1]} must shard into "
                f"whole 32-cell words over {_ny} mesh column(s) "
                f"(divisible by {32 * _ny})")
        if (self._generations and backend in ("packed", "pallas")
                and not self._gen_packed):
            if gens_per_exchange != 1:
                # the dense fallback has no communication-avoiding runner:
                # dropping the requested exchange depth silently would be a
                # contract violation, so mirror the binary path's hard error
                raise ValueError(
                    f"gens_per_exchange={gens_per_exchange} needs the "
                    f"bit-plane band runner, but width {self.shape[1]} does "
                    f"not pack into 32-cell words over {_ny} mesh column(s)")
            # same honesty as the LtL fallback: report the byte path that
            # actually runs, warn only on explicit requests
            if explicit_packed or backend == "pallas":
                warnings.warn(
                    f"bit-plane Generations unavailable for width "
                    f"{self.shape[1]} over {_ny} mesh column(s) (32-cell "
                    "words must shard whole); running the dense byte path",
                    stacklevel=3,
                )
            self.backend = backend = "dense"
        self._sparse = None
        self._flags = None
        self._sparse_tiles = None
        self._ghost_pipeline = False  # width-g overlapped pipeline in use
        if mesh is not None and backend == "paged":
            raise ValueError(
                "the paged backend is single-device (its page tables are "
                "host bookkeeping over one pool slab); use backend="
                "'sparse' for sharded activity skipping")
        if mesh is not None:
            # validate in *cell* units before packing, so the error names the
            # user's grid shape, not the packed word shape
            nx = mesh.shape[mesh_lib.ROW_AXIS]
            ny = mesh.shape[mesh_lib.COL_AXIS]
            # the dense fallbacks above may have walked an explicit pallas
            # request off the band path — re-derive from the final backend.
            # On (nx, 1) meshes the flattened spec degenerates to the
            # proven P('x', None) layout, so _banded placement only kicks
            # in when the column axis is real.
            _band_path = backend == "pallas"
            self._banded = _band_path and ny > 1
            if _band_path:
                # band path: nx*ny full-width bands over the flattened
                # mesh; the width packs whole words but is not sharded
                if self.shape[0] % (nx * ny) or self.shape[1] % bitpack.WORD:
                    raise ValueError(
                        f"grid {self.shape} not divisible into {nx * ny} "
                        f"full-width row bands over mesh ({nx}, {ny}): need "
                        f"height % {nx * ny} == 0 and width % "
                        f"{bitpack.WORD} == 0 (band-kernel path)")
            else:
                wq = (bitpack.WORD * ny if self._packed or self._gen_packed
                      else ny)
                if self.shape[0] % nx or self.shape[1] % wq:
                    raise ValueError(
                        f"grid {self.shape} not divisible over mesh ({nx}, {ny}): "
                        f"need height % {nx} == 0 and width % {wq} == 0"
                        + (" (bit-packed backends shard 32-cell words)" if self._packed else "")
                    )
        if self._gen_packed:
            from .ops.packed_generations import pack_generations_for

            state = pack_generations_for(grid, self.rule)
        else:
            state = bitpack.pack(grid) if self._packed else grid
        if mesh is not None:
            state = mesh_lib.device_put_sharded_grid(state, mesh,
                                                     banded=self._banded)
            def _band_kernel(make_band, make_pergen):
                # row-band native kernel: bulk chunks of g generations
                # through the slab kernel, n % g remainders on the
                # per-generation runner — one definition for the binary,
                # Generations, and LtL twins. On 2D meshes the remainder
                # runner must keep the flattened band layout (and its
                # width-not-sharded contract), so it is the banded XLA
                # runner, not the 2D-tile one.
                g = (gens_per_exchange if gens_per_exchange > 1
                     else pallas_stencil.DEFAULT_GENS_PER_CALL)
                self.gens_per_exchange = g
                pergen = (
                    sharded.make_multi_step_banded(
                        mesh, self.rule, topology, donate=True)
                    if ny > 1
                    else make_pergen(mesh, self.rule, topology, donate=True))
                return _chunked(
                    make_band(mesh, self.rule, topology,
                              gens_per_exchange=g, donate=True),
                    pergen, g)

            def _tiled_sparse(make):
                # shared tile-dim resolution for the per-tile sharded
                # sparse runners (binary bitboard / Generations stack):
                # auto-fit the LOCAL shard, honor sparse_opts overrides,
                # validate divisibility with a clear error
                from .ops import sparse as sparse_ops

                opts = dict(sparse_opts or {})
                local_h = self.shape[0] // nx
                local_w = self.shape[1] // bitpack.WORD // ny
                auto_tr, auto_tw = sparse_ops.auto_tile(local_h, local_w)
                tr = opts.get("tile_rows", auto_tr)
                tw = opts.get("tile_words", auto_tw)
                if local_h % tr or local_w % tw:
                    raise ValueError(
                        f"per-device shard {local_h}x"
                        f"{local_w * bitpack.WORD} cells not divisible "
                        f"into sparse tiles of {tr}x{tw * bitpack.WORD} "
                        "cells; pick sparse tile dims that divide the "
                        "shard (or omit them to auto-tile)")
                return self._tiled_sparse_runner(
                    make(mesh, self.rule, topology, tile_rows=tr,
                         tile_words=tw, capacity=opts.get("capacity"),
                         donate=True),
                    mesh, tr, tw, state)
            if self._ltl:
                r = self.rule.radius
                if _band_path:
                    # band path: full-width bands of h/(nx*ny) rows — the
                    # width is never sharded, so only the band height
                    # gates (>= r for the per-gen remainder exchange; the
                    # kernel's deeper r*g chunk requirement raises its own
                    # trace-time error naming gens_per_exchange)
                    if self.shape[0] // (nx * ny) < r:
                        raise ValueError(
                            f"{self.shape[0] // (nx * ny)}-row bands over "
                            f"the flattened ({nx}, {ny}) mesh are smaller "
                            f"than the rule radius {r}: halo exchange "
                            "needs depth <= band height; use fewer devices")
                elif self.shape[0] // nx < r or self.shape[1] // ny < r:
                    raise ValueError(
                        f"mesh tiles {self.shape[0] // nx}x{self.shape[1] // ny} "
                        f"smaller than the rule radius {r}: halo exchange "
                        "needs depth <= tile size; use fewer devices"
                    )
                if backend == "sparse":
                    # per-tile skipping inside each shard, radius-r halos
                    # and wake dilation (VERDICT r3 Weak #4); plane-stack
                    # form for C >= 3 decay
                    self._run = _tiled_sparse(
                        sharded.make_multi_step_generations_packed_sparse_tiled
                        if self._ltl_planes
                        else sharded.make_multi_step_packed_sparse_tiled)
                elif self._ltl_planes:
                    self._run = sharded.make_multi_step_ltl_planes(
                        mesh, self.rule, topology, donate=True)
                elif self._ltl_packed and backend == "pallas":
                    self._run = _band_kernel(
                        sharded.make_multi_step_ltl_pallas,
                        sharded.make_multi_step_ltl_packed)
                elif self._ltl_packed:
                    self._run = sharded.make_multi_step_ltl_packed(
                        mesh, self.rule, topology, donate=True)
                else:
                    self._run = sharded.make_multi_step_ltl(
                        mesh, self.rule, topology, donate=True)
            elif self._generations:
                if backend == "sparse":
                    # per-tile skipping inside each shard, plane-stack form
                    self._run = _tiled_sparse(
                        sharded.make_multi_step_generations_packed_sparse_tiled)
                elif self._gen_packed and backend == "pallas":
                    self._run = _band_kernel(
                        sharded.make_multi_step_generations_pallas,
                        sharded.make_multi_step_generations_packed)
                elif self._gen_packed:
                    self._run = sharded.make_multi_step_generations_packed(
                        mesh, self.rule, topology, donate=True)
                else:
                    self._run = sharded.make_multi_step_generations(
                        mesh, self.rule, topology, donate=True)
            elif backend == "sparse":
                # PER-TILE activity skipping inside each shard (VERDICT
                # round-2 item #5): the single-device engine's tiling
                # composed under shard_map — a mostly-empty 65536² gun
                # sharded over N devices sleeps at tile, not device,
                # granularity.
                self._run = _tiled_sparse(
                    sharded.make_multi_step_packed_sparse_tiled)
            elif backend == "pallas":
                # row-band native kernel: exchange a depth-g halo, advance g
                # gens in the Mosaic slab kernel, crop (parallel/sharded.py
                # make_multi_step_pallas — (nx, 1) meshes, both topologies;
                # it raises with directions otherwise)
                self._run = _band_kernel(
                    sharded.make_multi_step_pallas,
                    sharded.make_multi_step_packed)
            else:
                make = (
                    sharded.make_multi_step_packed
                    if backend == "packed"
                    else sharded.make_multi_step_dense
                )
                self._run = make(mesh, self.rule, topology, donate=True)
                if gens_per_exchange > 1 and backend == "packed":
                    # communication-avoiding: bulk generations go through
                    # the width-g ghost-zone pipeline (boundary-first
                    # compute, exchange overlapping the interior) when the
                    # per-device tile can host its 2g-row / 2·ceil(g/32)-
                    # word rings; tiles too small for overlap fall back to
                    # the plain depth-g runner. n % g remainders use the
                    # per-gen runner built above either way.
                    nx = mesh.shape[mesh_lib.ROW_AXIS]
                    ny = mesh.shape[mesh_lib.COL_AXIS]
                    if mesh_lib.ghost_fits(state.shape[0] // nx,
                                           state.shape[1] // ny,
                                           gens_per_exchange):
                        bulk = sharded.make_multi_step_packed_ghost(
                            mesh, self.rule, topology,
                            gens_per_exchange=gens_per_exchange,
                            donate=True)
                        self._ghost_pipeline = True
                    else:
                        bulk = sharded.make_multi_step_packed_deep(
                            mesh, self.rule, topology,
                            gens_per_exchange=gens_per_exchange,
                            donate=True)
                    self._run = _chunked(bulk, self._run, gens_per_exchange)
        elif backend == "sparse":
            from .ops.sparse import (
                DEFAULT_TILE_ROWS,
                DEFAULT_TILE_WORDS,
                SparseEngineState,
            )

            opts = dict(sparse_opts or {})
            # pre-validate in cell units only for explicit tile opts;
            # without them SparseEngineState auto-tiles divisibly
            tr = opts.get("tile_rows", DEFAULT_TILE_ROWS)
            tw = opts.get("tile_words", DEFAULT_TILE_WORDS)
            if (("tile_rows" in opts or "tile_words" in opts)
                    and (self.shape[0] % tr or self.shape[1] % (bitpack.WORD * tw))):
                raise ValueError(
                    f"grid {self.shape} not divisible into sparse tiles of "
                    f"{tr} x {bitpack.WORD * tw} cells; pass sparse_opts="
                    f"dict(tile_rows=..., tile_words=...) that divide it"
                )
            self._sparse = SparseEngineState(
                state, self.rule, topology=topology, **opts)
            self._run = None  # step() routes through the sparse state
            state = None  # the padded copy inside _sparse is the state now
        elif backend == "paged":
            from .memory import PagedEngineState

            # sparse_opts carries the slab geometry here too — the paged
            # engine is the sparse engine's pool-allocated sibling, and
            # the keys (tile_rows/tile_words/capacity) mean the same
            # thing; PagedEngineState validates divisibility itself
            self._sparse = PagedEngineState(
                state, self.rule, topology=topology,
                **dict(sparse_opts or {}))
            self._run = None  # step() routes through the paged state
            state = None  # the pool slab holds the live tiles now
        elif backend == "pallas" and self._ltl:
            # radius-r temporal-blocked kernel (native on TPU, interpret
            # elsewhere); unsupported shapes fall back to the bit-sliced
            # XLA path with a warning, like binary pallas
            interpret = pallas_stencil.default_interpret()
            if not pallas_stencil.ltl_supported(state.shape, self.rule,
                                                on_tpu=not interpret):
                warnings.warn(
                    f"pallas LtL kernel cannot serve {self.rule.notation} "
                    f"at {self.shape[0]}x{self.shape[1]} on TPU (lane/"
                    "sublane alignment or VMEM budget); falling back to "
                    "the XLA bit-sliced path",
                    stacklevel=3,
                )
                from .ops.packed_ltl import multi_step_ltl_packed

                self._run = lambda s, n: multi_step_ltl_packed(
                    s, n, rule=self.rule, topology=self.topology, donate=True)
            else:
                self._run = lambda s, n: pallas_stencil.multi_step_ltl_pallas(
                    s, int(n), rule=self.rule, topology=self.topology,
                    interpret=interpret, donate=True)
        elif backend == "pallas" and not self._generations:
            # native Mosaic on TPU; interpret mode elsewhere (CPU tests)
            interpret = pallas_stencil.default_interpret()
            if not pallas_stencil.supported(state.shape, on_tpu=not interpret):
                warnings.warn(
                    f"pallas backend needs width % 4096 == 0 and height % 8 "
                    f"== 0 on TPU (got {self.shape[0]}x{self.shape[1]}); "
                    "falling back to the XLA packed path",
                    stacklevel=3,
                )
                self._run = lambda s, n: multi_step_packed(
                    s, n, rule=self.rule, topology=self.topology, donate=True
                )
            else:
                self._run = lambda s, n: multi_step_pallas(
                    s, int(n), rule=self.rule, topology=self.topology,
                    interpret=interpret, donate=True,
                )
        elif self._ltl_packed:
            from .ops.packed_ltl import multi_step_ltl_packed

            self._run = lambda s, n: multi_step_ltl_packed(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        elif self._ltl_planes:
            from .ops.packed_ltl import multi_step_ltl_planes

            self._run = lambda s, n: multi_step_ltl_planes(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        elif self._ltl:
            from .ops.ltl import multi_step_ltl

            self._run = lambda s, n: multi_step_ltl(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        elif self._gen_packed and backend == "pallas":
            # temporal-blocked kernel over the bit-plane stack (native on
            # TPU, interpret elsewhere); unsupported shapes fall back to
            # the XLA bit-plane path with a warning, like binary pallas
            interpret = pallas_stencil.default_interpret()
            b = state.shape[0]
            if not pallas_stencil.supported(state.shape[1:],
                                            on_tpu=not interpret, planes=b):
                warnings.warn(
                    f"pallas Generations kernel needs width % 4096 == 0 and "
                    f"height % 8 == 0 on TPU (got "
                    f"{self.shape[0]}x{self.shape[1]}); falling back to the "
                    "XLA bit-plane path",
                    stacklevel=3,
                )
                from .ops.packed_generations import (
                    multi_step_packed_generations,
                )

                self._run = lambda s, n: multi_step_packed_generations(
                    s, n, rule=self.rule, topology=self.topology, donate=True
                )
            else:
                self._run = lambda s, n: (
                    pallas_stencil.multi_step_pallas_generations(
                        s, int(n), rule=self.rule, topology=self.topology,
                        interpret=interpret, donate=True))
        elif self._gen_packed:
            from .ops.packed_generations import multi_step_packed_generations

            self._run = lambda s, n: multi_step_packed_generations(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        elif self._generations:
            from .ops.generations import multi_step_generations

            self._run = lambda s, n: multi_step_generations(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        elif backend == "packed":
            self._run = lambda s, n: multi_step_packed(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        else:
            self._run = lambda s, n: multi_step(
                s, n, rule=self.rule, topology=self.topology, donate=True
            )
        self._state = state
        # warm start layer 2: when the AOT registry holds a serialized
        # runner for this exact (spec, jax/jaxlib, platform), load it in
        # place of the JIT path — no re-trace, and the loader's wrapper
        # compile rides the persistent cache. One hash + one stat when
        # nothing is registered; any load problem warns and keeps JIT.
        # Note the AOT path does not donate its input buffer (jax.export
        # has no donation contract), so it holds two state buffers in
        # memory — irrelevant on host-sized grids, and an engine that
        # needs in-place double-buffering can opt out via GOLTPU_AOT=0.
        self.aot_loaded = False
        if self._sparse is None:
            from .aot import registry as aot_registry

            aot_run = aot_registry.maybe_load_for_engine(self)
            if aot_run is not None:
                self._run = aot_run
                self.aot_loaded = True
        # retrace sanitizer (GOLTPU_SANITIZE=1): a warm-started engine
        # claiming zero compile cost must never pay a real XLA compile
        # again — arm a sentinel over the process compile log and check
        # it after every step (analysis/sanitizers.py). Cold engines are
        # exempt: their first steps legitimately compile.
        self._retrace_sentinel = None
        if self.aot_loaded and _sanitizers.enabled():
            self._retrace_sentinel = _sanitizers.RetraceSentinel(
                context=f"warm-started engine ({self.rule.notation} "
                        f"{self.shape[0]}x{self.shape[1]} "
                        f"{self.backend})").arm()

    def _flagged_sparse_runner(self, run2, mesh: Mesh):
        """Wrap a sharded sparse runner (binary bitboard or Generations
        plane stack — both return ``(state, flags)``) so the per-device
        activity flags ride along with the engine state."""
        self._flags = sharded.initial_flags(mesh)

        def _run(s, n):
            s, self._flags = run2(s, self._flags, n)
            return s

        return _run

    def _tiled_sparse_runner(self, run2, mesh: Mesh, tile_rows: int,
                             tile_words: int, state):
        """Like :meth:`_flagged_sparse_runner`, but the flags are the
        per-shard TILE activity map (one uint32 per tile, sharded like the
        grid) seeded from the initial state's live tiles."""
        self._sparse_tiles = (tile_rows, tile_words)
        self._flags = sharded.initial_tile_activity(
            state, mesh, tile_rows, tile_words)

        def _run(s, n):
            s, self._flags = run2(s, self._flags, n)
            return s

        return _run

    def _resolve_auto(self, grid, mesh: Optional[Mesh], topology: Topology,
                      gens_per_exchange: int = 1) -> str:
        """'auto' = the fastest correct backend for this rule/platform/shape:
        the temporal-blocked native Pallas kernel (canonical-protocol
        2.2e12 cell-updates/s on a v5e, ~12x the XLA SWAR rate) for 3x3
        binary rules at shapes it supports — single-device, and any mesh
        whose flattened row-band decomposition the kernel takes (2D
        meshes flatten, parallel/sharded.py) on TPU, either topology; the
        packed SWAR path everywhere else. Off
        'packed', Generations rules take the bit-plane stack when the width
        packs (% 32), the byte path otherwise; binary LtL picks bit-sliced
        packed on TPU and the byte path elsewhere; C >= 3 LtL picks the
        plane stack on CPU for diamonds and box radius <= 3 (measured
        crossover — see the notes in the LtL branch below), the byte path
        otherwise."""
        if self._ltl:
            # Binary: the bit-sliced path wins on the TPU VPU but measured
            # ~2.4x slower than the byte path under XLA's CPU lowering —
            # pick per platform (explicit backend='packed' still forces
            # it). Both neighborhoods pack (the diamond sum is per-row
            # separable). The width must shard into whole words across the
            # mesh columns, or the constructor would immediately walk the
            # choice back to dense.
            on_tpu = not pallas_stencil.default_interpret()
            shape = np.shape(grid)
            ny = mesh.shape[mesh_lib.COL_AXIS] if mesh is not None else 1
            packs = (len(shape) == 2
                     and shape[1] % (bitpack.WORD * ny) == 0)
            if self.rule.states == 2:
                return "packed" if on_tpu and packs else "dense"
            # C >= 3 plane stack vs dense byte path, measured on CPU
            # (2026-07-31, 1024² uniform soup, this host): planes wins
            # 2.0-6.5x for box radius <= 3 and 3.3-11x for EVERY diamond
            # (the dense diamond's cumsum assembly is the slow part);
            # dense wins 1.2-1.5x for box radius >= 4. On TPU the choice
            # is routed from the on-chip ltl_planes capture within the
            # same crossover envelope (diamond or box radius <= 3 — the
            # shapes where planes can win at all); absent a usable
            # capture, auto never routes onto an unmeasured path and
            # stays dense (explicit backend='packed' still forces it).
            if packs and (self.rule.neighborhood == "N"
                          or self.rule.radius <= 3):
                if not on_tpu:
                    return "packed"
                rates = _ltl_planes_tpu_rates()
                if rates is not None and rates["planes"] > rates["dense"]:
                    return "packed"
            return "dense"
        if self._generations:
            # bit-plane stack beats the dense byte path on BOTH platforms:
            # measured on this host's CPU 2026-08-01 (1024² soup, 64 gens)
            # planes/dense = 5.3x (brain C=3), 4.7x (starwars C=4), 3.6x
            # (belzhab C=8); on chip generations_brain measured the plane
            # path 6.4e9/s with bit-identity (results/tpu_worklist.json)
            return "packed"
        on_tpu = not pallas_stencil.default_interpret()
        shape = np.shape(grid)
        if len(shape) != 2 or shape[1] % bitpack.WORD:
            return "packed"  # shape errors surface in the main path
        if mesh is not None:
            # native row-band path: any mesh whose FLATTENED band
            # decomposition (nx·ny full-width bands — 2D meshes flatten,
            # parallel/sharded.py _band_axis) keeps the kernel's alignment
            # (width % 4096, band height th % 8, exchange depth % 8);
            # both topologies (DEAD rides the kernel's SMEM edge code).
            # An explicit gens_per_exchange the slab kernel cannot honor
            # (not a multiple of 8, or deeper than the band) must keep
            # resolving to the packed deep runner, as it did before the
            # band path existed — auto never picks a crashing backend.
            nb = (mesh.shape[mesh_lib.ROW_AXIS]
                  * mesh.shape[mesh_lib.COL_AXIS])
            th = shape[0] // nb if shape[0] % nb == 0 else 0
            g = (gens_per_exchange if gens_per_exchange > 1
                 else pallas_stencil.DEFAULT_GENS_PER_CALL)
            if (on_tpu and th > 0
                    and pallas_stencil.band_supported(
                        th, g, native=True,
                        wp=shape[1] // bitpack.WORD)
                    and pallas_stencil.supported(
                        (shape[0], shape[1] // bitpack.WORD), on_tpu=True)):
                return "pallas"
            return "packed"
        if on_tpu and pallas_stencil.supported(
                (shape[0], shape[1] // bitpack.WORD), on_tpu=True):
            return "pallas"
        return "packed"

    # -- stepping ------------------------------------------------------------

    def step(self, n: int = 1) -> None:
        """Advance ``n`` generations.

        Dense/packed/pallas backends dispatch async (no block). The sparse
        backend reads one scalar per call (generations completed by its
        on-device loop — the price of its copy-free overflow design), so
        it synchronizes with the device once per step() call."""
        if n < 0:
            raise ValueError(f"cannot step a negative number of generations: {n}")
        if n == 0:
            return
        # span = dispatch time only (async backends return before the device
        # finishes); the sync cost shows under engine.sync, readback under
        # engine.snapshot — the separation the telemetry report keys on
        # the profiler annotation is a nullcontext unless a sampling
        # profiler is armed (obs/profiler.py): armed capture windows
        # show "goltpu.dispatch[...]" slices on the host track, unarmed
        # runs pay nothing
        with obs_spans.span("engine.step", generations=n,
                            backend=self.backend), \
                obs_profiler.dispatch_annotation(
                    f"goltpu.dispatch[{self.backend}]"):
            if self._sparse is not None:
                # the sparse backend's one-scalar-per-step readback is
                # its documented contract (copy-free overflow design) —
                # a declared sync point, not a silent one
                with _sanitizers.allow_host_transfers(
                        "sparse step reads its generations-completed "
                        "scalar (see Engine.step docstring)"):
                    self._sparse.step(n)
            else:
                # sanitizer (GOLTPU_SANITIZE=1): the dense/packed/pallas
                # hot loop must stay transfer-free — an implicit
                # device→host fetch here serializes the async pipeline
                with _sanitizers.no_implicit_host_transfers():
                    self._state = self._run(self._state, n)
        self.generation += n
        if self._retrace_sentinel is not None:
            self._retrace_sentinel.check()

    def block_until_ready(self) -> None:
        with obs_spans.span("engine.sync"):
            if self._sparse is not None:
                self._sparse.padded.block_until_ready()  # no interior-slice copy
            else:
                self._state.block_until_ready()

    # -- observation ---------------------------------------------------------

    @property
    def state(self) -> jax.Array:
        """The raw device array (packed words or uint8 cells).

        The engine donates this buffer to the next :meth:`step` (in-place
        double-buffering in HBM), so a reference held across a step() is
        dead on TPU. Use :meth:`snapshot` for a stable host copy.
        """
        if self._sparse is not None:
            return self._sparse.packed
        return self._state

    def snapshot(self, max_shape: Optional[Tuple[int, int]] = None) -> np.ndarray:
        """The full grid as host uint8 (H, W); optionally block-max downsampled
        *on device* to fit within ``max_shape`` before transfer, so rendering
        a 16384² universe to an 80-column console ships ~2 KB, not 256 MB."""
        with obs_spans.span("engine.snapshot"), \
                _sanitizers.allow_host_transfers(
                    "snapshot IS the designated host readback (renderers, "
                    "checkpoints, reports fetch here, not in the loop)"):
            if self._gen_packed:
                from .ops.packed_generations import unpack_generations

                dense = unpack_generations(self.state)
            else:
                dense = bitpack.unpack(self.state) if self._packed else self.state
            if max_shape is not None:
                dense = _downsample_max(dense, max_shape)
            # copy while `dense` is still referenced: np.asarray of a CPU
            # jax.Array is a zero-copy view, and this buffer is either the
            # live state (donated to the next step) or a temporary about to
            # be collected — a view would dangle, and "stable host copy" is
            # this method's contract (see the `state` docstring)
            return np.array(dense, dtype=np.uint8, copy=True)

    def halo_bytes_per_gen(self, source: str = "auto") -> int:
        """Interconnect (ICI/DCN) bytes one generation moves: the ppermute
        strips per device tile (halo.py), amortized over the exchange
        period when a communication-avoiding runner is active
        (gens_per_exchange > 1). 0 when unsharded — the analogue of the
        reference's ~9·N·M mailbox messages/generation (SURVEY.md §4b)
        collapsing to 4 strip sends per *tile*.

        ``source``: "auto" (default) serves the figure **measured from the
        compiled HLO** — collective-permute operand bytes × pairs in the
        SPMD-partitioned program XLA actually emits
        (utils/profiling.measured_halo_bytes_per_gen; one extra
        one-generation compile, cached for the engine's lifetime) — and
        falls back to the arithmetic model only when that lowering fails;
        "measured" requires the HLO figure (raises otherwise); "model"
        returns the arithmetic estimate, whose agreement with the HLO on
        every lowerable sharded layout is pinned in
        tests/test_halo_bytes.py (VERDICT r3 Weak #6: derived beats
        hand-maintained wherever possible)."""
        if source not in ("auto", "measured", "model"):
            raise ValueError(
                f"source must be 'auto', 'measured', or 'model', got {source!r}")
        if self.mesh is None:
            return 0
        if source != "model":
            if not getattr(self, "_halo_hlo_tried", False):
                from .utils.profiling import measured_halo_bytes_per_gen

                self._halo_hlo = None          # before the flag: a mid-
                self._halo_hlo_err = None      # compile interrupt must not
                self._halo_hlo_tried = True    # leave the attrs unset
                try:
                    self._halo_hlo = measured_halo_bytes_per_gen(self)
                except Exception as exc:
                    # lowering unavailable (or the byte counter refused,
                    # e.g. an unlisted dtype): the arithmetic model stands
                    # in for 'auto'; 'measured' surfaces the cause below.
                    # Warn (not silent — ADVICE r4): the HLO figure is the
                    # advertised default, so a regression in the
                    # measurement path must be visible to 'auto' callers,
                    # not only on an explicit source='measured' probe.
                    self._halo_hlo_err = exc
                    warnings.warn(
                        "halo_bytes_per_gen: HLO measurement failed "
                        f"({type(exc).__name__}: {exc}); serving the "
                        "arithmetic model", RuntimeWarning, stacklevel=2)
            if self._halo_hlo is not None:
                return self._halo_hlo
            if source == "measured":
                raise RuntimeError(
                    "HLO measurement of the sharded one-generation step "
                    "failed on this platform; use source='model'"
                ) from self._halo_hlo_err
        nx = self.mesh.shape[mesh_lib.ROW_AXIS]
        ny = self.mesh.shape[mesh_lib.COL_AXIS]
        h, w = self.shape
        wq = (w // bitpack.WORD) if self._packed else w
        itemsize = 4 if self._packed else 1
        depth = self.rule.radius if self._ltl else 1  # strip depth in rows/cols
        g = self.gens_per_exchange
        wrap = self.topology is Topology.TORUS
        if self.backend == "pallas":
            # band-kernel path: the mesh flattens into nb full-width row
            # bands; per chunk each band ppermutes depth-(r·g) row strips
            # of the full packed width (× b planes stacked for
            # Generations), no column phase — then amortized over the g
            # generations the chunk advances. On (nx, 1) meshes this is
            # identical to the per-family branches below with their
            # column sends zeroed; on 2D meshes it is the only correct
            # model (the width is not sharded).
            nb = nx * ny
            if nb == 1:
                return 0
            b = 1
            if self._gen_packed:
                from .ops.packed_generations import n_planes

                b = n_planes(self.rule.states)
            strip = b * depth * g * (w // bitpack.WORD) * 4
            sends = 2 * (nb if wrap else nb - 1)
            return -(-sends * strip // g)  # ceil: per-generation figure
        if self._ltl_packed:
            # r halo rows of packed words + ONE halo word per side
            # (32 >= r cells), on a (h + 2r)-row-extended tile; the band
            # kernel (g > 1) ships r·g-deep strips once per chunk — the
            # per-chunk figure here, amortized /g below, lands back on the
            # same r rows/generation as the per-gen runner
            row_strip = depth * g * (wq // ny) * itemsize
            col_strip = (h // nx + 2 * depth * g) * itemsize
        elif self._gen_packed:
            # b uint32 bit-planes, each with depth-row / 1-word halos; the
            # band kernel (g > 1) ships g-deep plane strips once per chunk
            # — per-chunk figure here, amortized /g below (same shape as
            # the LtL branch above). ``depth`` > 1 is the multi-state LtL
            # plane stack (r halo rows per side, one stacked trip)
            from .ops.packed_generations import n_planes

            b = n_planes(self.rule.states)
            wq = w // bitpack.WORD
            itemsize = 4
            row_strip = b * depth * g * (wq // ny) * itemsize
            col_strip = b * (h // nx + 2 * depth * g) * itemsize
        elif g > 1:
            # communication-avoiding runner: one exchange of g-deep row
            # strips + ceil(g/32)-word column strips per g generations,
            # amortized (the ghost pipeline widens the word halo past
            # g = 32; the deep fallback is always 1 word, same formula)
            hw = mesh_lib.ghost_halo_words(g) if self._ghost_pipeline else 1
            row_strip = g * (wq // ny) * itemsize
            col_strip = hw * (h // nx + 2 * g) * itemsize
        else:
            row_strip = depth * (wq // ny) * itemsize  # d rows of one tile
            # d columns of a row-extended (h + 2d rows) tile
            col_strip = depth * (h // nx + 2 * depth) * itemsize
        # a size-1 axis exchanges nothing over the interconnect (the torus
        # "send" is a device-local self-copy); DEAD edges drop the wrap send
        row_sends = 2 * ny * (nx if wrap else nx - 1) if nx > 1 else 0
        col_sends = 2 * nx * (ny if wrap else ny - 1) if ny > 1 else 0
        total = row_sends * row_strip + col_sends * col_strip
        if g > 1:
            # per-generation figure: the chunk's bytes spread over g gens
            # (n % g remainder generations pay the 1-deep rate; ignored —
            # this is an estimate, and bulk stepping dominates)
            total = -(-total // g)  # ceil
        if self._flags is not None:
            # sharded sparse also halo-exchanges its uint32 activity map:
            # per-device (1, 1) flags cost 4-byte row / 12-byte col strips;
            # the tiled map's strips scale with the local tile-map dims
            # and, for radius-r rules, with the tile-ring wake radius
            if getattr(self, "_sparse_tiles", None):
                from .ops.sparse import _wake_dilation

                fy, fx = self._flags.shape
                dy, dx = _wake_dilation(self.rule, *self._sparse_tiles)
            else:
                (fy, fx), (dy, dx) = (nx, ny), (1, 1)
            total += (row_sends * dy * (fx // ny) * 4
                      + col_sends * dx * (fy // nx + 2 * dy) * 4)
        return total

    def runner_cost_analysis(self, gens: int = 8) -> Optional[dict]:
        """XLA's static cost analysis of THIS engine's compiled runner —
        the FLOPs and HBM bytes one ``gens``-generation dispatch costs,
        straight from ``Compiled.cost_analysis()`` (no arithmetic model,
        no hand-maintained constants). Feeds the RunReport's roofline
        section (obs/device.py). One extra lowering+compile the first
        time (served by the persistent cache on repeats), cached for the
        engine's lifetime; None for the sparse backend (its on-device
        while-loop cost depends on activity, a static figure would lie)
        and on platforms whose compiler refuses the query.
        """
        if self._sparse is not None:
            return None
        cache = getattr(self, "_cost_analysis_cache", None)
        if cache is None:
            cache = self._cost_analysis_cache = {}
        if gens in cache:
            return cache[gens]
        result = None
        try:
            with warnings.catch_warnings():
                # inner runners donate their args; under this outer
                # non-donating jit that degrades to a (correct) copy and
                # a donation warning we don't want surfaced per report
                warnings.simplefilter("ignore")
                # this jit exists only to be lowered for cost_analysis —
                # it is never dispatched, so no step time can hide in it
                # goltpu: ignore[GOL006] -- introspection-only lower/compile, never dispatched
                compiled = jax.jit(
                    lambda s: self._run(s, gens)).lower(self.state).compile()
                ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                result = {
                    "generations": gens,
                    "flops": float(ca["flops"]) if ca.get("flops") else None,
                    "bytes_accessed": (float(ca["bytes accessed"])
                                       if ca.get("bytes accessed") else None),
                }
        except Exception:
            result = None
        cache[gens] = result
        return result

    def active_tiles(self) -> Optional[int]:
        """Active-tile count of a sparse engine — the compute actually
        paid per generation, the observability number that explains why a
        65536² gun universe is cheap. None for non-sparse backends (and
        for the per-device-flag sparse runner, whose wake granularity is
        a whole shard, not tiles). Sharded tiled engines sum the
        distributed activity map (one device reduction)."""
        with _sanitizers.allow_host_transfers(
                "active-tile count is an explicit observability readback"):
            if self._sparse is not None:
                return self._sparse.active_tiles()
            if self._flags is not None and getattr(self, "_sparse_tiles",
                                                   None):
                return int(jnp.sum(self._flags))
            return None

    def population(self) -> int:
        """Exact live-cell count (device-side popcount, host-side total).

        For multi-state families (Generations; LtL with C >= 3) only
        state 1 is *alive* — dying states occupy space but are not
        population (they do not excite neighbors)."""
        with _sanitizers.allow_host_transfers(
                "population is an explicit scalar readback (device-side "
                "popcount, one host total)"):
            if self._packed:
                return bitpack.population(self.state)
            if self._gen_packed:
                from .ops.packed_generations import (
                    population_packed_generations,
                )

                return population_packed_generations(self.state)
            multistate = getattr(self.rule, "states", 2) > 2
            cells = (self._state == 1) if multistate else self._state
            return int(np.asarray(
                jnp.sum(cells, axis=-1, dtype=jnp.uint32)).sum())

    # -- state injection (checkpoint restore, pattern editing) ---------------

    def _validate_states(self, np_grid: np.ndarray) -> None:
        top = int(np_grid.max()) if np_grid.size else 0
        # one rule for every family: Generations and multi-state LtL carry
        # rule.states; binary families allow {0, 1}
        nstates = getattr(self.rule, "states", 2)
        if top >= nstates:
            raise ValueError(
                f"grid holds state {top} but rule {self.rule.notation} "
                + (f"has only states 0..{nstates - 1}" if nstates > 2
                   else "is binary: cells must be 0 or 1")
            )

    def set_grid(self, grid, generation: Optional[int] = None) -> None:
        np_grid = np.asarray(grid, dtype=np.uint8)
        self._validate_states(np_grid)
        # copy=True: same freed-seed hazard as __init__ — the restored
        # state must not alias the caller's host buffer (donation writes
        # through it for the rest of the run)
        grid = jnp.array(np_grid, copy=True)
        if tuple(grid.shape) != self.shape:
            raise ValueError(f"grid shape {grid.shape} != engine shape {self.shape}")
        if self._gen_packed:
            from .ops.packed_generations import pack_generations_for

            state = pack_generations_for(grid, self.rule)
        else:
            state = bitpack.pack(grid) if self._packed else grid
        if self.mesh is not None:
            state = mesh_lib.device_put_sharded_grid(state, self.mesh,
                                                     banded=self._banded)
        if self._sparse is not None:
            self._sparse = self._sparse.reseed(state)
        else:
            self._state = state
        if self._flags is not None:
            if getattr(self, "_sparse_tiles", None):
                tr, tw = self._sparse_tiles  # re-seed from the new grid
                self._flags = sharded.initial_tile_activity(
                    state, self.mesh, tr, tw)
            else:
                self._flags = sharded.initial_flags(self.mesh)  # wake all
        if generation is not None:
            self.generation = generation


from .ops._jit import tracked_jit


@tracked_jit(runner="_block_max", static_argnums=(1, 2))
def _block_max(x: jax.Array, fh: int, fw: int) -> jax.Array:
    h, w = x.shape
    # pad up to a block multiple (zeros are dead cells) so edge rows/columns
    # land in a partial block instead of being cropped away
    ph, pw = -h % fh, -w % fw
    if ph or pw:
        x = jnp.pad(x, ((0, ph), (0, pw)))
    return (
        x.reshape((h + ph) // fh, fh, (w + pw) // fw, fw)
        .max(axis=(1, 3))
    )


def _downsample_max(dense: jax.Array, max_shape: Tuple[int, int]) -> jax.Array:
    """Block-max pool so any live cell keeps its block lit (a renderer that
    averaged would fade sparse patterns like a lone glider to nothing)."""
    h, w = dense.shape
    mh, mw = max_shape
    fh, fw = max(1, -(-h // mh)), max(1, -(-w // mw))
    if fh == 1 and fw == 1:
        return dense
    return _block_max(dense, fh, fw)
