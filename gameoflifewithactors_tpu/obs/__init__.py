"""Run-telemetry subsystem: spans, compile events, metrics, watchdog, report.

The reference's only observability was its console renderer [ABSENT];
this package is the layer every perf/robustness claim reports through
(ROADMAP north star: no further perf work can be trusted without it).
Three pillars:

- **Span tracer** (:mod:`.spans`): nested named host-side spans with a
  context-manager API, thread-safe, exportable as chrome://tracing JSON
  (loadable in ui.perfetto.dev *alongside* a ``jax.profiler`` device
  trace — see README "Observability") and JSONL. The engine, coordinator
  and scheduler are instrumented, so dispatch vs. sync vs. readback vs.
  subscriber time is separable without a trace viewer.
- **Compile-event tracker + metrics registry** (:mod:`.compile`,
  :mod:`.registry`): every jit entry point in ``ops/_jit.py`` reports
  which runner compiled, its shape/dtype signature and wall seconds —
  so first-tick compile time stops masquerading as step time in
  ``StepMetrics`` — plus labeled counters/gauges/histograms for
  anything else worth counting.
- **Stall watchdog + RunReport** (:mod:`.watchdog`, :mod:`.report`):
  a monitor thread flags ticks exceeding a deadline and names the
  last-completed span (aimed at the wedged-TPU-probe failure mode,
  BENCH_r05.json), and :class:`RunReport` folds spans, compile events,
  ``StepMetrics``, halo-byte figures and (when a trace exists)
  ``perfetto_summary`` duty cycle into one JSON artifact — wired into
  ``bench.py`` and the CLI (``--telemetry-out``, ``report`` subcommand).

ISSUE-3 adds the *continuous* layer on top — what is the device doing
right now, and did the last change regress us:

- **Device sampler + roofline** (:mod:`.device`): a background poller
  folding ``memory_stats()`` into registry gauges, and XLA
  cost-analysis-based roofline attribution in the RunReport.
- **Prometheus exposition** (:mod:`.exporter`): the registry served as
  scrape-able text over a stdlib HTTP thread (CLI ``--serve-metrics``).
- **Flight recorder** (:mod:`.flight`): ring buffers of the last N
  StepMetrics / spans / compile events, dumped as a JSONL crash report
  on watchdog stall, coordinator-loop exception, or SIGTERM/SIGINT.
- **Report differ** (:mod:`.diff`): per-metric tolerance-banded deltas
  between two RunReports/bench records — the comparator under
  ``scripts/perf_gate.py`` and ``report --diff``, honoring the PR-2
  staleness flags (a stale record gates as "skipped", never "ok").

No module in this package imports jax at module scope (device/engine
lookups are lazy, inside the calls that need them), mirroring how
bench.py loads utils/provenance.py standalone: recorders and report
plumbing must stay loadable and usable while a TPU tunnel is wedged —
that is precisely when their output matters most.
"""

from .spans import (  # noqa: F401
    Span,
    SpanTracer,
    TRACER,
    TRACE_ENV_VAR,
    TraceContext,
    bind_trace,
    current_trace,
    new_span_id,
    new_trace_id,
    parse_trace_header,
    set_process_context,
    span,
)
from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
)
from .compile import (  # noqa: F401
    CompileEvent,
    CompileEventLog,
    COMPILE_LOG,
    tracked_call,
)
from .watchdog import StallEvent, StallWatchdog, active_watchdog, arm, disarm  # noqa: F401
from .report import RunReport, RunTelemetry, begin_run_telemetry  # noqa: F401
from .device import DeviceSampler, roofline_section  # noqa: F401
from .profiler import (  # noqa: F401
    OP_CLASSES,
    ProfileSampler,
    active_sampler,
    classify_slice,
    dispatch_annotation,
)
from .exporter import MetricsServer, render_prometheus, serve_metrics  # noqa: F401
from .flight import FlightRecorder, active_flight_recorder, load_dump  # noqa: F401
from .diff import diff_records, format_rows, gate  # noqa: F401

__all__ = [
    "Span", "SpanTracer", "TRACER", "span",
    "TRACE_ENV_VAR", "TraceContext", "bind_trace", "current_trace",
    "new_span_id", "new_trace_id", "parse_trace_header",
    "set_process_context",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "CompileEvent", "CompileEventLog", "COMPILE_LOG", "tracked_call",
    "StallEvent", "StallWatchdog", "active_watchdog", "arm", "disarm",
    "RunReport", "RunTelemetry", "begin_run_telemetry",
    "DeviceSampler", "roofline_section",
    "OP_CLASSES", "ProfileSampler", "active_sampler", "classify_slice",
    "dispatch_annotation",
    "MetricsServer", "render_prometheus", "serve_metrics",
    "FlightRecorder", "active_flight_recorder", "load_dump",
    "diff_records", "format_rows", "gate",
]
