"""Flight recorder: a bounded black box that dumps on trouble.

The wedged-probe failure mode (results/watch_r05.log) is a run that dies
with *zero* artifacts: the watchdog's stderr line is the only witness,
and a SIGTERM from a subprocess harness leaves nothing at all. The
flight recorder keeps ring buffers of the last N ``StepMetrics``, span
completions, and compile events (tapped live off the process recorders —
a later ``TRACER.clear()`` cannot erase what was already taped), and on

- a watchdog stall (chained via ``StallWatchdog.add_on_stall``),
- an unhandled exception escaping the coordinator tick loop, or
- SIGTERM / SIGINT

writes one JSONL crash report: a header line naming the trigger and the
last completed span, then the taped records, then a full metrics-registry
snapshot. The dump path is pre-opened-directory cheap (one atomic
``os.replace``), and dumping is idempotent per trigger but repeatable —
a stall dump followed by the SIGTERM dump overwrites with strictly more
recent tape.

Like the watchdog, a process-default recorder can be armed
(:func:`arm`) so ``GridCoordinator.tick`` finds it without plumbing.
Stdlib only; must stay importable and dumpable while jax is wedged.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Deque, List, Optional

from . import compile as compile_lib
from . import spans as spans_lib
from .registry import REGISTRY

DEFAULT_MAX_RECORDS = 256
SCHEMA_VERSION = 1


class FlightRecorder:
    """Tape the last N telemetry records; dump a crash report on demand."""

    def __init__(self, path: str, *, max_records: int = DEFAULT_MAX_RECORDS,
                 registry=REGISTRY,
                 tracer: Optional[spans_lib.SpanTracer] = None,
                 compile_log: Optional[compile_lib.CompileEventLog] = None):
        self.path = path
        self.registry = registry
        self._tracer = tracer or spans_lib.TRACER
        self._compile_log = compile_log or compile_lib.COMPILE_LOG
        self._steps: Deque[dict] = collections.deque(maxlen=max_records)
        self._spans: Deque[dict] = collections.deque(maxlen=max_records)
        self._compiles: Deque[dict] = collections.deque(maxlen=max_records)
        self._notes: Deque[dict] = collections.deque(maxlen=max_records)
        self._stalls: List[dict] = []
        self._lock = threading.Lock()
        self._installed = False
        self._watchdog = None
        self._prev_handlers: dict = {}
        self.dumps = 0
        self.last_dump_reason: Optional[str] = None

    # -- the tape (each is safe from any thread) -----------------------------

    def on_step(self, m) -> None:
        """StepMetrics sink — hang on a MetricsLogger next to the
        RunTelemetry buffer."""
        with self._lock:
            self._steps.append(m if isinstance(m, dict) else m.to_dict())

    def on_span(self, s) -> None:
        with self._lock:
            self._spans.append(s if isinstance(s, dict) else s.to_dict())

    def on_compile(self, ev) -> None:
        with self._lock:
            self._compiles.append(
                ev if isinstance(ev, dict) else ev.to_dict())

    def on_stall(self, ev) -> None:
        with self._lock:
            self._stalls.append(ev if isinstance(ev, dict) else ev.to_dict())
        self.dump(f"watchdog stall: {getattr(ev, 'label', '?')}")

    def note(self, kind: str, payload: Optional[dict] = None) -> None:
        """Tape a free-form event (fault injections, supervisor restarts —
        the resilience layer's breadcrumbs). Rides the same ring buffer
        discipline as the telemetry tapes and lands in every dump, so a
        crash report shows *what was done to* the run, not only what the
        run measured."""
        rec = {"kind": kind, "t": time.perf_counter()}
        if payload:
            rec.update(payload)
        with self._lock:
            self._notes.append(rec)

    # -- wiring --------------------------------------------------------------

    def install(self, *, watchdog=None, signals: bool = True) -> "FlightRecorder":
        """Tap the process recorders; optionally chain onto a watchdog's
        stall sink and take over SIGTERM/SIGINT (dump, then hand the
        signal on to whatever handler was there — default die included).
        Signal handlers only install from the main thread; elsewhere the
        tape still runs, just without the signal trigger."""
        # wiring state under the lock (goltpu-lint GOL004): install/
        # uninstall can race the signal handler and a second arm() call,
        # and the check-then-set on _installed was a classic TOCTOU
        with self._lock:
            if self._installed:
                return self
            self._installed = True
            if watchdog is not None:
                self._watchdog = watchdog
        self._tracer.add_listener(self.on_span)
        self._compile_log.add_listener(self.on_compile)
        if watchdog is not None:
            watchdog.add_on_stall(self.on_stall)
        if signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    prev = signal.getsignal(sig)
                    signal.signal(sig, self._on_signal)
                except (ValueError, OSError):  # not the main thread
                    continue
                with self._lock:
                    self._prev_handlers[sig] = prev
        return self

    def uninstall(self) -> None:
        with self._lock:
            if not self._installed:
                return
            self._installed = False
            watchdog, self._watchdog = self._watchdog, None
            prev_handlers = dict(self._prev_handlers)
            self._prev_handlers.clear()
        self._tracer.remove_listener(self.on_span)
        self._compile_log.remove_listener(self.on_compile)
        if watchdog is not None:
            watchdog.remove_on_stall(self.on_stall)
        for sig, prev in prev_handlers.items():
            try:
                if signal.getsignal(sig) == self._on_signal:
                    signal.signal(sig, prev)
            except (ValueError, OSError):
                pass

    def _on_signal(self, signum, frame) -> None:
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        self.dump(f"signal {name}")
        _continue_previous(self._prev_handlers.get(signum), signum, frame)

    # -- the crash report ----------------------------------------------------

    def dump(self, reason: str, extra: Optional[dict] = None) -> str:
        """Write the JSONL crash report (atomic replace). Returns the
        path. Never raises — a dump failure at crash time must not mask
        the crash itself; it falls back to a stderr line."""
        with self._lock:
            steps = list(self._steps)
            spans = list(self._spans)
            compiles = list(self._compiles)
            notes = list(self._notes)
            stalls = list(self._stalls)
        last = self._tracer.last_completed()
        ctx = spans_lib.current_trace()
        header = {
            "type": "flight",
            "schema_version": SCHEMA_VERSION,
            "reason": reason,
            "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "pid": os.getpid(),
            # this process's wall-clock <-> perf_counter anchor (written at
            # tracer startup): obs/aggregate.py aligns per-process tapes
            # onto one timeline by adding it to every t/t0 in the dump
            "epoch_anchor": self._tracer.epoch_anchor,
            "trace_id": ctx.trace_id if ctx is not None else None,
            "last_completed_span": last.name if last else None,
            "open_spans": self._tracer.current_stack(),
            "counts": {"step_metrics": len(steps), "spans": len(spans),
                       "compile_events": len(compiles),
                       "events": len(notes), "stalls": len(stalls)},
        }
        if extra:
            header.update(extra)
        try:
            # per-thread tmp name: a signal-handler dump racing the
            # watchdog thread's dump must not interleave one tmp file
            tmp = f"{self.path}.tmp{threading.get_ident()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(header) + "\n")
                for kind, records in (("step_metrics", steps),
                                      ("span", spans),
                                      ("compile_event", compiles),
                                      ("event", notes),
                                      ("stall", stalls)):
                    for rec in records:
                        f.write(json.dumps({"type": kind, **rec}) + "\n")
                f.write(json.dumps({"type": "registry",
                                    "snapshot": self.registry.snapshot()})
                        + "\n")
            os.replace(tmp, self.path)
        except Exception as exc:
            sys.stderr.write(
                f"flight recorder: dump to {self.path} failed "
                f"({type(exc).__name__}: {exc})\n")
            return self.path
        self.dumps += 1
        self.last_dump_reason = reason
        sys.stderr.write(
            f"flight recorder: dumped ({reason}) -> {self.path}\n")
        return self.path


def _continue_previous(prev, signum, frame) -> None:
    """Hand a handled signal on to the disposition that was installed
    before us — the one chaining rule every SIGTERM hook in this
    codebase must follow (FlightRecorder.install, the serve loop, the
    soak worker). A callable previous handler is called; SIG_DFL is
    restored and the signal re-raised so the process still dies with the
    right disposition (a harness watching the exit status must see
    SIGTERM, not a clean exit); SIG_IGN / None swallow it."""
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_DFL:
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def chain_signal_handler(sig, fn, *, propagate: bool = True):
    """Install ``fn(signum, frame)`` for ``sig`` WITHOUT dropping the
    handler already there: ``fn`` runs first, then (with ``propagate``)
    the previous disposition via :func:`_continue_previous`.

    This is the fix for the two-installers hazard: a later component
    calling raw ``signal.signal`` silently discards whatever hook was
    installed before it — e.g. the serve loop's graceful-shutdown hook
    replacing the flight recorder's dump-on-SIGTERM (or vice versa),
    losing either the crash dump or the final checkpoint. Every
    additional SIGTERM/SIGINT hook should install through here (or
    through ``FlightRecorder.install``, which follows the same rule).

    Returns an ``uninstall()`` callable that restores the previous
    handler — only if the chained one is still installed, the same
    steal-safe discipline as ``FlightRecorder.uninstall``.
    """
    prev = signal.getsignal(sig)

    def handler(signum, frame):
        fn(signum, frame)
        if propagate:
            _continue_previous(prev, signum, frame)

    signal.signal(sig, handler)

    def uninstall() -> None:
        try:
            if signal.getsignal(sig) is handler:
                signal.signal(sig, prev)
        except (ValueError, OSError):
            pass

    return uninstall


def load_dump(path: str) -> dict:
    """Parse a dump back into {"flight": header, "step_metrics": [...],
    "span": [...], "compile_event": [...], "event": [...], "stall":
    [...], "registry": snapshot} — the reader tests and post-mortem
    tooling use."""
    out: dict = {"step_metrics": [], "span": [], "compile_event": [],
                 "event": [], "stall": []}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.pop("type", None)
            if kind == "flight":
                out["flight"] = rec
            elif kind == "registry":
                out["registry"] = rec.get("snapshot", {})
            elif kind in out:
                out[kind].append(rec)
    return out


# -- process-default arming (how the coordinator finds the recorder) ----------

_ACTIVE: Optional[FlightRecorder] = None


def arm(fr: FlightRecorder) -> FlightRecorder:
    """Make ``fr`` the process-default recorder (installed) and return it."""
    global _ACTIVE
    _ACTIVE = fr.install()
    return fr


def disarm() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.uninstall()
    _ACTIVE = None


def active_flight_recorder() -> Optional[FlightRecorder]:
    return _ACTIVE


def note_event(kind: str, payload: Optional[dict] = None) -> None:
    """Tape an event onto the armed recorder; silently a no-op when none
    is armed — call sites (fault injectors, the supervisor) must not
    need to know whether a flight recorder exists."""
    fr = _ACTIVE
    if fr is not None:
        fr.note(kind, payload)
