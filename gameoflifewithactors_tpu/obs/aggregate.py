"""Fleet aggregation: many per-process telemetry sources, one view.

PRs 11-14 made the runtime a fleet, but every ``obs`` artifact stayed
per-process: each worker serves its own ``/metrics``, tapes its own
spans, dumps its own flight recording. This module is the merge layer:

- **Metrics**: :func:`merge_expositions` folds N Prometheus expositions
  into one, tagging every series with a ``proc`` label so per-worker
  series stay distinct. There is deliberately NO automatic summing —
  the COST paper's complaint is fleet totals hiding per-chip
  regressions, so per-chip throughput/capacity gauges
  (:data:`PER_CHIP_GAUGES`) *refuse* to be summed
  (:func:`sum_across_procs` raises :class:`PerChipSumError`).
  :class:`FleetAggregator` scrapes live endpoints with a TTL cache;
  :class:`AggregatorServer` re-exports the merged view over HTTP.
- **Timelines**: :func:`write_merged_timeline` merges per-process span
  tapes (chrome-trace JSON) and flight dumps into ONE clock-aligned
  chrome-trace file. Alignment uses the wall-clock↔perf_counter anchor
  every process writes at startup (``SpanTracer.epoch_anchor``, carried
  in each flight-dump header): ``wall = perf_counter + anchor``, so
  tapes from processes whose perf_counter origins differ by minutes
  land on one monotonic epoch timeline. Flight-dump trigger headers are
  preserved verbatim under ``flight_headers``.

Stdlib only, no jax — post-mortem merging must work on a machine where
the accelerator stack is wedged (that is when it is needed).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, List, Optional, Tuple

from . import flight as flight_lib
from .exporter import CONTENT_TYPE, PREFIX

#: Gauges whose value is a property of ONE chip/process — a cross-proc
#: sum is dimensionally wrong (summed HBM "in use" exceeds any real
#: chip; summed per-chip rates hide a straggler behind a healthy total).
#: The COST honesty check: these may be listed side by side, never added.
PER_CHIP_GAUGES = frozenset({
    "hbm_bytes_in_use", "hbm_bytes_peak", "hbm_bytes_limit",
    "tenant_steps_per_sec", "worker_steps_per_sec",
    "cell_updates_per_sec",
    # overlap efficiency is a ratio of one chip's block schedule; a
    # fleet "sum of ratios" is meaningless. The halo *totals*
    # (halo_bytes_total, halo_exchanges_total) are counters and sum.
    "halo_overlap_ratio",
    # same discipline for the sampling profiler's measured figures
    # (ISSUE 18): one chip's overlap ratio, attribution share, duty
    # cycle and overhead are per-chip ratios. The attributed
    # device-second *totals* (profile_op_class_seconds_total) are
    # counters and sum.
    "halo_overlap_ratio_measured",
    "profile_op_class_fraction",
    "profile_duty_cycle",
    "profile_overhead_ratio",
})


class PerChipSumError(ValueError):
    """Raised when asked to sum a per-chip gauge across processes."""


# -- exposition text parsing --------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> dict:
    """Prometheus text format -> ``{"types": {name: type}, "help":
    {name: help}, "samples": [(name, labels dict, value)]}``.

    ``name`` keeps its ``_bucket``/``_sum``/``_count`` suffix; ``types``
    and ``help`` are keyed by the family name from the ``# TYPE`` /
    ``# HELP`` lines. Tolerant of unparsable lines (skipped) — a
    half-written scrape must not kill the aggregate view."""
    out: dict = {"types": {}, "help": {}, "samples": []}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                out["types"][parts[2]] = parts[3] if len(parts) > 3 else ""
            elif len(parts) >= 3 and parts[1] == "HELP":
                out["help"][parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, rawlabels, rawval = m.groups()
        try:
            value = float(rawval)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(rawlabels or "")}
        out["samples"].append((name, labels, value))
    return out


def base_name(sample_name: str) -> str:
    """Family name of a sample: strips the exporter prefix and the
    histogram ``_bucket``/``_sum``/``_count`` suffix."""
    name = sample_name
    if name.startswith(PREFIX):
        name = name[len(PREFIX):]
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _render_labels(labels: dict) -> str:
    if not labels:
        return ""
    return ("{"
            + ",".join(f'{k}="{_escape(str(v))}"'
                       for k, v in sorted(labels.items()))
            + "}")


def merge_expositions(per_proc: Dict[str, str]) -> str:
    """N expositions (``proc label -> text``) -> one, every series tagged
    ``proc="<label>"``. Series are *preserved*, never summed — the
    per-chip view survives the merge by construction. A source series
    already carrying a ``proc`` label raises: silently overwriting the
    provenance label would forge per-worker attribution."""
    families: Dict[str, dict] = {}
    for proc in sorted(per_proc):
        parsed = parse_exposition(per_proc[proc])
        for name, labels, value in parsed["samples"]:
            if "proc" in labels:
                raise ValueError(
                    f"series {name} from {proc!r} already has a proc label "
                    f"({labels['proc']!r}); refusing to relabel")
            fam_match = [f for f in parsed["types"]
                         if name == f or (name.startswith(f) and
                                          name[len(f):] in
                                          ("_bucket", "_sum", "_count"))]
            fam_name = max(fam_match, key=len) if fam_match else name
            fam = families.setdefault(fam_name, {
                "type": parsed["types"].get(fam_name, "untyped"),
                "help": parsed["help"].get(fam_name, ""),
                "samples": []})
            fam["samples"].append((name, {**labels, "proc": proc}, value))
    out: List[str] = []
    for fam_name in sorted(families):
        fam = families[fam_name]
        if fam["help"]:
            out.append(f"# HELP {fam_name} {fam['help']}")
        out.append(f"# TYPE {fam_name} {fam['type']}")
        for name, labels, value in fam["samples"]:
            sval = str(int(value)) if value == int(value) and \
                abs(value) < 1e15 else repr(value)
            out.append(f"{name}{_render_labels(labels)} {sval}")
    return "\n".join(out) + ("\n" if out else "")


def series_across_procs(per_proc: Dict[str, str], name: str
                        ) -> List[Tuple[str, dict, float]]:
    """All samples of a family across processes, as ``(proc, labels,
    value)`` — the honest (unsummed) per-chip view."""
    rows = []
    for proc in sorted(per_proc):
        for sname, labels, value in parse_exposition(
                per_proc[proc])["samples"]:
            if base_name(sname) == name:
                rows.append((proc, labels, value))
    return rows


def sum_across_procs(per_proc: Dict[str, str], name: str) -> float:
    """Sum a family's plain samples across the fleet — REFUSED for
    per-chip gauges (:class:`PerChipSumError`): a summed per-chip rate
    is the COST paper's configuration-that-outperforms-nothing. Use
    :func:`series_across_procs` for those instead."""
    if name in PER_CHIP_GAUGES:
        raise PerChipSumError(
            f"{name!r} is a per-chip gauge; summing it across processes "
            "fabricates a fleet number no chip ever produced — read the "
            "per-proc series via series_across_procs() instead")
    total = 0.0
    for proc in sorted(per_proc):
        for sname, _labels, value in parse_exposition(
                per_proc[proc])["samples"]:
            # plain samples only: histogram _bucket/_sum/_count triplets
            # must not be folded into one number
            if sname in (name, PREFIX + name):
                total += value
    return total


# -- live scraping ------------------------------------------------------------

class FleetAggregator:
    """Scrape N ``/metrics`` endpoints into one labeled view.

    ``targets`` maps a ``proc`` label to a base URL
    (``{"w0": "http://127.0.0.1:9001"}``) or a bare ``host:port``. A
    short TTL cache (``ttl_seconds``) coalesces concurrent pollers —
    ``fleet_top`` at 2 Hz and a scraped ``AggregatorServer`` must not
    multiply load on the workers. Cache access is lock-disciplined
    (goltpu-lint GOL007); the HTTP fetches themselves run outside the
    lock so one slow worker cannot serialize every reader."""

    def __init__(self, targets: Dict[str, str], *,
                 ttl_seconds: float = 1.0, timeout_seconds: float = 2.0):
        self.targets = {
            proc: (url if "//" in url else f"http://{url}")
            for proc, url in targets.items()}
        self.ttl_seconds = float(ttl_seconds)
        self.timeout_seconds = float(timeout_seconds)
        self._lock = threading.Lock()
        # (perf_counter stamp, {proc: exposition text or None})
        self._cache: Optional[Tuple[float, Dict[str, Optional[str]]]] = None

    def _fetch(self, url: str) -> Optional[str]:
        try:
            with urllib.request.urlopen(
                    url + "/metrics", timeout=self.timeout_seconds) as resp:
                return resp.read().decode("utf-8", "replace")
        except Exception:
            return None  # a down worker is a row in the view, not a crash

    def scrape(self, *, force: bool = False) -> Dict[str, Optional[str]]:
        """``proc -> exposition text`` (``None`` for unreachable
        workers). Served from the TTL cache when fresh."""
        now = time.perf_counter()
        with self._lock:
            cached = self._cache
        if (not force and cached is not None
                and now - cached[0] < self.ttl_seconds):
            return dict(cached[1])
        texts = {proc: self._fetch(url)
                 for proc, url in sorted(self.targets.items())}
        with self._lock:
            self._cache = (time.perf_counter(), texts)
        return dict(texts)

    def up(self) -> Dict[str, bool]:
        return {proc: text is not None
                for proc, text in self.scrape().items()}

    def render(self) -> str:
        """The merged exposition (down workers omitted — absence, not a
        forged zero)."""
        return merge_expositions({proc: text
                                  for proc, text in self.scrape().items()
                                  if text is not None})

    def view(self) -> Dict[str, Optional[dict]]:
        """``proc -> parse_exposition(...)`` (``None`` when down)."""
        return {proc: (parse_exposition(text) if text is not None else None)
                for proc, text in self.scrape().items()}


class AggregatorServer:
    """The fleet's aggregate endpoint: ``/metrics`` re-exports the
    merged exposition, ``/fleet`` answers a JSON liveness map. A thin
    HTTP face over a :class:`FleetAggregator` (same stdlib daemon-thread
    shape as ``MetricsServer``)."""

    def __init__(self, aggregator: FleetAggregator, port: int = 0, *,
                 host: str = "127.0.0.1"):
        self.aggregator = aggregator
        self.requested_port = int(port)
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "AggregatorServer":
        if self._httpd is not None:
            return self
        agg = self.aggregator

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path in ("/metrics", "/"):
                    body = agg.render().encode("utf-8")
                    ctype = CONTENT_TYPE
                elif path == "/fleet":
                    body = (json.dumps({"up": agg.up()}) + "\n"
                            ).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404, "try /metrics or /fleet")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-aggregator",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "AggregatorServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- timeline merge -----------------------------------------------------------

def _tid_allocator():
    mapping: Dict[Tuple[str, str], int] = {}

    def tid_for(proc: str, thread_name: str) -> int:
        key = (proc, thread_name)
        if key not in mapping:
            mapping[key] = len(mapping) + 1
        return mapping[key]

    return mapping, tid_for


def merge_flight_dumps(paths: Iterable[str],
                       labels: Optional[Dict[str, str]] = None) -> dict:
    """Flight dumps -> one clock-aligned chrome-trace object.

    Every span/event/stall timestamp is perf_counter seconds in its own
    process; the dump header's ``epoch_anchor`` (written at tracer
    startup) converts it to wall clock, so tapes from processes started
    minutes apart land in true order. Dumps without an anchor (pre-PR-16
    files) cannot be aligned and are listed under ``"unaligned"``
    instead of being placed at a fabricated time. Each dump's trigger
    header is preserved verbatim under ``"flight_headers"``."""
    labels = labels or {}
    meta_events: List[dict] = []
    events: List[dict] = []
    headers: Dict[str, dict] = {}
    unaligned: List[str] = []
    _mapping, tid_for = _tid_allocator()
    for i, path in enumerate(sorted(str(p) for p in paths)):
        dump = flight_lib.load_dump(path)
        hdr = dump.get("flight", {})
        label = labels.get(path) or _default_label(path)
        headers[label] = hdr
        anchor = hdr.get("epoch_anchor")
        if anchor is None:
            unaligned.append(label)
            continue
        pid = hdr.get("pid", 100000 + i)
        meta_events.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": f"{label} pid={pid} "
                             f"[{hdr.get('reason', '?')}]"}})
        seen_tids = set()
        for rec in dump.get("span", []):
            tid = tid_for(label, rec.get("thread", "main"))
            if tid not in seen_tids:
                seen_tids.add(tid)
                meta_events.append({
                    "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                    "args": {"name": rec.get("thread", "main")}})
            args = dict(rec.get("attrs") or {})
            for k in ("trace_id", "span_id", "parent_id"):
                if rec.get(k) is not None:
                    args[k] = rec[k]
            ev = {"ph": "X", "pid": pid, "tid": tid,
                  "name": rec.get("name", "?"),
                  "ts": (rec["t0"] + anchor) * 1e6,
                  "dur": max(0.0, rec["t1"] - rec["t0"]) * 1e6}
            if args:
                ev["args"] = args
            events.append(ev)
        for kind, name_prefix in (("event", ""), ("stall", "stall:")):
            for rec in dump.get(kind, []):
                t = rec.get("t")
                if t is None:
                    continue
                args = {k: v for k, v in rec.items()
                        if k not in ("t",) and _jsonable(v)}
                name = (name_prefix + str(rec.get("kind", rec.get(
                    "label", kind)))) if kind == "event" else \
                    name_prefix + str(rec.get("label", "?"))
                events.append({
                    "ph": "i", "s": "p", "pid": pid,
                    "tid": tid_for(label, rec.get("thread", "main")),
                    "name": name, "ts": (t + anchor) * 1e6,
                    "args": args})
    events.sort(key=lambda ev: ev["ts"])
    return {"traceEvents": meta_events + events,
            "displayTimeUnit": "ms",
            "flight_headers": headers,
            "unaligned": unaligned}


def _default_label(path: str) -> str:
    stem = path.rsplit("/", 1)[-1]
    return stem[:-6] if stem.endswith(".jsonl") else stem


def _jsonable(v) -> bool:
    return isinstance(v, (str, int, float, bool, type(None), list, dict))


def merge_timelines(traces: Iterable[dict]) -> dict:
    """Merge already-epoch-anchored chrome-trace objects (a live
    tracer's ``to_chrome_trace()``, or :func:`merge_flight_dumps`
    output) into one: metadata events first, timed events interleaved in
    epoch order. Extra top-level keys (``flight_headers`` etc.) are
    union-merged."""
    meta_events: List[dict] = []
    events: List[dict] = []
    extra: dict = {"flight_headers": {}, "unaligned": []}
    for trace in traces:
        for ev in trace.get("traceEvents", []):
            (meta_events if ev.get("ph") == "M" else events).append(ev)
        extra["flight_headers"].update(trace.get("flight_headers", {}))
        extra["unaligned"].extend(trace.get("unaligned", []))
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    out = {"traceEvents": meta_events + events, "displayTimeUnit": "ms"}
    if extra["flight_headers"]:
        out["flight_headers"] = extra["flight_headers"]
    if extra["unaligned"]:
        out["unaligned"] = extra["unaligned"]
    return out


def validate_timeline(trace: dict) -> List[str]:
    """Clock-alignment lint for a merged timeline: negative durations
    and out-of-epoch-order timed events. Empty list = clean — what the
    chaos drill asserts before calling its artifact evidence."""
    problems: List[str] = []
    last_ts = None
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") == "M":
            continue
        if ev.get("dur", 0.0) < 0.0:
            problems.append(
                f"negative duration on {ev.get('name')!r}: {ev['dur']}")
        ts = ev.get("ts")
        if last_ts is not None and ts is not None and ts < last_ts:
            problems.append(
                f"out-of-order event {ev.get('name')!r}: "
                f"ts {ts} after {last_ts}")
        if ts is not None:
            last_ts = max(last_ts, ts) if last_ts is not None else ts
    return problems


def write_merged_timeline(out_path: str, *,
                          flight_dumps: Iterable[str] = (),
                          chrome_traces: Iterable[dict] = (),
                          labels: Optional[Dict[str, str]] = None) -> str:
    """The post-mortem artifact: merge flight dumps and live tapes into
    one clock-aligned chrome-trace JSON at ``out_path`` (loadable in
    ui.perfetto.dev). Returns the path."""
    merged = merge_timelines(
        [merge_flight_dumps(flight_dumps, labels=labels),
         *chrome_traces])
    with open(out_path, "w") as f:
        json.dump(merged, f)
        f.write("\n")
    return out_path
