"""Prometheus text-format exposition of the metrics registry.

The registry (obs/registry.py) is deliberately Prometheus-shaped — name
+ label dict -> series — so exposition is a pure rendering step:
:func:`render_prometheus` turns a registry snapshot into the text format
(version 0.0.4) any Prometheus/VictoriaMetrics/Grafana-agent scraper
ingests, and :class:`MetricsServer` serves it from a stdlib
``http.server`` daemon thread so a long-running engine can be scraped
*while stepping* (``--serve-metrics PORT`` on the CLI,
``GOLTPU_METRICS_PORT`` env).

Every metric is exported under the ``goltpu_`` namespace with the name
sanitized to the Prometheus grammar; histograms export the canonical
``_bucket``/``_sum``/``_count`` triplet with cumulative ``le`` buckets.
Stdlib only, no jax anywhere — the endpoint must stay alive precisely
when the device backend is wedged.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from .registry import REGISTRY, MetricsRegistry

PREFIX = "goltpu_"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")


def _metric_name(name: str) -> str:
    s = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not _NAME_OK.fullmatch(s):
        s = "_" + s
    return PREFIX + s


def _label_name(name: str) -> str:
    s = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if not _LABEL_OK.fullmatch(s):
        s = "_" + s
    return s


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels(labels: dict, extra: Optional[List[tuple]] = None) -> str:
    pairs = [(_label_name(k), str(v)) for k, v in sorted(labels.items())]
    pairs += extra or []
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in pairs) + "}"


def _num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(snapshot: dict) -> str:
    """Registry snapshot (``MetricsRegistry.snapshot()``) -> exposition
    text. Deterministic ordering (sorted names, sorted labels) so the
    output is golden-testable."""
    out: List[str] = []
    for name in sorted(snapshot):
        inst = snapshot[name]
        mname = _metric_name(name)
        mtype = inst.get("type", "untyped")
        if inst.get("help"):
            out.append(f"# HELP {mname} {_escape(inst['help'])}")
        out.append(f"# TYPE {mname} {mtype}")
        if mtype == "histogram":
            uppers = [_num(b) for b in inst.get("buckets", [])] + ["+Inf"]
            for series in inst.get("series", []):
                labels = series.get("labels", {})
                cum = 0
                for upper, count in zip(uppers, series.get("counts", [])):
                    cum += count
                    out.append(
                        f"{mname}_bucket"
                        f"{_labels(labels, [('le', upper)])} {cum}")
                out.append(f"{mname}_sum{_labels(labels)}"
                           f" {_num(series.get('sum', 0.0))}")
                out.append(f"{mname}_count{_labels(labels)}"
                           f" {series.get('n', 0)}")
        else:
            for series in inst.get("series", []):
                out.append(f"{mname}{_labels(series.get('labels', {}))}"
                           f" {_num(series.get('value', 0.0))}")
    return "\n".join(out) + ("\n" if out else "")


class MetricsServer:
    """``/metrics`` over a stdlib HTTP daemon thread.

    ``MetricsServer(port).start()`` binds immediately (port 0 picks an
    ephemeral port — read it back from ``.port``); ``stop()`` shuts the
    thread down. ``/metrics`` renders the registry live per scrape;
    ``/healthz`` answers 200 with a one-line JSON heartbeat, merged with
    whatever ``health_info()`` returns — the soak driver's liveness +
    progress probe (a worker reports its generation/restart counts
    there, cheaper than parsing the full exposition). Also a context
    manager."""

    def __init__(self, port: int = 0, *,
                 registry: MetricsRegistry = REGISTRY,
                 host: str = "0.0.0.0",
                 health_info: Optional[Callable[[], dict]] = None):
        self.requested_port = int(port)
        self.host = host
        self.registry = registry
        self._health_info = health_info
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def set_health_info(self, health_info: Optional[Callable[[], dict]]
                        ) -> None:
        """Install or replace the /healthz info hook after construction.
        The serve layer starts the exporter first (scrapable during
        warmup) and wires its live session/lane/queue counts in once the
        session service exists; the handler reads the hook per request,
        so the swap needs no restart."""
        self._health_info = health_info

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        registry = self.registry
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?")[0]
                if path in ("/metrics", "/"):
                    body = render_prometheus(registry.snapshot()
                                             ).encode("utf-8")
                    ctype = CONTENT_TYPE
                elif path == "/healthz":
                    payload = {"ok": True}
                    health_info = srv._health_info  # late-bound per request
                    if health_info is not None:
                        try:
                            payload.update(health_info() or {})
                        except Exception:
                            # a broken info hook must not take the
                            # liveness probe down with it
                            payload["info_error"] = True
                    body = (json.dumps(payload) + "\n").encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404, "try /metrics")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes every few seconds must not spam stderr

        self._httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-exporter",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def serve_metrics(port: int, *, registry: MetricsRegistry = REGISTRY,
                  host: str = "0.0.0.0",
                  health_info: Optional[Callable[[], dict]] = None,
                  ) -> MetricsServer:
    """Start and return a :class:`MetricsServer` (CLI convenience)."""
    return MetricsServer(port, registry=registry, host=host,
                         health_info=health_info).start()
