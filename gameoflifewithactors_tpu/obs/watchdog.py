"""Stall watchdog: a monitor thread that flags ticks exceeding a deadline.

Aimed squarely at the wedged-TPU-probe failure mode (BENCH_r05.json): a
tunnel wedge shows up as a tick that never returns, and before this the
only diagnostic was a subprocess timeout with zero context. The watchdog
watches each tick from a separate thread; when one overruns its
deadline it emits a :class:`StallEvent` naming the *last-completed span*
— so "wedged inside the first compile" vs. "wedged in snapshot readback"
vs. "wedged in a subscriber callback" is readable straight off the
report, without a debugger attached to the hung process.

One event per stalled tick (not one per poll), and the event fires
*while the tick is still stuck* — that is the point: the diagnosis must
escape (stderr, a sink, the RunReport of a parallel thread) even if the
tick never finishes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import sys
import threading
import time
from typing import Callable, Iterator, List, Optional

from . import spans as spans_lib
from .registry import REGISTRY


@dataclasses.dataclass(frozen=True)
class StallEvent:
    label: str                          # what was being watched ("tick@gen8")
    elapsed_seconds: float              # overrun at detection time
    deadline_seconds: float
    last_completed_span: Optional[str]  # where progress was last observed
    open_spans: tuple                   # the stalled thread's span stack
    t: float                            # perf_counter at detection

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["open_spans"] = list(self.open_spans)
        return d


def _default_on_stall(ev: StallEvent) -> None:
    sys.stderr.write(
        f"STALL: {ev.label} exceeded its {ev.deadline_seconds:.1f}s deadline "
        f"({ev.elapsed_seconds:.1f}s elapsed); last completed span: "
        f"{ev.last_completed_span or '<none>'}"
        + (f"; open: {' > '.join(ev.open_spans)}" if ev.open_spans else "")
        + "\n")


class StallWatchdog:
    """``with wd.watch("tick@gen8"): coordinator.tick(...)``.

    The monitor thread polls at ``deadline/4`` (min 10 ms, max 500 ms);
    detection latency is at most one poll past the deadline. ``on_stall``
    defaults to a stderr line; the RunReport reads ``wd.events`` either
    way. Use as a context manager (``with StallWatchdog(1.0) as wd:``)
    or call :meth:`start`/:meth:`stop` explicitly."""

    def __init__(self, deadline_seconds: float, *,
                 tracer: Optional[spans_lib.SpanTracer] = None,
                 on_stall: Optional[Callable[[StallEvent], None]] = None):
        if deadline_seconds <= 0:
            raise ValueError(
                f"deadline must be positive, got {deadline_seconds}")
        self.deadline = float(deadline_seconds)
        self._tracer = tracer or spans_lib.TRACER
        self._on_stall = on_stall or _default_on_stall
        self._extra_on_stall: List[Callable[[StallEvent], None]] = []
        self.events: List[StallEvent] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the watched tick: (label, t0, watched thread's live span stack,
        # flagged)
        self._active: Optional[list] = None

    # -- the watched section -------------------------------------------------

    @contextlib.contextmanager
    def watch(self, label: str) -> Iterator[None]:
        # capture the watched thread's live stack object NOW: the monitor
        # thread must read THIS thread's open spans, and a thread-local
        # getter called over there would see the monitor's own stack
        stack = self._tracer._live_stack()
        with self._lock:
            self._active = [label, time.perf_counter(), stack, False]
        try:
            yield
        finally:
            with self._lock:
                self._active = None

    # -- the monitor thread --------------------------------------------------

    def start(self) -> "StallWatchdog":
        # lifecycle state under the lock too (goltpu-lint GOL004): two
        # threads racing start() must not each spawn a monitor
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            t = self._thread = threading.Thread(
                target=self._monitor, name="stall-watchdog", daemon=True)
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def __enter__(self) -> "StallWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _monitor(self) -> None:
        poll = min(max(self.deadline / 4.0, 0.01), 0.5)
        while not self._stop.wait(poll):
            self._check(time.perf_counter())

    def _check(self, now: float) -> Optional[StallEvent]:
        """One poll; factored out so tests can drive detection without
        racing a real thread."""
        with self._lock:
            active = self._active
            if active is None or active[3]:
                return None
            label, t0, stack, _ = active
            elapsed = now - t0
            if elapsed <= self.deadline:
                return None
            active[3] = True  # one event per stalled tick
            # snapshot the sink chain inside the lock: add_on_stall from
            # another thread (flight-recorder arming) must not mutate the
            # list this poll is iterating
            sinks = [self._on_stall, *self._extra_on_stall]
        last = self._tracer.last_completed()
        ev = StallEvent(
            label=label, elapsed_seconds=elapsed,
            deadline_seconds=self.deadline,
            last_completed_span=last.name if last else None,
            open_spans=tuple(stack), t=now)
        with self._lock:
            self.events.append(ev)
        # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
        REGISTRY.counter("stalls", "ticks that overran the watchdog deadline"
                         ).inc(label=label)
        for sink in sinks:
            try:
                sink(ev)
            except Exception:
                pass  # a broken sink must not kill the monitor thread
        return ev

    def events_since(self, n: int) -> List[StallEvent]:
        """Stall events recorded after index ``n`` — the supervisor's
        poll: snapshot ``len(wd.events)`` before a tick, read the tail
        after it, and any entries are the stalls that tick suffered.
        Under the lock: the monitor thread appends concurrently."""
        with self._lock:
            return list(self.events[n:])

    def add_on_stall(self, fn: Callable[[StallEvent], None]) -> None:
        """Chain an extra stall sink after ``on_stall`` (the flight
        recorder hangs its dump-on-stall here without displacing the
        stderr diagnostic)."""
        # under the lock (goltpu-lint GOL004): the monitor thread
        # snapshots this list mid-poll
        with self._lock:
            self._extra_on_stall = self._extra_on_stall + [fn]

    def remove_on_stall(self, fn: Callable[[StallEvent], None]) -> None:
        # equality, not identity: bound methods are rebuilt per access
        with self._lock:
            self._extra_on_stall = [f for f in self._extra_on_stall
                                    if f != fn]


# -- process-default arming (how the coordinator finds the watchdog) ---------
#
# GridCoordinator.tick wraps itself in the armed watchdog's watch() when
# one is armed, so telemetry setup needs no coordinator plumbing and a
# library user can arm/disarm around any code at all.

_ACTIVE: Optional[StallWatchdog] = None


def arm(wd: StallWatchdog) -> StallWatchdog:
    """Make ``wd`` the process-default watchdog (started) and return it."""
    global _ACTIVE
    _ACTIVE = wd.start()
    return wd


def disarm() -> None:
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.stop()
    _ACTIVE = None


def active_watchdog() -> Optional[StallWatchdog]:
    return _ACTIVE
