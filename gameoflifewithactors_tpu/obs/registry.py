"""Labeled counters / gauges / histograms — the metrics registry.

``StepMetrics`` (utils/metrics.py) is the per-tick record stream; this is
the cumulative face: named instruments any layer can bump without
plumbing a logger through every call site, snapshotted into the
RunReport at the end of a run. Deliberately tiny and Prometheus-shaped
(name + label dict -> series), stdlib only.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _key(labels: Optional[dict]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class Counter:
    """Monotonic count (events, bytes, cache misses)."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._series: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        k = _key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        # reads take the lock too: a dict resize mid-read from a writer
        # thread is a real (if rare) RuntimeError under free-threading,
        # and a torn read is worse — silently wrong
        with self._lock:
            return self._series.get(_key(labels), 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "counter", "help": self.help,
                    "series": [{"labels": dict(k), "value": v}
                               for k, v in self._series.items()]}


class Gauge:
    """Point-in-time value (active tiles, queue depth, HBM bytes)."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._series: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_key(labels)] = float(value)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._series.get(_key(labels))

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "gauge", "help": self.help,
                    "series": [{"labels": dict(k), "value": v}
                               for k, v in self._series.items()]}


# decade buckets from 100 µs to 100 s — host-side phase times; compile
# times land in the seconds decades, steady-state ticks in the millis
DEFAULT_BUCKETS = (1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0, 100.0)


class Histogram:
    """Bucketed distribution (tick seconds, compile seconds)."""

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name, self.help = name, help
        self.buckets = tuple(sorted(buckets))
        self._series: Dict[LabelKey, List] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        k = _key(labels)
        with self._lock:
            rec = self._series.get(k)
            if rec is None:
                # [bucket counts..., +inf count], total sum, n
                rec = self._series[k] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            rec[0][bisect.bisect_left(self.buckets, value)] += 1
            rec[1] += value
            rec[2] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "histogram", "help": self.help,
                    "buckets": list(self.buckets),
                    "series": [{"labels": dict(k), "counts": list(rec[0]),
                                "sum": rec[1], "n": rec[2]}
                               for k, rec in self._series.items()]}


class MetricsRegistry:
    """Name -> instrument. ``counter``/``gauge``/``histogram`` get-or-create
    so call sites never race on registration."""

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help, **kw)
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        """Get-or-create. ``buckets=None`` means "whatever this
        instrument has" (DEFAULT_BUCKETS when creating) — so generic
        call sites compose with instruments registered under custom
        boundaries (queue-wait seconds are not step-latency decades).
        Passing explicit buckets that CONFLICT with an existing
        instrument raises: silently observing into someone else's
        boundaries is the bug this guard exists for."""
        inst = self._get(Histogram, name, help,
                         buckets=buckets if buckets is not None
                         else DEFAULT_BUCKETS)
        if buckets is not None and inst.buckets != tuple(sorted(buckets)):
            raise ValueError(
                f"histogram {name!r} is registered with buckets "
                f"{inst.buckets}, not {tuple(sorted(buckets))}; pick a "
                "different name or drop the buckets argument")
        return inst

    def snapshot(self) -> dict:
        with self._lock:
            insts = dict(self._instruments)
        return {name: inst.snapshot() for name, inst in sorted(insts.items())}

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()


REGISTRY = MetricsRegistry()
