"""RunReport: one JSON artifact answering "where did this run's time go".

Folds the other pillars together — host spans (per-phase time:
dispatch / sync / readback / subscribers), compile events, ``StepMetrics``
records, model-vs-measured halo bytes, stall events, the metrics
registry, and (when a perfetto trace exists) the measured device duty
cycle from ``utils.profiling.perfetto_summary``. Written by the CLI
(``--telemetry-out``), ``bench.py`` and ``examples/telemetry.py``; read
back by the ``report`` CLI subcommand and :meth:`RunReport.load`.

:class:`RunTelemetry` is the session object: ``begin_run_telemetry()``
resets the process-global tracer/compile log, arms the stall watchdog,
and hands back the ``StepMetrics`` buffer sink to hang on a coordinator;
``finish()`` assembles the report.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import List, Optional

from . import compile as compile_lib
from . import spans as spans_lib
from . import watchdog as watchdog_lib
from .registry import REGISTRY

SCHEMA_VERSION = 1


@dataclasses.dataclass
class RunReport:
    created_at: str                    # ISO-8601 UTC
    config: dict                       # free-form run description
    platform: dict                     # jax platform/devices (may be empty)
    phase_seconds: dict                # span name -> {total_s, count, mean_s}
    spans: List[dict]                  # individual span records
    compile_events: List[dict]
    compile_seconds_total: float
    step_metrics: List[dict]           # StepMetrics.to_dict() records
    halo_bytes: dict                   # {"model_per_gen", "measured_per_gen"}
    stalls: List[dict]
    metrics: dict                      # registry snapshot
    perfetto: Optional[dict] = None    # device duty cycle, when a trace exists
    roofline: Optional[dict] = None    # obs.device.roofline_section output
    profile: Optional[dict] = None     # ProfileSampler.attribution(), armed runs
    schema_version: int = SCHEMA_VERSION

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("profile") is None:
            # sampler off (the default): the serialized report stays
            # byte-compatible with pre-profiler reports
            del d["profile"]
        return d

    def to_json(self, indent: int = 1) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json())
            f.write("\n")
        return path

    @classmethod
    def from_dict(cls, d: dict) -> "RunReport":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_json(cls, text: str) -> "RunReport":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path: str) -> "RunReport":
        with open(path) as f:
            return cls.from_json(f.read())

    # -- the human face (the `report` CLI subcommand) ------------------------

    def summary_lines(self) -> List[str]:
        lines = [f"RunReport {self.created_at}  "
                 f"platform={self.platform.get('platform', '?')}"]
        if self.config:
            lines.append("config: " + json.dumps(self.config, sort_keys=True))
        if self.phase_seconds:
            lines.append("host phases (where the wall-clock went):")
            width = max(map(len, self.phase_seconds))
            for name, rec in sorted(self.phase_seconds.items(),
                                    key=lambda kv: -kv[1]["total_s"]):
                lines.append(
                    f"  {name:{width}}  {rec['total_s']:10.4f}s"
                    f"  x{rec['count']:<6} mean {rec['mean_s']:.4f}s")
        # warm-start attribution (aot/): real compiles vs persistent-cache
        # hits vs AOT loads; pre-`kind` reports only recorded real misses
        kind_of = lambda e: e.get(  # noqa: E731 - local classifier
            "kind", "cache_miss" if e.get("cache_miss") else "cache_hit")
        misses = [e for e in self.compile_events
                  if kind_of(e) == "cache_miss"]
        hits = sum(1 for e in self.compile_events
                   if kind_of(e) == "cache_hit")
        aot = sum(1 for e in self.compile_events
                  if kind_of(e) == "aot_loaded")
        warm = (f", {hits} cache-hit" if hits else "") + \
               (f", {aot} aot-loaded" if aot else "")
        lines.append(
            f"compiles: {len(misses)} "
            f"({self.compile_seconds_total:.2f}s total){warm}")
        for e in misses:
            lines.append(f"  {e['wall_seconds']:8.3f}s  {e['runner']}"
                         f"({e['signature']})")
        if self.step_metrics:
            rates = [m["cell_updates_per_sec"] for m in self.step_metrics]
            lines.append(
                f"step metrics: {len(self.step_metrics)} records, "
                f"best {max(rates):.3g} cell-updates/s")
        hb = self.halo_bytes or {}
        if hb.get("model_per_gen") is not None:
            meas = hb.get("measured_per_gen")
            lines.append(
                f"halo bytes/gen: model {hb['model_per_gen']}"
                + (f", measured {meas}" if meas is not None else ""))
        if self.stalls:
            lines.append(f"STALLS: {len(self.stalls)}")
            for s in self.stalls:
                lines.append(
                    f"  {s['label']}: {s['elapsed_seconds']:.1f}s "
                    f"(deadline {s['deadline_seconds']:.1f}s), last span "
                    f"{s['last_completed_span'] or '<none>'}")
        if self.roofline:
            from . import device as device_lib

            lines.extend(device_lib.summary_lines(self.roofline))
        if self.perfetto:
            busy, span = (self.perfetto.get("device_busy_us", 0.0),
                          self.perfetto.get("device_span_us", 0.0))
            if span:
                lines.append(
                    f"device duty cycle: {busy / span:.1%} "
                    f"({self.perfetto.get('device_track')})")
        if self.profile:
            p = self.profile
            lines.append(
                f"sampling profiler: {p.get('windows', 0)} window(s), "
                f"source={p.get('source')}, "
                f"duty {p.get('duty_cycle', 0.0):.2%}")
            frac = p.get("op_class_fraction") or {}
            shares = sorted(((k, v) for k, v in frac.items() if v),
                            key=lambda kv: -kv[1])
            if shares:
                lines.append("  op classes: " + ", ".join(
                    f"{k} {v:.0%}" for k, v in shares))
            meas = p.get("halo_overlap_ratio_measured")
            static = p.get("halo_overlap_ratio_static")
            if meas is not None:
                line = f"  halo overlap measured {meas:.1%}"
                if static is not None:
                    line += f" vs static {static:.1%}"
                lines.append(line)
            elif static is not None:
                lines.append(
                    f"  halo overlap static {static:.1%} "
                    f"(measured: n/a — {p.get('source')} capture)")
        return lines


def _platform_info() -> dict:
    """Best-effort device description; {} when jax is unimportable or the
    backend refuses (a wedged tunnel must not take the report down)."""
    try:
        import jax

        devs = jax.devices()
        return {"platform": devs[0].platform,
                "device_kind": devs[0].device_kind,
                "device_count": len(devs)}
    except Exception:
        return {}


def build_run_report(
    *,
    tracer: Optional[spans_lib.SpanTracer] = None,
    compile_log: Optional[compile_lib.CompileEventLog] = None,
    step_records: Optional[list] = None,
    engine=None,
    watchdog: Optional[watchdog_lib.StallWatchdog] = None,
    trace_path: Optional[str] = None,
    config: Optional[dict] = None,
    halo_bytes: Optional[dict] = None,
    roofline: Optional[dict] = None,
    profile: Optional[dict] = None,
) -> RunReport:
    """Assemble a RunReport from whichever pillars the run exercised.

    ``step_records`` may be StepMetrics objects or plain dicts. Halo
    bytes: the arithmetic model always (cheap, pinned == HLO in
    tests/test_halo_bytes.py); the measured HLO figure only when the
    engine already computed it (no surprise compile at report time).
    ``halo_bytes`` overrides for engine-less callers (bench.py times raw
    ops runners single-device, where the honest figure is 0).
    """
    tracer = tracer or spans_lib.TRACER
    compile_log = compile_log or compile_lib.COMPILE_LOG

    halo: dict = dict(halo_bytes or {})
    if engine is not None:
        halo["model_per_gen"] = engine.halo_bytes_per_gen(source="model")
        measured = getattr(engine, "_halo_hlo", None)
        halo["measured_per_gen"] = measured
        config = dict(config or {})
        config.setdefault("shape", list(engine.shape))
        config.setdefault("rule", engine.rule.notation)
        config.setdefault("backend", engine.backend)
        config.setdefault("sharded", engine.mesh is not None)

    perfetto = None
    if trace_path:
        from ..utils.profiling import perfetto_summary

        try:
            perfetto = perfetto_summary(trace_path)
        except Exception as exc:  # a malformed trace must not eat the report
            perfetto = {"error": f"{type(exc).__name__}: {exc}"}

    records = []
    for m in step_records or []:
        records.append(m if isinstance(m, dict) else m.to_dict())

    if roofline is None and engine is not None:
        # static XLA cost of the compiled runner x measured step rates.
        # Best-effort: cost analysis needs a lowering, and a platform
        # that refuses it must not take the report down.
        from . import device as device_lib

        try:
            cost = engine.runner_cost_analysis()
        except Exception:
            cost = None
        platform = None
        try:
            platform = engine.state.devices().pop().platform  # type: ignore
        except Exception:
            platform = _platform_info().get("platform")
        roofline = device_lib.roofline_section(
            cost=cost, step_records=records, platform=platform)

    return RunReport(
        created_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        config=config or {},
        platform=_platform_info(),
        phase_seconds=tracer.phase_seconds(),
        spans=[s.to_dict() for s in tracer.spans()],
        compile_events=[e.to_dict() for e in compile_log.events()],
        compile_seconds_total=compile_log.total_compile_seconds(),
        step_metrics=records,
        halo_bytes=halo,
        stalls=[e.to_dict() for e in (watchdog.events if watchdog else [])],
        metrics=REGISTRY.snapshot(),
        perfetto=perfetto,
        roofline=roofline,
        profile=profile,
    )


class RunTelemetry:
    """One run's telemetry session over the process-global recorders.

    Continuous-telemetry extensions (ISSUE 3): ``flight_path`` arms a
    :class:`~.flight.FlightRecorder` (crash-report JSONL on stall /
    signal / coordinator-loop exception) for the session, and
    ``device_poll`` starts a :class:`~.device.DeviceSampler` feeding HBM
    gauges into the registry on that interval — both torn down by
    :meth:`finish`. ``profile_sample`` (ISSUE 18) arms a duty-cycled
    :class:`~.profiler.ProfileSampler` on that period; its cumulative
    op-class attribution lands in the report's ``profile`` section."""

    def __init__(self, *, stall_deadline: Optional[float] = None,
                 flight_path: Optional[str] = None,
                 device_poll: Optional[float] = None,
                 profile_sample: Optional[float] = None):
        from ..utils.metrics import BufferSink

        spans_lib.TRACER.clear()
        compile_lib.COMPILE_LOG.clear()
        self.step_buffer = BufferSink()
        self.watchdog: Optional[watchdog_lib.StallWatchdog] = None
        if stall_deadline:
            self.watchdog = watchdog_lib.arm(
                watchdog_lib.StallWatchdog(stall_deadline))
        self.flight = None
        if flight_path:
            from . import flight as flight_lib

            self.flight = flight_lib.FlightRecorder(flight_path)
            self.flight.install(watchdog=self.watchdog)
            flight_lib.arm(self.flight)
        self.sampler = None
        if device_poll:
            from .device import DeviceSampler

            self.sampler = DeviceSampler(device_poll).start()
        self.profiler = None
        if profile_sample:
            from . import profiler as profiler_lib

            self.profiler = profiler_lib.arm(
                profiler_lib.ProfileSampler(profile_sample))

    def attach(self, coordinator) -> None:
        """Hang the StepMetrics buffer on a coordinator (creating its
        MetricsLogger when it has none)."""
        from ..utils.metrics import MetricsLogger

        if coordinator.metrics is None:
            coordinator.metrics = MetricsLogger(self.step_buffer)
        else:
            coordinator.metrics.add_sink(self.step_buffer)
        if self.flight is not None:
            # the black box tapes FIRST: a signal landing between sinks
            # must not leave a dump whose tape is missing the record a
            # user-facing sink already printed
            coordinator.metrics.sinks.insert(0, self.flight.on_step)

    def finish(self, *, engine=None, trace_path: Optional[str] = None,
               config: Optional[dict] = None,
               halo_bytes: Optional[dict] = None) -> RunReport:
        """Disarm the watchdog and assemble the report. When an engine is
        given, close the run observably first: a sync (so in-flight
        dispatches land inside the spans being reported) and a tiny
        downsampled snapshot (so the readback phase exists even for runs
        that never rendered)."""
        if engine is not None:
            engine.block_until_ready()
            engine.snapshot(max_shape=(8, 8))
        profile = None
        if self.profiler is not None:
            from . import profiler as profiler_lib

            if self.profiler is profiler_lib.active_sampler():
                profiler_lib.disarm()
            else:
                self.profiler.stop()
            profile = self.profiler.attribution()
        if self.sampler is not None:
            self.sampler.sample_once()  # final gauges reflect end-of-run
            self.sampler.stop()
        if self.flight is not None:
            from . import flight as flight_lib

            if self.flight is flight_lib.active_flight_recorder():
                flight_lib.disarm()
            else:
                self.flight.uninstall()
        if self.watchdog is not None and self.watchdog is \
                watchdog_lib.active_watchdog():
            watchdog_lib.disarm()
        return build_run_report(
            step_records=self.step_buffer.records, engine=engine,
            watchdog=self.watchdog, trace_path=trace_path, config=config,
            halo_bytes=halo_bytes, profile=profile)


def begin_run_telemetry(*, stall_deadline: Optional[float] = None,
                        flight_path: Optional[str] = None,
                        device_poll: Optional[float] = None,
                        profile_sample: Optional[float] = None
                        ) -> RunTelemetry:
    """Start a fresh telemetry session (clears the global tracer and
    compile log — earlier runs' spans must not leak into this report)."""
    return RunTelemetry(stall_deadline=stall_deadline,
                        flight_path=flight_path, device_poll=device_poll,
                        profile_sample=profile_sample)
