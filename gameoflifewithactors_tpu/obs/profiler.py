"""Always-on sampling profiler: duty-cycled capture -> op-class attribution.

PR 17's ghost pipeline *claims* comms/compute overlap and the roofline
section *models* device time — both arithmetic. This module makes them
measurements with a hard overhead budget: :class:`ProfileSampler` is a
daemon that opens a short ``jax.profiler`` window (default 200 ms) once
per period (default 30 s), feeds the perfetto dump through
``utils.profiling.perfetto_summary``, and publishes **op-class
attribution** — busy seconds bucketed into {collective-permute, fused
stencil/convolution, copy/reshape, infeed/host, other} by slice-name
classification — as registry gauges and a cumulative ``attribution()``
dict the RunReport carries.

Off by default; armed by ``--profile-sample S`` or
``GOLTPU_PROFILE_SAMPLE_S``. The budget is enforced, not aspirational:
a window/period ratio above :data:`MAX_DUTY_CYCLE` refuses to
construct, and the measured excess (capture wall beyond the window
itself — start/stop/parse cost) is published as
``profile_overhead_ratio`` so the budget is auditable from a scrape.

COST discipline (same as ``halo_overlap_ratio``): attribution fractions
and the measured overlap ratio are per-chip figures —
``obs.aggregate.PER_CHIP_GAUGES`` refuses to sum them across procs. On
a host-only capture (CPU: no device tracks) attribution is labeled
``source="host_tracks"`` — mirroring ``obs.device``'s ``host_rss``
idiom — and ``halo_overlap_ratio_measured`` is ``None``, never a
fabricated 0.0.

Like the rest of ``obs/``, no jax import at module scope: the capture
backend imports jax lazily inside the sampler thread, and tests inject
a fake ``capture`` callable.
"""

from __future__ import annotations

import contextlib
import os
import re
import threading
import time
from typing import Callable, Optional

from .registry import REGISTRY, MetricsRegistry

DEFAULT_WINDOW_S = 0.2
DEFAULT_PERIOD_S = 30.0
ENV_SAMPLE = "GOLTPU_PROFILE_SAMPLE_S"
#: Hard overhead budget: the capture window may occupy at most this
#: fraction of the sampling period.
MAX_DUTY_CYCLE = 0.1

OTHER_CLASS = "other"
#: The attribution buckets, in display order.
OP_CLASSES = ("collective_permute", "stencil", "copy_reshape",
              "infeed_host", OTHER_CLASS)

# First match wins. Collectives before everything (an async
# collective-permute-start must not read as a copy); infeed/transfer
# next; fusions/kernels before copy_reshape so "broadcast_multiply_fusion"
# reads as compute, not as a broadcast; bare data-movement ops last.
_CLASS_PATTERNS = (
    ("collective_permute",
     re.compile(r"collective-permute|collective_permute|all-reduce|"
                r"all-gather|reduce-scatter|all-to-all|ppermute|"
                r"^(send|recv)[.-]", re.IGNORECASE)),
    ("infeed_host",
     re.compile(r"infeed|outfeed|transfer|memcpy|h2d|d2h|"
                r"buffer[- ]?copy", re.IGNORECASE)),
    ("stencil",
     re.compile(r"fusion|conv|dot|while|custom-call|custom_call|mosaic|"
                r"stencil|reduce-window|select-and-scatter|gol_step|"
                r"goltpu\.dispatch", re.IGNORECASE)),
    ("copy_reshape",
     re.compile(r"copy|reshape|transpose|bitcast|broadcast|slice|"
                r"concatenate|\bpad\b|gather|scatter", re.IGNORECASE)),
)


def classify_slice(name: str) -> str:
    """Op class of one profiler slice name (first matching bucket)."""
    for cls, pat in _CLASS_PATTERNS:
        if pat.search(name):
            return cls
    return OTHER_CLASS


def attribution_path_for(report_path: str) -> str:
    """Where the standalone attribution JSON lives, next to its
    RunReport (``foo.json`` -> ``foo.attribution.json``) — one rule for
    the CLI writer, bench.py's pointer, and the CI artifact glob."""
    stem = (report_path[: -len(".json")]
            if report_path.endswith(".json") else report_path)
    return stem + ".attribution.json"


class ProfileSampler:
    """Duty-cycled sampling profiler: short capture windows -> gauges.

    ``ProfileSampler(period).start()`` captures one window immediately
    (a short run still gets attribution), then one per period until
    ``stop()``. Each window's summary updates cumulative op-class
    seconds, the measured comms/compute overlap, and the registry:

    - ``profile_windows_total`` / ``profile_capture_errors`` counters,
    - ``profile_op_class_seconds_total{op_class,source}`` counter
      (device-seconds: sums meaningfully across a fleet),
    - ``profile_op_class_fraction{op_class,source}`` gauge (per-chip:
      refuses fleet summing),
    - ``profile_duty_cycle`` / ``profile_overhead_ratio`` gauges,
    - ``halo_overlap_ratio_measured`` gauge — only when a device-track
      capture actually observed collectives.

    ``capture`` is the injectable seam (a callable ``(window_s) ->
    summary dict | None``); the default opens a real ``jax.profiler``
    window and parses the perfetto dump. ``sample_once()`` is the
    deterministic unit tests drive; it never raises.
    """

    def __init__(self, period_seconds: Optional[float] = None, *,
                 window_seconds: float = DEFAULT_WINDOW_S,
                 registry: MetricsRegistry = REGISTRY,
                 capture: Optional[Callable[[float], Optional[dict]]] = None):
        if period_seconds is None:
            period_seconds = float(
                os.environ.get(ENV_SAMPLE, DEFAULT_PERIOD_S))
        if period_seconds <= 0:
            raise ValueError(
                f"sampling period must be positive, got {period_seconds}")
        if window_seconds <= 0:
            raise ValueError(
                f"capture window must be positive, got {window_seconds}")
        if window_seconds > period_seconds * MAX_DUTY_CYCLE:
            raise ValueError(
                f"profiler duty cycle {window_seconds / period_seconds:.1%} "
                f"exceeds the {MAX_DUTY_CYCLE:.0%} overhead budget; raise "
                "the period or shrink the window")
        self.period = float(period_seconds)
        self.window = float(window_seconds)
        self.registry = registry
        self._capture = capture or self._capture_window
        self._lock = threading.Lock()
        self._stop = threading.Event()
        with self._lock:
            self._thread: Optional[threading.Thread] = None
            self._started_at: Optional[float] = None
            self._windows = 0
            self._errors = 0
            self._capture_seconds = 0.0
            self._excess_seconds = 0.0   # capture wall beyond the window
            self._op_class_us: dict = {}
            self._collective_us = 0.0
            self._compute_us = 0.0
            self._overlapped_us = 0.0
            self._source: Optional[str] = None

    # -- capture --------------------------------------------------------------

    def _capture_window(self, window_seconds: float) -> Optional[dict]:
        """One real ``jax.profiler`` window into a temp dir, parsed and
        deleted. Returns None when the backend produced no perfetto dump
        (nothing to attribute is not an error)."""
        import glob
        import shutil
        import tempfile

        import jax  # lazy: obs stays importable with a wedged backend

        from ..utils.profiling import perfetto_summary

        tmp = tempfile.mkdtemp(prefix="goltpu-profile-")
        try:
            jax.profiler.start_trace(tmp, create_perfetto_trace=True)
            try:
                # the window itself: sleep while the workload runs in
                # other threads; interruptible so stop() is prompt
                self._stop.wait(window_seconds)
            finally:
                jax.profiler.stop_trace()
            dumps = sorted(glob.glob(
                os.path.join(tmp, "**", "perfetto_trace.json.gz"),
                recursive=True))
            if not dumps:
                return None
            return perfetto_summary(dumps[0])
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def sample_once(self) -> Optional[dict]:
        """One capture window, folded into cumulative state + gauges.
        Never raises — a wedged profiler bumps ``profile_capture_errors``
        instead of taking the run down."""
        t0 = time.perf_counter()
        try:
            summary = self._capture(self.window)
        except Exception as exc:
            with self._lock:
                self._errors += 1
            # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
            self.registry.counter(
                "profile_capture_errors",
                "profiler capture windows that raised").inc(
                    error=type(exc).__name__)
            return None
        wall = time.perf_counter() - t0
        if not summary:
            with self._lock:
                self._capture_seconds += wall
                self._excess_seconds += max(0.0, wall - self.window)
            return None
        self._fold(summary, wall)
        return summary

    def _fold(self, summary: dict, wall: float) -> None:
        op_us = summary.get("op_class_us") or {}
        overlap = summary.get("overlap") or {}
        source = summary.get("source") or (
            "host_tracks" if summary.get("tracks") else None)
        with self._lock:
            self._windows += 1
            self._capture_seconds += wall
            self._excess_seconds += max(0.0, wall - self.window)
            if source:
                self._source = source
            for cls, us in op_us.items():
                self._op_class_us[cls] = self._op_class_us.get(cls, 0.0) + us
            self._collective_us += overlap.get("collective_us") or 0.0
            self._compute_us += overlap.get("compute_us") or 0.0
            self._overlapped_us += overlap.get("overlapped_us") or 0.0
            cum_op = dict(self._op_class_us)
            collective_us = self._collective_us
            overlapped_us = self._overlapped_us
            excess = self._excess_seconds
            started_at = self._started_at
        # publish outside our lock (the registry has its own)
        reg = self.registry
        label_source = source or "?"
        total_us = sum(cum_op.values())
        for cls in OP_CLASSES:
            us = op_us.get(cls)
            if us:
                reg.counter(
                    "profile_op_class_seconds_total",
                    "sampled busy seconds attributed to an op class "
                    "(device-seconds: sums across a fleet)").inc(
                        us / 1e6, op_class=cls, source=label_source)
            if total_us > 0:
                reg.gauge(
                    "profile_op_class_fraction",
                    "share of sampled busy time in an op class "
                    "(per-chip: refuses fleet summing)").set(
                        cum_op.get(cls, 0.0) / total_us,
                        op_class=cls, source=label_source)
        reg.counter("profile_windows_total",
                    "profiler capture windows completed").inc()
        reg.gauge("profile_duty_cycle",
                  "configured capture-window share of the sampling "
                  "period (per-chip)").set(self.window / self.period)
        elapsed = (time.perf_counter() - started_at
                   if started_at is not None else wall)
        if elapsed > 0:
            reg.gauge(
                "profile_overhead_ratio",
                "measured capture cost beyond the window itself, as a "
                "share of elapsed run time (per-chip)").set(
                    min(1.0, excess / elapsed))
        if source == "device_tracks" and collective_us > 0:
            reg.gauge(
                "halo_overlap_ratio_measured",
                "measured share of collective time overlapped with "
                "interior compute (interval-union, device tracks; "
                "per-chip)").set(overlapped_us / collective_us)

    # -- cumulative view ------------------------------------------------------

    def attribution(self) -> dict:
        """Cumulative attribution for the RunReport ``profile`` section.

        ``halo_overlap_ratio_measured`` is the busy-weighted ratio over
        all windows when a device-track capture observed collectives,
        and ``None`` otherwise (host-only capture, or no collectives in
        any window) — absent, never 0.0. The static schedule gauge
        (PR 17's ``halo_overlap_ratio``) rides along for the
        cross-check when the run set it.
        """
        with self._lock:
            windows = self._windows
            errors = self._errors
            cap = self._capture_seconds
            excess = self._excess_seconds
            cum_op = dict(self._op_class_us)
            collective_us = self._collective_us
            compute_us = self._compute_us
            overlapped_us = self._overlapped_us
            source = self._source
        total_us = sum(cum_op.values())
        out: dict = {
            "source": source,
            "windows": windows,
            "capture_errors": errors,
            "window_seconds": self.window,
            "period_seconds": self.period,
            "duty_cycle": self.window / self.period,
            "capture_seconds_total": round(cap, 6),
            "capture_excess_seconds_total": round(excess, 6),
            "op_class_seconds": {cls: round(cum_op.get(cls, 0.0) / 1e6, 6)
                                 for cls in OP_CLASSES},
            "op_class_fraction": ({cls: cum_op.get(cls, 0.0) / total_us
                                   for cls in OP_CLASSES}
                                  if total_us > 0 else {}),
            "per_chip": True,
        }
        measured = None
        if source == "device_tracks" and collective_us > 0:
            measured = overlapped_us / collective_us
            out["overlap_collective_seconds"] = round(collective_us / 1e6, 6)
            out["overlap_compute_seconds"] = round(compute_us / 1e6, 6)
        out["halo_overlap_ratio_measured"] = measured
        static = self.registry.gauge(
            "halo_overlap_ratio",
            "interior compute share of the static block schedule "
            "(per-chip)").value()
        if static is not None:
            out["halo_overlap_ratio_static"] = static
            if measured is not None:
                out["overlap_measured_minus_static"] = measured - static
        return out

    # -- the sampler thread ---------------------------------------------------

    def start(self) -> "ProfileSampler":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._started_at = time.perf_counter()
            self._thread = threading.Thread(
                target=self._run, name="profile-sampler", daemon=True)
            thread = self._thread
        thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=max(5.0, 4 * self.window))

    def _run(self) -> None:
        # capture immediately (a run shorter than one period still gets
        # attribution), then once per period until stopped
        self.sample_once()
        while not self._stop.wait(max(self.period - self.window, 0.01)):
            self.sample_once()

    def __enter__(self) -> "ProfileSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- the process-global armed sampler (mirrors obs.flight.arm) ---------------

_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[ProfileSampler] = None


def arm(sampler: ProfileSampler) -> ProfileSampler:
    """Install + start ``sampler`` as the process's armed profiler
    (stopping any predecessor): ``dispatch_annotation`` regions only
    pay their cost while one is armed."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        previous, _ACTIVE = _ACTIVE, sampler
    if previous is not None:
        previous.stop()
    return sampler.start()


def disarm() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        sampler, _ACTIVE = _ACTIVE, None
    if sampler is not None:
        sampler.stop()


def active_sampler() -> Optional[ProfileSampler]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def dispatch_annotation(name: str):
    """A profiler timeline region that is free when no sampler is armed
    (``nullcontext``) — the engine wraps every dispatch in one, so armed
    windows show ``goltpu.dispatch`` slices without taxing unarmed
    runs."""
    if active_sampler() is None:
        return contextlib.nullcontext()
    from ..utils.profiling import annotate

    return annotate(name)
