"""Host-side span tracer: nested named regions, chrome-trace exportable.

``jax.profiler`` answers "what did the *device* do"; this answers "what
did the *host* do between dispatches" — the half of a stall that a
device trace cannot see (a wedged tunnel shows an empty device timeline
and a host stuck inside one span; the span name is the diagnosis). Spans
nest via a per-thread stack, recording is thread-safe, and the export is
chrome://tracing JSON, so a host span file drops into ui.perfetto.dev
next to a ``jax.profiler`` perfetto dump for a combined timeline.

The default :data:`TRACER` is always on: recording a span is two
``perf_counter`` calls and a deque append (~1 µs), noise against a
device dispatch, and the ring buffer bounds memory on long runs.

**Distributed trace context.** A :class:`TraceContext` (128-bit trace id
plus the parent span's 64-bit id) can be bound to the current thread
with :func:`bind_trace`, or to the whole process via the
``GOLTPU_TRACE`` env var (how a fleet driver makes worker spans nest
under its own span — see ``resilience/`` and ``scripts/soak.py``).
While a context is in effect, every recorded span carries ``trace_id``,
its own ``span_id``, and ``parent_id`` (the enclosing open span, or the
bound context's span id for roots), so per-process tapes merge into one
end-to-end trace in ``obs/aggregate.py``. With no context bound, the
fields stay ``None`` and the record path costs exactly what it did
before — the telemetry CLI's perf budget is unchanged.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Deque, Iterator, List, Optional, TextIO

DEFAULT_MAX_SPANS = 65536

#: Env var carrying a parent trace context into child processes
#: (``"<32-hex trace id>"`` or ``"<32-hex trace id>:<16-hex span id>"``).
TRACE_ENV_VAR = "GOLTPU_TRACE"


def new_trace_id() -> str:
    """A fresh 128-bit trace id as 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """A fresh 64-bit span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The ambient trace a thread/process records spans under.

    ``span_id`` is the *parent* for root spans opened while this context
    is bound — the fleet driver's span id when inherited via env, the
    caller's span id when it arrived on an ``X-Goltpu-Trace`` header, or
    ``None`` when the caller supplied only a trace id."""

    trace_id: str
    span_id: Optional[str] = None

    def header(self) -> str:
        """The wire form (HTTP header / env var value)."""
        return (f"{self.trace_id}:{self.span_id}" if self.span_id
                else self.trace_id)

    def child_env(self) -> dict:
        """Env entries that make a subprocess inherit this context."""
        return {TRACE_ENV_VAR: self.header()}


def parse_trace_header(value: str) -> TraceContext:
    """Parse ``"<trace id>[:<span id>]"``; raises ``ValueError`` on
    anything that is not 32 (+ optional 16) hex chars."""
    hexdigits = set("0123456789abcdef")
    parts = value.strip().split(":")
    if len(parts) not in (1, 2):
        raise ValueError(f"malformed trace header: {value!r}")
    trace_id, span_id = parts[0], (parts[1] if len(parts) == 2 else None)
    if len(trace_id) != 32 or not set(trace_id) <= hexdigits:
        raise ValueError(f"malformed trace header: {value!r}")
    if span_id is not None and (len(span_id) != 16
                                or not set(span_id) <= hexdigits):
        raise ValueError(f"malformed trace header: {value!r}")
    return TraceContext(trace_id=trace_id, span_id=span_id)


_TRACE_LOCAL = threading.local()


def _context_from_env() -> Optional[TraceContext]:
    raw = os.environ.get(TRACE_ENV_VAR, "").strip()
    if not raw:
        return None
    try:
        return parse_trace_header(raw)
    except ValueError:
        return None  # a garbled env var must not break the child


#: Process-wide ambient context (inherited from ``GOLTPU_TRACE`` at
#: import — how worker spans nest under the fleet driver's span).
_PROCESS_CONTEXT: Optional[TraceContext] = _context_from_env()


def set_process_context(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install (or clear, with ``None``) the process-ambient context;
    returns the previous one so callers can restore it."""
    global _PROCESS_CONTEXT
    prev = _PROCESS_CONTEXT
    _PROCESS_CONTEXT = ctx
    return prev


def current_trace() -> Optional[TraceContext]:
    """The context in effect on this thread: a :func:`bind_trace` binding
    wins; otherwise the process-ambient (env-inherited) context."""
    ctx = getattr(_TRACE_LOCAL, "ctx", None)
    return ctx if ctx is not None else _PROCESS_CONTEXT


@contextlib.contextmanager
def bind_trace(trace_id: Optional[str] = None,
               parent_id: Optional[str] = None) -> Iterator[TraceContext]:
    """Bind a trace context to the current thread for the block.

    ``trace_id=None`` mints a fresh one (the frontend's "no caller
    header" path). Bindings nest; the previous binding is restored on
    exit, so concurrent request threads can never cross-contaminate."""
    ctx = TraceContext(trace_id=trace_id or new_trace_id(),
                       span_id=parent_id)
    prev = getattr(_TRACE_LOCAL, "ctx", None)
    _TRACE_LOCAL.ctx = ctx
    try:
        yield ctx
    finally:
        _TRACE_LOCAL.ctx = prev


@dataclasses.dataclass(frozen=True)
class Span:
    """One completed region. ``t0``/``t1`` are ``time.perf_counter``
    seconds; add the tracer's ``epoch_anchor`` for wall-clock time."""

    name: str
    t0: float
    t1: float
    thread_id: int
    thread_name: str
    depth: int                      # nesting level at record time (0 = root)
    attrs: Optional[dict] = None
    # distributed trace identity — None unless a TraceContext was bound
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0

    def to_dict(self) -> dict:
        d = {"name": self.name, "t0": self.t0, "t1": self.t1,
             "seconds": self.seconds, "thread": self.thread_name,
             "depth": self.depth}
        if self.attrs:
            d["attrs"] = self.attrs
        # additive: untraced spans serialize exactly as before
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
            d["span_id"] = self.span_id
            if self.parent_id is not None:
                d["parent_id"] = self.parent_id
        return d


class SpanTracer:
    """Thread-safe recorder of nested spans with a bounded ring buffer."""

    def __init__(self, maxlen: int = DEFAULT_MAX_SPANS, enabled: bool = True):
        self._spans: Deque[Span] = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._last: Optional[Span] = None
        self._listeners: List = []
        self.enabled = enabled
        # perf_counter -> wall-clock anchor, so exported timestamps can be
        # correlated with a jax.profiler trace captured in the same process
        # goltpu: ignore[GOL005] -- wall-clock is the point: this anchors perf_counter spans to epoch time for perfetto correlation
        self.epoch_anchor = time.time() - time.perf_counter()

    def add_listener(self, fn) -> None:
        """Call ``fn(span)`` on every completed span (flight recorder tap).
        Listeners run on the recording thread, outside the tracer lock —
        they must be cheap and must not call back into the tracer."""
        with self._lock:
            self._listeners = self._listeners + [fn]

    def remove_listener(self, fn) -> None:
        # equality, not identity: ``obj.method`` builds a fresh bound-
        # method object per access, so ``is`` would never match
        with self._lock:
            self._listeners = [f for f in self._listeners if f != fn]

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        """``with tracer.span("engine.step", generations=8): ...``"""
        if not self.enabled:
            yield
            return
        stack = self._live_stack()
        depth = len(stack)
        stack.append(name)
        # trace identity only when a context is bound: the untraced fast
        # path stays two perf_counter calls + an append (the perf budget)
        ctx = current_trace()
        if ctx is not None:
            ids = self._live_ids()
            span_id = new_span_id()
            parent_id = ids[-1] if ids else ctx.span_id
            ids.append(span_id)
        else:
            ids = span_id = parent_id = None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            stack.pop()
            if ids is not None:
                ids.pop()
            th = threading.current_thread()
            s = Span(name=name, t0=t0, t1=t1, thread_id=th.ident or 0,
                     thread_name=th.name, depth=depth, attrs=attrs or None,
                     trace_id=ctx.trace_id if ctx is not None else None,
                     span_id=span_id, parent_id=parent_id)
            with self._lock:
                self._spans.append(s)
                self._last = s
                listeners = self._listeners
            for fn in listeners:
                try:
                    fn(s)
                except Exception:
                    pass  # a broken tap must not break the traced code

    # -- inspection ----------------------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def last_completed(self) -> Optional[Span]:
        """The most recently *finished* span — what the stall watchdog
        names when a tick wedges (the span after it never completed)."""
        with self._lock:
            return self._last

    def current_stack(self) -> List[str]:
        """This thread's open spans, outermost first."""
        return list(getattr(self._local, "stack", ()))

    def _live_stack(self) -> List[str]:
        """The calling thread's live stack *object* (created if absent).
        The stall watchdog snapshots this from its monitor thread — a
        thread-local read over there would see the monitor's own (empty)
        stack, so the watched thread's list must be captured by identity
        at watch() time. Copying it cross-thread is safe: span() only
        appends/pops under the GIL."""
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _live_ids(self) -> List[str]:
        """The calling thread's open-span *id* stack — parallel to
        ``_live_stack`` but only maintained while a trace context is
        bound, so the untraced record path never touches it."""
        ids = getattr(self._local, "ids", None)
        if ids is None:
            ids = self._local.ids = []
        return ids

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._last = None

    def phase_seconds(self) -> dict:
        """Per-name totals/counts — PhaseTimer-shaped, derived from spans.

        Nested spans each count their own wall time (``engine.step``
        inside ``coordinator.tick`` appears under both names), which is
        exactly what "where did the host time go, by layer" wants."""
        out: dict = {}
        for s in self.spans():
            rec = out.setdefault(s.name, {"total_s": 0.0, "count": 0})
            rec["total_s"] += s.seconds
            rec["count"] += 1
        for rec in out.values():
            rec["mean_s"] = rec["total_s"] / rec["count"]
        return out

    # -- export --------------------------------------------------------------

    def to_chrome_trace(self) -> dict:
        """chrome://tracing / Perfetto JSON object format. Timestamps are
        wall-clock microseconds (epoch-anchored), so this file and a
        ``jax.profiler`` dump from the same process line up when both are
        opened in ui.perfetto.dev."""
        pid = os.getpid()
        events = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": "gameoflifewithactors_tpu host spans"},
        }]
        seen_threads = set()
        for s in self.spans():
            if s.thread_id not in seen_threads:
                seen_threads.add(s.thread_id)
                events.append({
                    "ph": "M", "pid": pid, "tid": s.thread_id,
                    "name": "thread_name",
                    "args": {"name": s.thread_name},
                })
            ev = {
                "ph": "X", "pid": pid, "tid": s.thread_id, "name": s.name,
                "ts": (s.t0 + self.epoch_anchor) * 1e6,
                "dur": s.seconds * 1e6,
            }
            args = dict(s.attrs) if s.attrs else {}
            if s.trace_id is not None:
                args["trace_id"] = s.trace_id
                args["span_id"] = s.span_id
                if s.parent_id is not None:
                    args["parent_id"] = s.parent_id
            if args:
                ev["args"] = args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
            f.write("\n")
        return path

    def write_jsonl(self, stream_or_path: "TextIO | str") -> None:
        """One span per line (`tail -f`-able; the log-shipping form)."""
        if isinstance(stream_or_path, str):
            with open(stream_or_path, "w") as f:
                self.write_jsonl(f)
            return
        for s in self.spans():
            stream_or_path.write(json.dumps(s.to_dict()) + "\n")


TRACER = SpanTracer()


def span(name: str, **attrs):
    """Record on the process-default tracer (what the engine/coordinator/
    scheduler instrumentation uses)."""
    return TRACER.span(name, **attrs)
