"""Compile-event tracking for the jit entry points in ``ops/_jit.py``.

A first tick through a fresh runner pays XLA compilation — seconds,
against the microseconds a steady-state dispatch costs — and before this
module that time hid inside ``StepMetrics.wall_seconds`` (and inside the
bench autotune probe, and inside "why is the first tick 400x slower").
``tracked_call`` wraps every execution of an ``optionally_donated``
runner: when the call grew the jit cache (``_cache_size``, with a
signature-keyed fallback for jax versions without it), a
:class:`CompileEvent` records which runner, the shape/dtype signature
that triggered the trace, and the call's wall seconds.

The recorded ``wall_seconds`` is the *whole compiling call* — trace +
XLA compile + the first dispatch. The dispatch share is the steady-state
call time (microseconds), so the figure is compile time to within noise;
the coordinator subtracts exactly this from the tick it happened in.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, List, Optional

from .registry import REGISTRY

MAX_EVENTS = 4096  # a runaway retrace loop must not grow memory unbounded


def _describe(x) -> str:
    """'u32[512,16]'-style for array-likes, short repr otherwise."""
    dtype = getattr(x, "dtype", None)
    shape = getattr(x, "shape", None)
    if dtype is not None and shape is not None:
        return f"{dtype}[{','.join(map(str, shape))}]"
    r = repr(x)
    return r if len(r) <= 32 else r[:29] + "..."


def signature_of(args, kwargs) -> str:
    parts = [_describe(a) for a in args]
    parts += [f"{k}={_describe(v)}" for k, v in sorted(kwargs.items())]
    return ", ".join(parts)


@dataclasses.dataclass(frozen=True)
class CompileEvent:
    runner: str            # the wrapped function's name
    signature: str         # shape/dtype signature that triggered the trace
    wall_seconds: float    # the compiling call's wall time (compile-dominated)
    cache_miss: bool       # True: this call paid a REAL XLA compile
    donated: bool          # which of the two jit instances compiled
    t0: float              # perf_counter at call start
    t1: float              # perf_counter at completion
    # warm-start attribution (aot/): "cache_miss" = a real XLA compile
    # ran; "cache_hit" = the jit cache grew but the executable was served
    # from the persistent disk cache (trace + disk read, no compile);
    # "aot_loaded" = a serialized jax.export runner was loaded in place
    # of jitting (aot/registry.py). Only "cache_miss" events count toward
    # compile-second totals — the whole point of the warm path is that
    # the other two cost ~nothing.
    kind: str = "cache_miss"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class CompileEventLog:
    """Thread-safe bounded log of compile events, queryable by window —
    the coordinator asks "how much compile landed inside this tick"."""

    def __init__(self, maxlen: int = MAX_EVENTS):
        self._events: Deque[CompileEvent] = collections.deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._listeners: List = []

    def add_listener(self, fn) -> None:
        """Call ``fn(event)`` on every recorded event (flight recorder
        tap); runs on the recording thread, outside the log lock."""
        with self._lock:
            self._listeners = self._listeners + [fn]

    def remove_listener(self, fn) -> None:
        # equality, not identity: bound methods are rebuilt per access
        with self._lock:
            self._listeners = [f for f in self._listeners if f != fn]

    def record(self, ev: CompileEvent) -> None:
        with self._lock:
            self._events.append(ev)
            listeners = self._listeners
        for fn in listeners:
            try:
                fn(ev)
            except Exception:
                pass  # a broken tap must not break the compiling call

    def events(self) -> List[CompileEvent]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def total_compile_seconds(self) -> float:
        return sum(e.wall_seconds for e in self.events() if e.cache_miss)

    def compile_seconds_between(self, t0: float, t1: float) -> float:
        """Compile seconds of events that *completed* in the
        ``perf_counter`` window [t0, t1] — a compiling call completes
        inside the tick that paid for it, so completion time is the
        right attribution point."""
        return sum(e.wall_seconds for e in self.events()
                   if e.cache_miss and t0 <= e.t1 <= t1)


COMPILE_LOG = CompileEventLog()

# fallback bookkeeping for jitted objects without _cache_size: signatures
# this process has already seen per wrapped instance
_SEEN_SIGS: dict = {}
_SEEN_LOCK = threading.Lock()

# persistent-compilation-cache event counters, fed by the jax.monitoring
# listener aot/cache.py installs (this module must stay importable with
# no jax in sight, so the jax-touching half lives there). tracked_call
# snapshots these around each call to attribute its CompileEvent.
_PC_LOCK = threading.Lock()
_PC_COUNTS = {"hit": 0, "miss": 0}


def note_persistent_cache_event(kind: str) -> None:
    """Record one persistent-cache ``"hit"`` or ``"miss"`` (listener API)."""
    with _PC_LOCK:
        _PC_COUNTS[kind] += 1
    # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
    REGISTRY.counter(
        "persistent_cache_events",
        "XLA persistent compilation cache hits/misses").inc(kind=kind)


def persistent_cache_counts() -> tuple:
    with _PC_LOCK:
        return _PC_COUNTS["hit"], _PC_COUNTS["miss"]


def record_aot_load(runner: str, signature: str, wall_seconds: float,
                    *, log: CompileEventLog = None) -> None:
    """Record that a serialized AOT runner was loaded in place of a jit
    compile (aot/registry.py calls this at load time). Attributed like a
    compile event so the RunReport tells the whole warm-start story, but
    never counted as compile seconds."""
    t1 = time.perf_counter()
    (log if log is not None else COMPILE_LOG).record(CompileEvent(
        runner=runner, signature=signature, wall_seconds=wall_seconds,
        cache_miss=False, donated=False, t0=t1 - wall_seconds, t1=t1,
        kind="aot_loaded"))
    # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
    REGISTRY.counter(
        "aot_loads", "serialized AOT runners loaded (no jit compile)"
    ).inc(runner=runner)


def _cache_size(target) -> Optional[int]:
    try:
        return target._cache_size()
    except Exception:
        return None


def tracked_call(target: Callable, runner: str, args: tuple, kwargs: dict,
                 *, donated: bool = False, log: CompileEventLog = None):
    """Execute ``target(*args, **kwargs)``, recording a CompileEvent when
    the call compiled. The non-compiling path costs two ``perf_counter``
    and one ``_cache_size`` pair — noise against any dispatch."""
    log = log if log is not None else COMPILE_LOG
    before = _cache_size(target)
    pc_hit0, pc_miss0 = persistent_cache_counts()
    t0 = time.perf_counter()
    out = target(*args, **kwargs)
    t1 = time.perf_counter()
    if before is not None:
        missed = (_cache_size(target) or 0) > before
    else:
        # no _cache_size on this jax: first sight of (instance, signature)
        # approximates a miss (weaker: it can't see re-traces after a
        # cache eviction, but never false-positives on a steady state)
        sig = signature_of(args, kwargs)
        k = (id(target), sig)
        with _SEEN_LOCK:
            missed = k not in _SEEN_SIGS
            _SEEN_SIGS[k] = True
    if missed:
        # attribute the jit-cache miss: if jax's persistent disk cache
        # served EVERY executable this call needed (>= 1 hit, 0 misses in
        # the window), no XLA compile ran — the call cost trace + disk
        # read, and the warm-start report should say so
        pc_hit1, pc_miss1 = persistent_cache_counts()
        served = pc_hit1 > pc_hit0 and pc_miss1 == pc_miss0
        kind = "cache_hit" if served else "cache_miss"
        ev = CompileEvent(
            runner=runner, signature=signature_of(args, kwargs),
            wall_seconds=t1 - t0, cache_miss=not served, donated=donated,
            t0=t0, t1=t1, kind=kind)
        log.record(ev)
        # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
        REGISTRY.counter(
            "jit_compiles", "jit cache misses (one XLA compile each, "
            "unless served by the persistent cache — see 'kind')"
        ).inc(runner=runner, kind=kind)
        REGISTRY.histogram(
            "jit_compile_seconds", "wall seconds of compiling calls"
        ).observe(t1 - t0, runner=runner)
    return out
