"""RunReport / bench-record differ: per-metric deltas with tolerance bands.

The BENCH_r01-r05 trajectory exists as JSON on disk but nothing
machine-checks it — a regression is only caught if a human rereads the
numbers. This module is the comparator under ``scripts/perf_gate.py``
and the ``report --diff`` CLI: it extracts a flat metric dict from
either artifact shape (a RunReport or a bench record), diffs two of
them with per-metric relative tolerance bands, and classifies each row
``ok`` / ``regression`` / ``improved`` / ``missing``.

Provenance gating (PR 2): a record flagged ``needs_recapture`` /
``stale`` — or whose commit-stamped provenance ``staleness()`` refuses
to certify — can pass the gate only as **"skipped"**, never as "ok":
comparing against numbers that describe a predecessor of HEAD's kernel
proves nothing either way. Stdlib only (the gate must run while a TPU
tunnel is wedged).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

HIGHER = "higher_is_better"
LOWER = "lower_is_better"

# relative tolerance per metric-name prefix (first match wins; the
# longest prefixes first). Host-side phase timings are noisy — loose
# bands; the headline rates are the contract — tighter bands.
DEFAULT_TOLERANCES = (
    ("bench/value", 0.20),
    ("scaling/single_chip_equivalent_updates_per_sec", 0.25),
    ("step/best_cell_updates_per_sec", 0.25),
    ("step/seconds_per_gen", 0.35),
    ("compile/", 2.0),     # cache state dominates; only gross blowups gate
    ("phase/", 0.60),
    ("stalls/", 0.0),      # any new stall is a regression
)
DEFAULT_TOLERANCE = 0.30

# absolute floors for lower-is-better timing metrics: when BOTH sides
# sit under the floor the delta is scheduler noise (a 5 µs -> 30 µs sync
# is not a regression anyone can act on), so the row reports "ok" with
# the ratio still visible
DEFAULT_FLOORS = (
    ("phase/", 5e-3),
    ("compile/", 0.5),
    ("step/seconds_per_gen", 0.0),
)


def tolerance_for(metric: str, overrides: Optional[dict] = None,
                  default: float = DEFAULT_TOLERANCE) -> float:
    for prefix, tol in tuple((overrides or {}).items()) + DEFAULT_TOLERANCES:
        if metric.startswith(prefix):
            return float(tol)
    return default


def floor_for(metric: str) -> float:
    for prefix, floor in DEFAULT_FLOORS:
        if metric.startswith(prefix):
            return floor
    return 0.0


def extract_metrics(record: dict) -> Dict[str, dict]:
    """Flatten either artifact shape into {name: {value, direction}}.

    Bench records (``{"metric", "value", ...}``) yield one headline row;
    RunReports yield step rates, compile totals, per-phase means, and the
    stall count. Unknown shapes yield {} (the caller reports "nothing
    comparable" instead of crashing on a future schema).
    """
    out: Dict[str, dict] = {}
    if not isinstance(record, dict):
        return out
    if "value" in record and "metric" in record:  # bench.py record
        if isinstance(record["value"], (int, float)):
            out["bench/value"] = {"value": float(record["value"]),
                                  "direction": HIGHER,
                                  "label": record["metric"]}
        # weak-scaling COST records (scripts/weak_scaling.py --out) ride
        # the bench shape plus the per-chip-equivalent headline: the
        # fleet's rate per device, in single-chip-bench units, at the
        # largest device count measured
        sceq = record.get("single_chip_equivalent_updates_per_sec")
        if isinstance(sceq, (int, float)):
            out["scaling/single_chip_equivalent_updates_per_sec"] = {
                "value": float(sceq), "direction": HIGHER,
                "label": "per-chip-equivalent updates/sec"}
        return out
    steps = record.get("step_metrics") or []
    rates = [m.get("cell_updates_per_sec") for m in steps
             if isinstance(m, dict) and m.get("cell_updates_per_sec")]
    if rates:
        out["step/best_cell_updates_per_sec"] = {
            "value": max(rates), "direction": HIGHER}
    walls = sum(m.get("wall_seconds", 0.0) for m in steps
                if isinstance(m, dict))
    gens = sum(m.get("generations_stepped", 0) for m in steps
               if isinstance(m, dict))
    if gens:
        out["step/seconds_per_gen"] = {"value": walls / gens,
                                       "direction": LOWER}
    if isinstance(record.get("compile_seconds_total"), (int, float)):
        out["compile/seconds_total"] = {
            "value": float(record["compile_seconds_total"]),
            "direction": LOWER}
    for name, rec in (record.get("phase_seconds") or {}).items():
        if isinstance(rec, dict) and isinstance(rec.get("mean_s"),
                                                (int, float)):
            out[f"phase/{name}/mean_s"] = {"value": float(rec["mean_s"]),
                                           "direction": LOWER}
    if isinstance(record.get("stalls"), list):
        out["stalls/count"] = {"value": float(len(record["stalls"])),
                               "direction": LOWER}
    ach = ((record.get("roofline") or {}).get("achieved") or {})
    if isinstance(ach.get("bytes_per_sec"), (int, float)):
        out["roofline/achieved_bytes_per_sec"] = {
            "value": float(ach["bytes_per_sec"]), "direction": HIGHER}
    return out


# op-class deltas within this band read as "flat" in the blame section
BLAME_FLAT_PCT = 0.02


def extract_attribution(record: dict) -> Optional[dict]:
    """Per-window op-class seconds from a RunReport's ``profile``
    section (ISSUE 18), or None when the record carries none (sampler
    unarmed, bench record, pre-profiler schema). Normalizing by window
    count makes two runs with different durations comparable."""
    if not isinstance(record, dict):
        return None
    prof = record.get("profile")
    if not isinstance(prof, dict):
        return None
    op = prof.get("op_class_seconds")
    if not isinstance(op, dict) or not op:
        return None
    windows = prof.get("windows") or 1
    per_window = {cls: float(v) / windows for cls, v in op.items()
                  if isinstance(v, (int, float))}
    if not any(per_window.values()):
        return None
    return {"source": prof.get("source"), "windows": windows,
            "per_window_s": per_window}


def attribution_blame(baseline: dict, current: dict) -> List[dict]:
    """Rank op classes by their contribution to the busy-time delta —
    the "why" behind a step-time regression: "collective-permute +31%,
    stencil flat" instead of a bare fail. Empty when either side lacks
    attribution (the gate's exit-code contract never depends on it)."""
    b = extract_attribution(baseline)
    c = extract_attribution(current)
    if not b or not c:
        return []
    classes = sorted(set(b["per_window_s"]) | set(c["per_window_s"]))
    rows = []
    for cls in classes:
        bv = b["per_window_s"].get(cls, 0.0)
        cv = c["per_window_s"].get(cls, 0.0)
        delta = cv - bv
        if bv > 0:
            pct: Optional[float] = delta / bv
        else:
            pct = None if cv > 0 else 0.0  # None = class appeared fresh
        rows.append({"op_class": cls,
                     "baseline_s_per_window": bv,
                     "current_s_per_window": cv,
                     "delta_s_per_window": delta,
                     "delta_pct": pct})
    rows.sort(key=lambda r: (-abs(r["delta_s_per_window"]), r["op_class"]))
    return rows


def format_blame(rows: List[dict]) -> List[str]:
    """The human blame section (perf_gate stdout under a regression)."""
    if not rows:
        return []
    width = max(len(r["op_class"]) for r in rows)
    lines = ["attribution blame (op-class busy s/window, "
             "largest contribution delta first):"]
    for r in rows:
        pct = r["delta_pct"]
        if pct is None:
            label = "new"
        elif abs(pct) <= BLAME_FLAT_PCT:
            label = "flat"
        else:
            label = f"{pct:+.0%}"
        lines.append(
            f"  {r['op_class']:{width}}  {label:>6}  "
            f"({r['baseline_s_per_window']:.4g}s -> "
            f"{r['current_s_per_window']:.4g}s)")
    return lines


@dataclasses.dataclass
class DiffRow:
    metric: str
    baseline: Optional[float]
    current: Optional[float]
    tolerance: float
    status: str                    # ok | regression | improved | missing
    ratio: Optional[float] = None  # current / baseline when both exist

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _classify(base: float, cur: float, direction: str, tol: float):
    if base == 0.0:
        if cur == 0.0:
            return "ok", None
        return ("improved" if direction == HIGHER else "regression"), None
    ratio = cur / base
    worsening = (1.0 - ratio) if direction == HIGHER else (ratio - 1.0)
    if worsening > tol:
        return "regression", ratio
    if -worsening > tol:
        return "improved", ratio
    return "ok", ratio


def diff_records(baseline: dict, current: dict, *,
                 tolerances: Optional[dict] = None,
                 default_tolerance: float = DEFAULT_TOLERANCE
                 ) -> List[DiffRow]:
    """Per-metric delta rows over the union of both records' metrics,
    sorted regressions first (then name) so the table leads with what
    matters."""
    b, c = extract_metrics(baseline), extract_metrics(current)
    rows = []
    for name in sorted(set(b) | set(c)):
        tol = tolerance_for(name, tolerances, default_tolerance)
        bv = b.get(name, {}).get("value")
        cv = c.get(name, {}).get("value")
        if bv is None or cv is None:
            rows.append(DiffRow(name, bv, cv, tol, "missing"))
            continue
        direction = c.get(name, b.get(name, {})).get("direction", LOWER)
        status, ratio = _classify(bv, cv, direction, tol)
        if (status != "ok" and direction == LOWER
                and max(bv, cv) < floor_for(name)):
            status = "ok"  # sub-floor timing churn is noise, not signal
        rows.append(DiffRow(name, bv, cv, tol, status, ratio))
    order = {"regression": 0, "improved": 1, "ok": 2, "missing": 3}
    rows.sort(key=lambda r: (order.get(r.status, 9), r.metric))
    return rows


def record_staleness(record: dict, *, provenance=None) -> Optional[str]:
    """Why this record cannot be trusted as a comparison anchor, or None.

    Honors the PR-2 flags directly (``needs_recapture`` / ``stale`` with
    their recorded reason) and, when a jax-free ``provenance`` module is
    supplied (bench.py's ``_provenance()`` loader) and the record carries
    a commit stamp, re-checks the measured paths against HEAD — a record
    committed fresh goes stale the moment the kernel under it changes.
    """
    if not isinstance(record, dict):
        return None
    if record.get("needs_recapture") or record.get("stale"):
        return record.get("stale_reason") or "record flagged needs_recapture"
    if provenance is not None and record.get("commit") \
            and "metric" in record:
        st = provenance.staleness(record)
        if st.get("stale"):
            return st.get("reason") or "provenance stale"
    return None


def gate(baseline: dict, current: dict, *,
         tolerances: Optional[dict] = None,
         default_tolerance: float = DEFAULT_TOLERANCE,
         provenance=None) -> dict:
    """The perf-gate verdict: {"status": ok|regression|skipped, "rows",
    "reason"}. ``skipped`` (stale anchor) is its own terminal state —
    the caller must surface it as "skipped (stale)", never fold it into
    "ok" (a stale baseline would wave every regression through)."""
    for which, rec in (("baseline", baseline), ("current", current)):
        why = record_staleness(rec, provenance=provenance)
        if why:
            return {"status": "skipped",
                    "reason": f"{which} record is stale: {why}",
                    "rows": []}
    rows = diff_records(baseline, current, tolerances=tolerances,
                        default_tolerance=default_tolerance)
    comparable = [r for r in rows if r.status != "missing"]
    if not comparable:
        return {"status": "skipped",
                "reason": "no comparable metrics between the two records",
                "rows": rows}
    bad = [r for r in rows if r.status == "regression"]
    verdict = {"status": "regression" if bad else "ok",
               "reason": (f"{len(bad)} metric(s) regressed beyond tolerance"
                          if bad else
                          f"{len(comparable)} metric(s) within tolerance"),
               "rows": rows}
    blame = attribution_blame(baseline, current)
    if blame:
        # advisory only: blame explains a verdict, it never changes one
        # (the 0/1/2 exit contract is pinned by tests/test_perf_gate.py)
        verdict["blame"] = blame
    return verdict


def format_rows(rows: List[DiffRow]) -> List[str]:
    """The human delta table (report --diff / perf_gate stdout)."""
    if not rows:
        return ["(no comparable metrics)"]
    name_w = max(len(r.metric) for r in rows)

    def fmt(v):
        return f"{v:.4g}" if isinstance(v, (int, float)) else "-"

    lines = [f"{'metric':{name_w}}  {'baseline':>12}  {'current':>12}"
             f"  {'ratio':>7}  {'tol':>5}  status"]
    for r in rows:
        ratio = f"{r.ratio:.3f}" if r.ratio is not None else "-"
        lines.append(
            f"{r.metric:{name_w}}  {fmt(r.baseline):>12}  "
            f"{fmt(r.current):>12}  {ratio:>7}  {r.tolerance:>5.2f}  "
            f"{r.status.upper() if r.status == 'regression' else r.status}")
    return lines
