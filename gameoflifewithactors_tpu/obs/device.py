"""Continuous device-resource sampling + roofline attribution.

PR 1's telemetry is post-hoc (spans and compile events folded into a
RunReport after the run ends); this module answers "what is the device
doing *right now*" and "how close is this runner to the roofline":

- :class:`DeviceSampler` — a daemon thread polling every local device's
  ``memory_stats()`` (bytes_in_use / peak / limit) into registry gauges
  (``hbm_bytes_in_use`` etc.) on a configurable interval, so a scraped
  ``/metrics`` endpoint (obs/exporter.py) shows live HBM pressure while
  the engine steps. Backends without ``memory_stats`` (CPU) fall back to
  host-process RSS, labeled ``source="host_rss"`` so the number is never
  mistaken for device memory.
- :func:`roofline_section` — per-runner static cost attribution: XLA's
  own cost analysis of the *compiled* runner (``Compiled.cost_analysis``:
  FLOPs, bytes accessed — see ``Engine.runner_cost_analysis``) folded
  with the measured ``StepMetrics`` wall time into achieved-vs-modelled
  throughput. The arithmetic peak model (the figures BASELINE.md and
  ``scripts/roofline_report.py`` quote) lives here as :data:`PEAKS` so
  every consumer reads one source.

Like the rest of ``obs/``, no jax import at module scope: the sampler
looks devices up lazily inside the thread, and a wedged backend degrades
to the host fallback instead of taking the telemetry layer down.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional

from .registry import REGISTRY, MetricsRegistry

DEFAULT_INTERVAL_S = 1.0
ENV_POLL = "GOLTPU_DEVICE_POLL_S"

# Arithmetic peak model per platform — promoted from
# scripts/roofline_report.py ARITHMETIC so the RunReport and the script
# quote the same bounds. hbm_gbps is the memory-bandwidth roof the
# stencil family actually runs against (the packed kernels are
# HBM-traffic engineered, BASELINE.md "Roofline sanity bound");
# cell_updates_ceiling is the 2-HBM-touch packed model at g=8 temporal
# blocking. CPU has no published bound on this rig — consumers get None
# and must say "unmodelled", never invent a denominator.
PEAKS = {
    "tpu": {
        "hbm_gbps": 820.0,                 # v5e HBM bandwidth
        "packed_2touch_ceiling": 3.3e12,   # 2 HBM touches/gen, 32 cells/word
        "temporal_g8_ceiling": 2.6e13,     # 2 touches per 8 gens
    },
}


def _host_rss_stats() -> dict:
    """Host-process RSS as the CPU stand-in for device memory stats.
    /proc on Linux (current RSS), ru_maxrss everywhere (peak)."""
    stats: dict = {"source": "host_rss"}
    try:
        import resource

        peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        stats["peak_bytes_in_use"] = int(peak_kb) * 1024
    except Exception:
        pass
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        stats["bytes_in_use"] = rss_pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        # no /proc: serve peak as the (monotone) in-use figure rather
        # than nothing — a gauge that exists beats a gauge that lies low
        if "peak_bytes_in_use" in stats:
            stats["bytes_in_use"] = stats["peak_bytes_in_use"]
    return stats


def default_memory_backend() -> List[dict]:
    """One dict per local device: {device, platform, bytes_in_use, ...}.
    The injectable seam the sampler polls — tests swap in a fake."""
    import jax

    out = []
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        rec = {"device": str(dev.id), "platform": dev.platform,
               "source": "memory_stats"}
        if stats:
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                      "bytes_reserved", "largest_free_block_bytes"):
                if stats.get(k) is not None:
                    rec[k] = int(stats[k])
        else:  # CPU / backends without allocator stats
            rec.update(_host_rss_stats())
        out.append(rec)
    return out


class DeviceSampler:
    """Background poller: device memory stats -> registry gauges.

    ``with DeviceSampler(0.5): ...`` or ``start()``/``stop()``. Each
    sample sets ``hbm_bytes_in_use`` / ``hbm_bytes_peak`` /
    ``hbm_bytes_limit`` gauges labeled by device id + platform (+
    ``source`` when the figure is the host-RSS fallback) and bumps the
    ``device_samples`` counter — everything lands in the same registry
    the Prometheus exporter and the RunReport snapshot read.
    ``sample_once()`` is the deterministic unit tests drive."""

    _GAUGES = {"bytes_in_use": ("hbm_bytes_in_use",
                                "device memory currently allocated (bytes)"),
               "peak_bytes_in_use": ("hbm_bytes_peak",
                                     "high-water device allocation (bytes)"),
               "bytes_limit": ("hbm_bytes_limit",
                               "device memory capacity (bytes)")}

    def __init__(self, interval_seconds: Optional[float] = None, *,
                 registry: MetricsRegistry = REGISTRY,
                 backend: Optional[Callable[[], List[dict]]] = None):
        if interval_seconds is None:
            interval_seconds = float(
                os.environ.get(ENV_POLL, DEFAULT_INTERVAL_S))
        if interval_seconds <= 0:
            raise ValueError(
                f"poll interval must be positive, got {interval_seconds}")
        self.interval = float(interval_seconds)
        self.registry = registry
        self._backend = backend or default_memory_backend
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def sample_once(self) -> List[dict]:
        """One poll; returns what the backend reported (tests assert on
        it). Never raises — a wedged backend yields an empty sample and
        a bumped ``device_sample_errors`` counter instead."""
        try:
            stats = self._backend()
        except Exception as exc:
            # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
            self.registry.counter(
                "device_sample_errors",
                "device memory polls that raised").inc(
                    error=type(exc).__name__)
            return []
        for rec in stats:
            labels = {"device": str(rec.get("device", "?")),
                      "platform": str(rec.get("platform", "?"))}
            if rec.get("source") == "host_rss":
                labels["source"] = "host_rss"
            for key, (gname, ghelp) in self._GAUGES.items():
                if rec.get(key) is not None:
                    self.registry.gauge(gname, ghelp).set(
                        float(rec[key]), **labels)
        self.samples += 1
        # goltpu: ignore[GOL010] -- series name frozen pre-_total convention: committed history.jsonl/RunReports key on it
        self.registry.counter(
            "device_samples", "device memory polls completed").inc()
        return stats

    # -- the poller thread ---------------------------------------------------

    def start(self) -> "DeviceSampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._poll, name="device-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _poll(self) -> None:
        # sample immediately (a short run should still leave gauges),
        # then on the interval until stopped
        self.sample_once()
        while not self._stop.wait(self.interval):
            self.sample_once()

    def __enter__(self) -> "DeviceSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- roofline attribution -----------------------------------------------------


def roofline_section(*, cost: Optional[dict] = None,
                     step_records: Optional[list] = None,
                     platform: Optional[str] = None,
                     gens: Optional[int] = None) -> Optional[dict]:
    """Fold static XLA cost analysis with measured step rates.

    ``cost`` is ``Engine.runner_cost_analysis()`` output (``flops`` /
    ``bytes_accessed`` for a ``gens``-generation dispatch of the compiled
    runner); ``step_records`` are StepMetrics dicts or objects. Returns
    the RunReport ``roofline`` dict — static per-generation cost,
    achieved throughput (best measured record), and achieved-vs-modelled
    fractions against :data:`PEAKS` — or None when there is nothing to
    attribute (no cost analysis and no measurements).
    """
    gens = gens or (cost or {}).get("generations") or 1
    section: dict = {}
    if cost:
        flops = cost.get("flops")
        bytes_acc = cost.get("bytes_accessed")
        section["cost_analysis"] = {
            "generations": gens,
            "flops": flops,
            "bytes_accessed": bytes_acc,
            "flops_per_gen": flops / gens if flops else None,
            "bytes_per_gen": bytes_acc / gens if bytes_acc else None,
        }
        if flops and bytes_acc:
            section["cost_analysis"]["arithmetic_intensity"] = \
                flops / bytes_acc

    best = None
    records = [m if isinstance(m, dict) else m.to_dict()
               for m in step_records or []]
    rated = [m for m in records if m.get("cell_updates_per_sec")]
    if rated:
        best = max(rated, key=lambda m: m["cell_updates_per_sec"])
        rate = best["cell_updates_per_sec"]
        section["achieved"] = {
            "cell_updates_per_sec": rate,
            "records": len(rated),
        }
        ca = section.get("cost_analysis") or {}
        cells_per_gen = None
        if best.get("generations_stepped") and best.get("wall_seconds"):
            cells_per_gen = (rate * best["wall_seconds"]
                             / best["generations_stepped"])
        if ca.get("flops_per_gen") and cells_per_gen:
            # measured rate x static per-cell cost = achieved FLOP/s and
            # HBM traffic of the runner XLA actually compiled
            section["achieved"]["flops_per_sec"] = \
                rate * ca["flops_per_gen"] / cells_per_gen
            if ca.get("bytes_per_gen"):
                section["achieved"]["bytes_per_sec"] = \
                    rate * ca["bytes_per_gen"] / cells_per_gen

    if not section:
        return None

    peaks = PEAKS.get(platform or "")
    section["platform"] = platform
    if peaks:
        section["peak_modelled"] = dict(peaks)
        if best is not None:
            frac = {}
            rate = best["cell_updates_per_sec"]
            if peaks.get("temporal_g8_ceiling"):
                frac["of_temporal_g8_ceiling"] = \
                    rate / peaks["temporal_g8_ceiling"]
            bps = section.get("achieved", {}).get("bytes_per_sec")
            if bps and peaks.get("hbm_gbps"):
                frac["of_hbm_bandwidth"] = bps / (peaks["hbm_gbps"] * 1e9)
            if frac:
                section["achieved_fraction"] = frac
    else:
        # no invented denominators: an unmodelled platform says so
        section["peak_modelled"] = None
    return section


def summary_lines(roofline: dict) -> List[str]:
    """The human face of a roofline section (RunReport.summary_lines)."""
    lines = []
    ca = roofline.get("cost_analysis") or {}
    if ca.get("flops_per_gen"):
        per = f"  {ca['flops_per_gen']:.3g} FLOPs/gen"
        if ca.get("bytes_per_gen"):
            per += f", {ca['bytes_per_gen']:.3g} HBM bytes/gen"
        if ca.get("arithmetic_intensity"):
            per += f" (intensity {ca['arithmetic_intensity']:.2f})"
        lines.append("roofline (XLA cost analysis of the compiled runner):")
        lines.append(per)
    ach = roofline.get("achieved") or {}
    if ach.get("cell_updates_per_sec"):
        line = f"  achieved {ach['cell_updates_per_sec']:.3g} cell-updates/s"
        if ach.get("flops_per_sec"):
            line += f" = {ach['flops_per_sec']:.3g} FLOP/s"
        if ach.get("bytes_per_sec"):
            line += f", {ach['bytes_per_sec'] / 1e9:.1f} GB/s HBM"
        lines.append(line)
    frac = roofline.get("achieved_fraction") or {}
    if frac.get("of_hbm_bandwidth") is not None:
        lines.append(
            f"  {frac['of_hbm_bandwidth']:.1%} of the "
            f"{roofline['peak_modelled']['hbm_gbps']:.0f} GB/s modelled "
            "HBM bound")
    elif roofline.get("peak_modelled") is None and lines:
        lines.append(f"  (no modelled peak for platform "
                     f"{roofline.get('platform')!r})")
    return lines
