"""Two-phase corner-correct halo exchange via ``lax.ppermute``.

This is the TPU-native replacement for the reference's neighbor-to-neighbor
actor ``Tell`` messages (BASELINE.json north_star: "lax.ppermute halo
exchange replacing neighbor-to-neighbor actor Tell messages"). Where each
CellActor Tells its state to 8 neighbors every generation (~8·N·M mailbox
messages), a sharded tile sends 4 ppermute messages per generation — two
1-row strips and two 1-column strips riding ICI — and the 8-way neighbor
data dependency is reconstructed locally by the stencil.

Corner correctness (SURVEY.md §8 "hard parts") comes from phasing: rows are
exchanged first, then *columns of the row-extended tile*, so the column
strips already carry the north/south halo rows — my NW corner halo is the
bottom-right element of my NW diagonal neighbor, delivered via my west
neighbor's extended edge. No diagonal sends needed.

Boundary semantics: for TORUS the permutation wraps; for DEAD the edge
tiles receive ``lax.ppermute``'s zero-fill for absent sources, which is
exactly the all-dead boundary — no special-casing.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.stencil import Topology
from .mesh import COL_AXIS, ROW_AXIS


def _shift_perm(n: int, direction: int, wrap: bool) -> List[Tuple[int, int]]:
    """(source, dest) pairs sending data ``direction`` steps along an axis:
    direction=+1 means device i's data lands on device i+1."""
    perm = [(i, i + direction) for i in range(n) if 0 <= i + direction < n]
    if wrap:
        if direction == +1:
            perm.append((n - 1, 0))
        else:
            perm.append((0, n - 1))
    return perm


def band_edge_code(nx: int, axis=ROW_AXIS) -> jax.Array:
    """This device's global-edge code for row-band decompositions, as the
    (1, 1) int32 SMEM operand the dead_band slab kernels consume
    (ops/pallas_stencil.py _zero_band_exterior): bit0 = the device holds
    the global top band, bit1 = the bottom. One definition for every band
    runner so the bit contract can't drift between them. shard_map only.
    ``axis`` may be a tuple of mesh axis names — the flattened band axis
    of the 2D-mesh band runners (``lax.axis_index`` composes row-major)."""
    ix = lax.axis_index(axis)
    return (jnp.where(ix == 0, 1, 0)
            | jnp.where(ix == nx - 1, 2, 0)).astype(jnp.int32).reshape(1, 1)


def exchange_rows(tile: jax.Array, nx: int, topology: Topology, axis=ROW_AXIS,
                  depth: int = 1) -> jax.Array:
    """(h, w) tile -> (h+2·depth, w) with north/south halo strips of
    ``depth`` rows from mesh neighbors (depth > 1 serves radius-r stencils
    like Larger-than-Life; requires depth <= tile height). ``axis`` may be
    a tuple of mesh axis names treated as one flattened axis of size ``nx``
    (the 2D-mesh band runners' x-major band ordering)."""
    wrap = topology is Topology.TORUS
    # My north halo rows are my north neighbor's bottom rows: data flows +1.
    north = lax.ppermute(tile[-depth:], axis, _shift_perm(nx, +1, wrap))
    south = lax.ppermute(tile[:depth], axis, _shift_perm(nx, -1, wrap))
    return jnp.concatenate([north, tile, south], axis=0)


def exchange_cols(ext: jax.Array, ny: int, topology: Topology, axis: str = COL_AXIS,
                  depth: int = 1) -> jax.Array:
    """(h+2d, w) row-extended tile -> (h+2d, w+2d) with west/east halo
    columns (the diagonal corners ride in the already-extended rows)."""
    wrap = topology is Topology.TORUS
    west = lax.ppermute(ext[:, -depth:], axis, _shift_perm(ny, +1, wrap))
    east = lax.ppermute(ext[:, :depth], axis, _shift_perm(ny, -1, wrap))
    return jnp.concatenate([west, ext, east], axis=1)


def exchange_rows_parts(top: jax.Array, bottom: jax.Array, nx: int,
                        topology: Topology,
                        axis=ROW_AXIS) -> Tuple[jax.Array, jax.Array]:
    """Row phase of a *split* two-phase exchange: given MY top and bottom
    d-row strips, return ``(north_halo, south_halo)`` — the strips my
    neighbors just sent me. Identical wire traffic and direction contract
    to :func:`exchange_rows` (my north halo is my north neighbor's bottom
    strip), but the caller supplies the strips instead of the whole tile,
    so the ghost-zone runner can issue the sends from freshly-computed
    boundary rings while the tile interior is still being stepped."""
    wrap = topology is Topology.TORUS
    north = lax.ppermute(bottom, axis, _shift_perm(nx, +1, wrap))
    south = lax.ppermute(top, axis, _shift_perm(nx, -1, wrap))
    return north, south


def exchange_cols_parts(west_cols: jax.Array, east_cols: jax.Array, ny: int,
                        topology: Topology,
                        axis: str = COL_AXIS) -> Tuple[jax.Array, jax.Array]:
    """Column phase of a split two-phase exchange: given MY west and east
    d-word columns *of the row-extended tile* (so the corner blocks ride
    along, exactly as in :func:`exchange_cols`), return
    ``(west_halo, east_halo)``."""
    wrap = topology is Topology.TORUS
    west = lax.ppermute(east_cols, axis, _shift_perm(ny, +1, wrap))
    east = lax.ppermute(west_cols, axis, _shift_perm(ny, -1, wrap))
    return west, east


def exchange_rows_stack(stack: jax.Array, nx: int, topology: Topology,
                        axis=ROW_AXIS, depth: int = 1) -> jax.Array:
    """(b, h, w) stack -> (b, h+2d, w): the row half of
    :func:`exchange_halo_stack` — one ppermute per side carries all b
    members. Serves the batched row-band runner, whose full-width bands
    need no column phase. ``axis`` may be a flattened axis-name tuple,
    like :func:`exchange_rows`."""
    wrap = topology is Topology.TORUS
    north = lax.ppermute(stack[:, -depth:, :], axis, _shift_perm(nx, +1, wrap))
    south = lax.ppermute(stack[:, :depth, :], axis, _shift_perm(nx, -1, wrap))
    return jnp.concatenate([north, stack, south], axis=1)


def exchange_cols_stack(ext: jax.Array, ny: int, topology: Topology,
                        depth: int = 1) -> jax.Array:
    """(b, h', w) row-extended stack -> (b, h', w+2d): the column half of
    :func:`exchange_halo_stack`, separated so depth can differ per axis
    (the radius-r LtL plane layout ships r halo rows but one halo word)."""
    wrap = topology is Topology.TORUS
    west = lax.ppermute(ext[:, :, -depth:], COL_AXIS, _shift_perm(ny, +1, wrap))
    east = lax.ppermute(ext[:, :, :depth], COL_AXIS, _shift_perm(ny, -1, wrap))
    return jnp.concatenate([west, ext, east], axis=2)


def exchange_halo_stack(stack: jax.Array, nx: int, ny: int, topology: Topology,
                        depth: int = 1) -> jax.Array:
    """(b, h, w) plane stack -> (b, h+2d, w+2d): the same two-phase trip as
    :func:`exchange_halo`, but one ppermute per side carries ALL b planes
    (payload (b, d, w)) instead of b separate sends — 4 collectives per
    generation for the bit-plane Generations layout regardless of b."""
    return exchange_cols_stack(
        exchange_rows_stack(stack, nx, topology, depth=depth), ny, topology,
        depth=depth)


def exchange_halo(tile: jax.Array, nx: int, ny: int, topology: Topology,
                  depth: int = 1) -> jax.Array:
    """Full two-phase exchange: (h, w) tile -> (h+2d, w+2d) haloed tile.

    Works identically for unpacked (halo = cell strips) and packed tiles
    (halo = word strips, of which the 3×3 stencil consumes 1 bit — shipping
    whole words keeps payloads aligned; at 32768 rows/tile the E/W halo is
    128 KB, negligible on ICI). ``depth`` d exchanges d-deep strips for
    radius-d neighborhoods; the two phases make the (d, d) corner blocks
    correct with 4 sends, no diagonal messages.
    """
    return exchange_cols(
        exchange_rows(tile, nx, topology, depth=depth), ny, topology, depth=depth
    )
