"""Device-mesh construction for 2D grid sharding.

The reference "scales" by spawning more actors on one CPU (SURVEY.md §2 —
its entire communication substrate is the in-process Akka mailbox). The
TPU-native scaling story is a 2D ``jax.sharding.Mesh``: the grid is cut into
(nx, ny) tiles, one per device, and neighbor state crosses tile edges as
``ppermute`` halo exchange over ICI (see halo.py). These helpers build
near-square meshes from whatever devices exist — real TPU slices or the
8-fake-CPU-device test rig.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "x"  # shards grid rows
COL_AXIS = "y"  # shards grid columns (packed: word columns)


def factor2d(n: int) -> Tuple[int, int]:
    """Factor n devices into the most-square (nx, ny) grid, nx <= ny.

    Near-square tiles minimise halo perimeter per tile (the analogue of
    picking a good actor-partitioning, except here it is bytes on ICI).
    """
    best = (1, n)
    for a in range(1, int(np.sqrt(n)) + 1):
        if n % a == 0:
            best = (a, n // a)
    return best


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 2D mesh with axes (ROW_AXIS, COL_AXIS) over the given devices."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = factor2d(len(devices))
    nx, ny = shape
    if nx * ny != len(devices):
        raise ValueError(f"mesh shape {shape} needs {nx * ny} devices, have {len(devices)}")
    return Mesh(np.asarray(devices).reshape(nx, ny), (ROW_AXIS, COL_AXIS))


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that tiles a (H, W) or (H, W/32) grid 2D over the mesh."""
    return NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))


def check_divisible(shape: Tuple[int, int], mesh: Mesh) -> None:
    h, w = shape
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    if h % nx or w % ny:
        raise ValueError(
            f"grid {shape} not divisible by mesh ({nx}, {ny}); "
            f"pad the grid or pick a different mesh shape"
        )


def device_put_sharded_grid(grid: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a (possibly packed) grid onto the mesh with 2D tiling."""
    check_divisible(grid.shape, mesh)
    return jax.device_put(grid, grid_sharding(mesh))
