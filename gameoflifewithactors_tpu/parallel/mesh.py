"""Device-mesh construction for 2D grid sharding.

The reference "scales" by spawning more actors on one CPU (SURVEY.md §2 —
its entire communication substrate is the in-process Akka mailbox). The
TPU-native scaling story is a 2D ``jax.sharding.Mesh``: the grid is cut into
(nx, ny) tiles, one per device, and neighbor state crosses tile edges as
``ppermute`` halo exchange over ICI (see halo.py). These helpers build
near-square meshes from whatever devices exist — real TPU slices or the
8-fake-CPU-device test rig.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "x"  # shards grid rows
COL_AXIS = "y"  # shards grid columns (packed: word columns)


def factor2d(n: int) -> Tuple[int, int]:
    """Factor n devices into the most-square (nx, ny) grid, nx <= ny.

    Near-square tiles minimise halo perimeter per tile (the analogue of
    picking a good actor-partitioning, except here it is bytes on ICI).
    """
    best = (1, n)
    for a in range(1, int(np.sqrt(n)) + 1):
        if n % a == 0:
            best = (a, n // a)
    return best


def factor2d_sliced(n: int, n_slices: int) -> Tuple[int, int]:
    """Most-square (nx, ny) such that slices can band into whole mesh rows:
    ny must divide the per-slice device count (so each slice fills complete
    rows and only N/S halos cross DCN)."""
    if n % n_slices:
        raise ValueError(f"{n} devices do not split over {n_slices} slices")
    per = n // n_slices
    best = None
    for ny in range(1, per + 1):
        if per % ny == 0:
            nx = n // ny
            if best is None or abs(nx - ny) <= abs(best[0] - best[1]):
                best = (nx, ny)  # ties resolve to nx <= ny, like factor2d
    return best


def slice_ids_of(devices: Sequence[jax.Device]) -> list:
    """Per-device slice index (DCN granule); devices without one (CPU fakes,
    single-slice TPUs) all report 0."""
    return [getattr(d, "slice_index", 0) or 0 for d in devices]


def order_devices_for_slices(
    devices: Sequence[jax.Device],
    shape: Tuple[int, int],
    slice_ids: Optional[Sequence[int]] = None,
) -> "np.ndarray":
    """Arrange devices into an (nx, ny) array so each mesh row holds devices
    of exactly one slice (slices own contiguous row *bands*).

    This is the multi-slice layout decision: the grid's row axis is cut
    across slices, so per generation the only traffic that crosses **DCN**
    is one north + one south halo strip per slice boundary; all other halo
    exchange (and everything on the column axis) rides **ICI**. The
    reference has no analogue — its one "interconnect" is the in-process
    Akka mailbox (SURVEY.md §2) — so this layout rule is the framework's
    DCN story, and it degrades to a plain reshape when there is one slice.
    """
    nx, ny = shape
    devices = list(devices)
    ids = list(slice_ids) if slice_ids is not None else slice_ids_of(devices)
    if len(ids) != len(devices):
        raise ValueError(f"{len(ids)} slice ids for {len(devices)} devices")
    groups: dict = {}
    for d, s in zip(devices, ids):
        groups.setdefault(s, []).append(d)
    if len(groups) == 1:
        return np.asarray(devices).reshape(nx, ny)
    sizes = {s: len(g) for s, g in groups.items()}
    per = next(iter(sizes.values()))
    if any(v != per for v in sizes.values()):
        raise ValueError(f"uneven devices per slice: {sizes}")
    rows_per_slice, rem = divmod(per, ny)
    if rem or rows_per_slice == 0:
        raise ValueError(
            f"mesh shape {shape}: each slice's {per} devices must fill whole "
            f"mesh rows (need {ny} per row) so slice boundaries align with "
            f"row bands and only N/S halos cross DCN"
        )
    if rows_per_slice * len(groups) != nx:
        raise ValueError(
            f"mesh shape {shape} incompatible with {len(groups)} slices of {per}"
        )
    bands = [
        np.asarray(groups[s]).reshape(rows_per_slice, ny)
        for s in sorted(groups)
    ]
    return np.vstack(bands)


def make_mesh(
    shape: Optional[Tuple[int, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    slice_ids: Optional[Sequence[int]] = None,
) -> Mesh:
    """A 2D mesh with axes (ROW_AXIS, COL_AXIS) over the given devices.

    Multi-slice device sets (distinct ``slice_index``) are laid out so
    slices form contiguous row bands — see :func:`order_devices_for_slices`.
    """
    import warnings

    devices = list(devices if devices is not None else jax.devices())
    ids = list(slice_ids) if slice_ids is not None else slice_ids_of(devices)
    n_slices = len(set(ids))
    if shape is None:
        if n_slices > 1 and len(devices) % n_slices == 0:
            shape = factor2d_sliced(len(devices), n_slices)
        else:
            shape = factor2d(len(devices))
    nx, ny = shape
    if nx * ny != len(devices):
        raise ValueError(f"mesh shape {shape} needs {nx * ny} devices, have {len(devices)}")
    try:
        arr = order_devices_for_slices(devices, (nx, ny), ids)
    except ValueError as e:
        if slice_ids is not None:
            raise  # caller asked for this exact banding; don't paper over it
        warnings.warn(
            f"slice-banded layout impossible for mesh {shape} "
            f"({n_slices} slices): {e}; falling back to unordered layout "
            "(halo exchange may cross DCN on both axes)",
            stacklevel=2,
        )
        arr = np.asarray(devices).reshape(nx, ny)
    return Mesh(arr, (ROW_AXIS, COL_AXIS))


def band_axis(mesh: Mesh):
    """The band runners' logical band axis: ROW_AXIS on an (.., nx, 1)
    mesh, the flattened (ROW_AXIS, COL_AXIS) tuple on a 2D spatial
    (sub)mesh — nx·ny full-width bands in x-major device order. The ONE
    definition shared by the sharded and batched band runners and their
    edge-code/exchange calls, so the flattening convention cannot drift.
    Returns (axis, n_bands)."""
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    axis = ROW_AXIS if ny == 1 else (ROW_AXIS, COL_AXIS)
    return axis, nx * ny


def ghost_halo_words(gens_per_exchange: int) -> int:
    """East/west ghost-zone depth in packed words for a width-k pipeline:
    ``ceil(k / 32)`` (ops/bitpack.py WORD). Horizontal edge corruption
    creeps 1 cell per in-block generation, so k generations need k cells
    = this many whole words of halo per side — word granularity is what
    lifts the old g <= 32 cap of the 1-word deep runner."""
    from ..ops import bitpack

    return -(-int(gens_per_exchange) // bitpack.WORD)


def ghost_fits(tile_rows: int, tile_words: int,
               gens_per_exchange: int) -> bool:
    """Whether a (tile_rows, tile_words) per-device packed tile can run
    the width-k ghost-zone pipeline: the boundary rings consumed per
    block are 2k rows and 2·ceil(k/32) words deep, and both must fit
    inside the tile (k > tile capacity is refused, not clamped)."""
    k = int(gens_per_exchange)
    if k < 1:
        return False
    hw = ghost_halo_words(k)
    return 2 * k <= int(tile_rows) and 2 * hw <= int(tile_words)


def best_mesh_shape(n: int, rows: int, words: int, *,
                    gens_per_exchange: int = 1) -> Optional[Tuple[int, int]]:
    """Most-square (nx, ny) factorization of ``n`` devices that divides a
    packed (rows, words) grid AND leaves tiles deep/wide enough for a
    width-``gens_per_exchange`` ghost-zone pipeline
    (``gens_per_exchange=0`` skips the capacity constraint — plain
    divisibility, for lock-step per-generation exchange). Deterministic
    in its inputs, so every process of a multi-controller fleet computes
    the same shape from the same roster — the elastic runtime's
    re-tiling decision after a shrink lives here, not in per-worker
    state. Returns None when no factorization fits (callers fall back to
    lock-step bands)."""
    best = None
    for nx in range(1, n + 1):
        if n % nx:
            continue
        ny = n // nx
        if rows % nx or words % ny:
            continue
        if (gens_per_exchange >= 1
                and not ghost_fits(rows // nx, words // ny,
                                   gens_per_exchange)):
            continue
        if best is None or abs(nx - ny) < abs(best[0] - best[1]):
            best = (nx, ny)
    return best


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that tiles a (H, W) or (H, W/32) grid 2D over the mesh."""
    return NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))


def check_divisible(shape: Tuple[int, int], mesh: Mesh) -> None:
    h, w = shape
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    if h % nx or w % ny:
        raise ValueError(
            f"grid {shape} not divisible by mesh ({nx}, {ny}); "
            f"pad the grid or pick a different mesh shape"
        )


def device_put_sharded_grid(grid: jax.Array, mesh: Mesh,
                            banded: bool = False) -> jax.Array:
    """Place a grid onto the mesh with 2D spatial tiling.

    Accepts (H, W) / (H, W/32) grids, or a (b, H, W/32) bit-plane stack
    (Generations packed layout) whose leading plane axis is replicated.
    ``banded=True`` places full-width row bands over the FLATTENED mesh
    instead (``P(('x', 'y'), None)``) — the layout the band-kernel runners
    use on 2D meshes (parallel/sharded.py); rows must divide by nx·ny.
    """
    if banded:
        nb = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
        if grid.shape[-2] % nb:
            raise ValueError(
                f"grid rows {grid.shape[-2]} not divisible into {nb} "
                f"full-width bands over the flattened mesh")
        spec = (P(None, (ROW_AXIS, COL_AXIS), None) if grid.ndim == 3
                else P((ROW_AXIS, COL_AXIS), None))
        return jax.device_put(grid, NamedSharding(mesh, spec))
    if grid.ndim == 3:
        check_divisible(grid.shape[1:], mesh)
        return jax.device_put(
            grid, NamedSharding(mesh, P(None, ROW_AXIS, COL_AXIS)))
    check_divisible(grid.shape, mesh)
    return jax.device_put(grid, grid_sharding(mesh))
