"""jax API compatibility for the sharded runners.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map``, renaming ``check_rep`` to ``check_vma`` on the way.
The runners are written against the graduated API; on older jax this
adapter serves the experimental implementation under the new spelling,
so every call site stays version-agnostic.
"""

from __future__ import annotations

import functools

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:
    from jax.experimental.shard_map import shard_map as _experimental

    @functools.wraps(_experimental)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        else:
            # the experimental checker predates replication rules for
            # control flow (a fori_loop body raises NotImplementedError:
            # "No replication rule for while"); the graduated API types
            # these fine, so match its permissiveness rather than make
            # every call site version-gate a static check
            kwargs.setdefault("check_rep", False)
        return _experimental(*args, **kwargs)
