"""SPMD sharded stepping: ``shard_map`` over a 2D mesh + halo exchange.

One jitted call = one (or n) global generations: each device holds a (h/nx,
w/ny) tile, exchanges halos over ICI (halo.py), and runs the same fused
stencil the single-device path uses (ops/packed.py, ops/stencil.py). The
generation barrier the reference implements by counting N·M actor replies in
GridCoordinator (SURVEY.md §4b) is implicit in the SPMD dataflow — the next
ppermute cannot start before the previous step's tiles exist.

Builders return jitted callables closed over (mesh, rule, topology); the
multi-step variants keep the whole generation loop on-device (halo exchange
inside ``lax.fori_loop``), so scaling runs pay zero host round-trips per
generation. All four builders share one per-tile generation body, so halo
ordering and stencil math exist in exactly one place per format.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from ._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.rules import CONWAY, Rule
from ..ops import packed as packed_ops
from ..ops._jit import BuiltRunner, register_builder, tracked_jit
from ..ops import stencil as stencil_ops
from ..ops.stencil import Topology
from .halo import (
    band_edge_code,
    exchange_cols,
    exchange_cols_stack,
    exchange_halo,
    exchange_halo_stack,
    exchange_rows,
    exchange_rows_stack,
)
from .mesh import COL_AXIS, ROW_AXIS, band_axis as _band_axis

_SPEC = P(ROW_AXIS, COL_AXIS)


def _tracked(run, runner: str, donate: bool, nargs: int = 1):
    """Jit a shard_map runner through the compile-accounting choke point
    (ops/_jit.tracked_jit) so sharded compiles become CompileEvents: a
    multi-device first tick used to hide its whole XLA compile inside
    StepMetrics.wall_seconds because these builders returned bare jits."""
    return tracked_jit(run, runner=runner,
                       donate_argnums=tuple(range(nargs)) if donate else ())


def _dense_ext_step(ext: jax.Array, rule: Rule) -> jax.Array:
    """One generation from a halo-extended unpacked tile."""
    return stencil_ops.apply_rule(
        ext[1:-1, 1:-1], stencil_ops.neighbor_counts_ext(ext), rule
    )


def _make_runner(
    mesh: Mesh,
    rule,
    topology: Topology,
    ext_step: Callable[[jax.Array, "Rule"], jax.Array],
    multi: bool,
    depth: int = 1,
    donate: bool = False,
    runner: str = "sharded.step",
) -> Callable:
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def generation(tile):
        return ext_step(exchange_halo(tile, nx, ny, topology, depth=depth), rule)

    if multi:
        @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
        def _run(tile, n):
            return jax.lax.fori_loop(0, n, lambda _, t: generation(t), tile)
    else:
        @partial(shard_map, mesh=mesh, in_specs=_SPEC, out_specs=_SPEC)
        def _run(tile):
            return generation(tile)

    # donation is opt-in (see ops/_jit.py): only buffer owners like Engine
    # should let a runner consume the incoming grid
    return _tracked(_run, runner, donate)


def make_step_packed(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
                     donate: bool = False) -> Callable:
    """Jitted one-generation step on a 2D-sharded packed grid."""
    return _make_runner(mesh, rule, topology, packed_ops.step_packed_ext,
                        multi=False, donate=donate,
                        runner="sharded.step_packed")


def make_multi_step_packed(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
                           donate: bool = False) -> Callable:
    """Jitted (grid, n) -> grid running n sharded generations on-device."""
    return _make_runner(mesh, rule, topology, packed_ops.step_packed_ext,
                        multi=True, donate=donate,
                        runner="sharded.multi_step_packed")


def make_multi_step_packed_sparse(
    mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
    donate: bool = False,
) -> Callable:
    """Sharded stepping with per-tile activity skipping.

    The distributed face of ops/sparse.py's idea: each device carries a
    1-element *changed-last-generation* flag next to its tile, the flags
    make the same two-phase halo trip as the grid (a 3×3 flag neighborhood
    costs 4 one-word ppermutes), and a tile whose whole flag neighborhood
    is quiet skips the stencil via ``lax.cond`` — GoL locality makes that
    exact, so still-life regions fall asleep per *device*. Unlike the
    single-device engine this supports TORUS too (halo exchange handles the
    wrap; no zero ring involved). Finer-than-device granularity stays the
    single-device engine's job.

    Returns jitted ``(grid, flags, n) -> (grid, flags)``; ``flags`` is an
    (nx, ny) uint32 array sharded one flag per device (use
    :func:`initial_flags`). Compute cost per active tile gains one
    tile-compare pass (the next generation's flag); quiet tiles pay only
    the halo exchange.
    """
    return _make_flagged_sparse(
        mesh, _SPEC,
        lambda tile, nx_, ny_: exchange_halo(tile, nx_, ny_, topology),
        lambda ext: packed_ops.step_packed_ext(ext, rule),
        topology, donate, runner="sharded.multi_step_packed_sparse")


def _make_flagged_sparse(mesh, state_spec, exchange, step_ext, topology,
                         donate, runner="sharded.sparse"):
    """The shared per-device activity-skipping runner for both layouts
    (2D bitboard, Generations plane stack). ``exchange(state, nx, ny)``
    runs UNCONDITIONALLY — halo ppermutes are collectives and every device
    must participate even while asleep; only the local stencil
    ``step_ext(ext)`` hides behind the ``lax.cond`` activity gate. The
    flags make their own (3, 3)-neighborhood trip."""
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def gen(state, flag):
        ext = exchange(state, nx, ny)
        fext = exchange_halo(flag, nx, ny, topology)  # (3, 3) neighborhood

        def do(_):
            new = step_ext(ext)
            changed = jnp.any(new != state).astype(jnp.uint32).reshape(1, 1)
            return new, changed

        def skip(_):
            # flag & 0 (not a fresh zeros constant) keeps the value tagged
            # as device-varying, matching do()'s outputs under shard_map
            return state, flag & 0

        return jax.lax.cond(jnp.sum(fext) > 0, do, skip, None)

    @partial(shard_map, mesh=mesh, in_specs=(state_spec, _SPEC, P()),
             out_specs=(state_spec, _SPEC))
    def _run(state, flag, n):
        return jax.lax.fori_loop(0, n, lambda _, c: gen(*c), (state, flag))

    return _tracked(_run, runner, donate, nargs=2)


def initial_tile_activity(packed: jax.Array, mesh: Mesh, tile_rows: int,
                          tile_words: int) -> jax.Array:
    """The global (H/tile_rows, Wp/tile_words) changed-flag map for
    :func:`make_multi_step_packed_sparse_tiled`, sharded over ``mesh`` like
    the grid: every tile containing a live cell starts 'changed'. uint32
    0/1 (the map makes ppermute halo trips)."""
    from jax.sharding import NamedSharding

    from ..ops import sparse as sparse_ops

    act = sparse_ops.tile_activity(packed, tile_rows, tile_words).astype(jnp.uint32)
    return jax.device_put(act, NamedSharding(mesh, _SPEC))


def make_multi_step_packed_sparse_tiled(
    mesh: Mesh,
    rule: Rule,
    topology: Topology = Topology.TORUS,
    *,
    tile_rows: int,
    tile_words: int,
    capacity: int | None = None,
    donate: bool = False,
) -> Callable:
    """Sharded stepping with PER-TILE activity skipping inside every shard.

    VERDICT round-2 item #5: :func:`make_multi_step_packed_sparse` skips at
    whole-device granularity, so a 65536² gun sharded over 8 devices keeps
    ~all devices awake. This runner composes the single-device engine's
    activity tiling (ops/sparse.py) *within* each device's shard: per
    generation each device

    1. halo-exchanges its grid tile (unconditional — collectives need every
       device) and a 1-tile-deep halo of its LOCAL activity map (a
       neighbor's edge-tile change must wake this device's edge tile);
    2. dilates the extended map into the candidate set (exact for 3×3
       rules: a tile can only change if its 3×3 tile-neighborhood did);
    3. gathers a static ``capacity`` of candidate windows, steps them as a
       vmapped batch, scatters the interiors back (the mirror of
       ops/sparse.py sparse_gen with the halo-extended shard as the padded
       grid); a device whose candidate count exceeds capacity takes one
       whole-shard dense generation instead (``lax.cond`` — per-device,
       collective-free branches, so sleepy devices stay cheap while a hot
       device overflows safely).

    ``tile_rows``/``tile_words`` are per-shard tile dims (use
    ops.sparse.auto_tile on the LOCAL shard shape); ``capacity`` defaults
    to a quarter of the local tile count, clamped to [32, 1024].

    Serves every packed-bitboard rule family: life-like 3x3 AND radius-r
    binary LtL (VERDICT r3 Weak #4) — the halo depth, window extension,
    and activity wake dilation all scale with the rule's influence radius
    exactly as in the single-device engine (ops/sparse.py _rule_halo /
    _wake_dilation).

    Returns jitted ``(grid, act, n) -> (grid, act)``; ``act`` is the
    sharded global tile map from :func:`initial_tile_activity`.
    """
    return _make_tiled_sparse(
        mesh, rule, topology, _SPEC, tile_rows, tile_words, capacity, donate,
        runner="sharded.multi_step_packed_sparse_tiled")


def make_multi_step_ltl_pallas(
    mesh: Mesh,
    rule,
    topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    block_rows: int | None = None,
    interpret: bool | None = None,
    donate: bool = False,
) -> Callable:
    """Row-band sharding over the radius-r LtL kernel: the LtL twin of
    :func:`make_multi_step_pallas` (same full-width-band contract — incl.
    the flattened band axis on 2D meshes — and SMEM edge-code DEAD
    closure; see that docstring), with the exchange depth and crop scaled
    to r·g rows (LtL influence travels r rows per generation). Returns
    jitted ``(grid, chunks) -> grid`` advancing ``chunks * g``
    generations, grid sharded P('x', None) / P(('x', 'y'), None)."""
    from ..ops.pallas_stencil import default_interpret, make_ltl_pallas_slab_step

    axis, nb = _band_axis(mesh)
    g = int(gens_per_exchange)
    hr = rule.radius * g
    if interpret is None:
        interpret = default_interpret()

    band_spec = P(axis, None)
    dead = topology is Topology.DEAD

    def chunk(tile):
        if hr > tile.shape[0]:  # static shapes: caught at trace time
            raise ValueError(
                f"radius*gens_per_exchange={hr} exceeds the per-device band "
                f"height {tile.shape[0]} (exchange_rows needs depth <= band "
                "rows)")
        ext = exchange_rows(tile, nb, topology, axis=axis, depth=hr)
        call = make_ltl_pallas_slab_step(
            rule, topology, ext.shape, gens=g, block_rows=block_rows,
            interpret=interpret, dead_band=dead)
        if dead:
            return call(ext, band_edge_code(nb, axis=axis))[hr:-hr]
        return call(ext)[hr:-hr]

    # check_vma=False: same scratch-DMA typing limitation as the other
    # band runners
    @partial(shard_map, mesh=mesh, in_specs=(band_spec, P()),
             out_specs=band_spec, check_vma=False)
    def _run(tile, chunks):
        return jax.lax.fori_loop(0, chunks, lambda _, t: chunk(t), tile)

    return _tracked(_run, "sharded.multi_step_ltl_pallas", donate)


def make_multi_step_ltl_planes(
    mesh: Mesh, rule, topology: Topology = Topology.TORUS,
    donate: bool = False,
) -> Callable:
    """Sharded multi-state (C >= 3) LtL on a (b, H, W/32) bit-plane stack:
    the radius-r face of :func:`make_multi_step_generations_packed` — per
    generation one stacked ppermute trip of r halo ROWS and one halo WORD
    per side (32 >= r cells; the asymmetric depth trick of
    make_multi_step_ltl_packed, stack form), then
    ops/packed_ltl.step_ltl_planes_ext. Jitted ``(planes, n) -> planes``
    sharded P(None, 'x', 'y')."""
    from ..ops.packed_generations import n_planes
    from ..ops.packed_ltl import step_ltl_planes_ext

    r = rule.radius
    b = n_planes(rule.states)
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    spec3 = P(None, ROW_AXIS, COL_AXIS)

    def generation(planes):
        if planes.shape[1] < r:  # static shapes: caught at trace time
            raise ValueError(
                f"per-device tile height {planes.shape[1]} smaller than "
                f"the rule radius {r}; use fewer mesh rows")
        ext = exchange_cols_stack(
            exchange_rows_stack(planes, nx, topology, depth=r),
            ny, topology, depth=1)
        return jnp.stack(step_ltl_planes_ext(
            tuple(ext[i] for i in range(b)), rule))

    @partial(shard_map, mesh=mesh, in_specs=(spec3, P()), out_specs=spec3)
    def _run(planes, n):
        return jax.lax.fori_loop(0, n, lambda _, t: generation(t), planes)

    return _tracked(_run, "sharded.multi_step_ltl_planes", donate)


def make_multi_step_generations_packed_sparse_tiled(
    mesh: Mesh,
    rule,
    topology: Topology = Topology.TORUS,
    *,
    tile_rows: int,
    tile_words: int,
    capacity: int | None = None,
    donate: bool = False,
) -> Callable:
    """Per-tile sharded sparse for (b, H, W/32) plane stacks: the
    multi-state twin of :func:`make_multi_step_packed_sparse_tiled` (same
    activity-map halo trip and candidate gather/step/scatter; windows
    carry all b planes, ONE stacked ppermute trip per generation).
    Decaying tiles keep themselves awake by changing, so the wake rule
    stays exact. Serves Generations rules AND multi-state C >= 3 LtL
    (radius-r halos/dilation, ops/sparse._step_window plane dispatch).
    Returns jitted ``(planes, act, n) -> (planes, act)``."""
    return _make_tiled_sparse(
        mesh, rule, topology, P(None, ROW_AXIS, COL_AXIS),
        tile_rows, tile_words, capacity, donate,
        runner="sharded.multi_step_generations_packed_sparse_tiled")


def _make_tiled_sparse(mesh, rule, topology, state_spec,
                       tile_rows, tile_words, capacity, donate,
                       runner="sharded.sparse_tiled"):
    """Shared per-tile sharded sparse builder for both layouts: the state
    is (h, w) or (b, h, w) per shard; the activity map is always the 2D
    local tile map. ops.sparse._step_window dispatches the stencil by
    rule family and ndim, so the layouts differ only in halo exchange and
    the plane axis of the scatter (the mirror of ops/sparse.py's ``lead``
    handling). Radius-r rules scale the grid halo to (r rows, 1 word) and
    the activity exchange/dilation to the tile-ring wake radius, exactly
    like the single-device engine.
    """
    from ..ops.sparse import (
        _births_from_nothing,
        _dilate,
        _rule_halo,
        _step_window,
        _wake_dilation,
    )

    if _births_from_nothing(rule):
        # same contract as the single-device SparseEngineState: under B0
        # every quiescent region births cells each generation, so a tile
        # seeded asleep (no live cells) would immediately be wrong
        raise ValueError(
            f"sparse backends cannot run birth-from-nothing rules "
            f"({rule.notation}): nothing ever sleeps — use the packed "
            "backend")
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    r, rw = _rule_halo(rule)
    dy, dx = _wake_dilation(rule, tile_rows, tile_words)

    def exchange(state):
        if state.ndim == 3:
            return exchange_cols_stack(
                exchange_rows_stack(state, nx, topology, depth=r),
                ny, topology, depth=rw)
        return exchange_cols(
            exchange_rows(state, nx, topology, depth=r), ny, topology,
            depth=rw)

    def gen(state, act):
        lead = state.shape[:-2]
        h, w = state.shape[-2:]
        nty, ntx = h // tile_rows, w // tile_words
        cap = capacity or max(32, min(1024, (nty * ntx) // 4 or 32))
        ext = exchange(state)
        aext = exchange_cols(
            exchange_rows(act, nx, topology, depth=dy), ny, topology,
            depth=dx)
        cand = _dilate(aext.astype(bool), wrap=False, dy=dy,
                       dx=dx)[dy:-dy, dx:-dx]
        n_cand = jnp.sum(cand)

        def sparse_branch(_):
            idx = jnp.nonzero(cand.ravel(), size=cap, fill_value=0)[0]
            valid = jnp.arange(cap) < n_cand
            tys, txs = idx // ntx, idx % ntx
            windows = jax.vmap(lambda ty, tx: jax.lax.dynamic_slice(
                ext, (0,) * len(lead) + (ty * tile_rows, tx * tile_words),
                lead + (tile_rows + 2 * r, tile_words + 2 * rw)))(tys, txs)
            stepped = jax.vmap(lambda win: _step_window(win, rule))(windows)
            olds = windows[..., r:-r, rw:-rw]
            changed = jnp.logical_and(
                (stepped != olds).any(axis=tuple(range(1, stepped.ndim))),
                valid)
            # one batched scatter; fill slots routed out of bounds (drop)
            row0 = jnp.where(valid, tys * tile_rows + r, h + 2 * r)
            col0 = jnp.where(valid, txs * tile_words + rw, w + 2 * rw)
            rows = row0[:, None, None] + jnp.arange(tile_rows)[None, :, None]
            cols = col0[:, None, None] + jnp.arange(tile_words)[None, None, :]
            if lead:
                # (K, b, tr, tw) -> (b, K, tr, tw): one spatial scatter
                # shared by every plane of the stack
                new_ext = ext.at[:, rows, cols].set(
                    jnp.moveaxis(stepped, 1, 0), mode="drop",
                    unique_indices=True)
            else:
                new_ext = ext.at[rows, cols].set(stepped, mode="drop",
                                                 unique_indices=True)
            new_act = jnp.zeros((nty, ntx), jnp.uint32)
            new_act = new_act.at[jnp.where(valid, tys, nty),
                                 jnp.where(valid, txs, ntx)].set(
                changed.astype(jnp.uint32), mode="drop", unique_indices=True)
            return new_ext[..., r:-r, rw:-rw], new_act

        def dense_branch(_):
            new = _step_window(ext, rule)
            t_old = state.reshape(*lead, nty, tile_rows, ntx, tile_words)
            t_new = new.reshape(*lead, nty, tile_rows, ntx, tile_words)
            changed = (t_old != t_new).any(
                axis=tuple(range(len(lead))) + (-3, -1))
            return new, changed.astype(jnp.uint32)

        return jax.lax.cond(n_cand <= cap, sparse_branch, dense_branch, None)

    @partial(shard_map, mesh=mesh, in_specs=(state_spec, _SPEC, P()),
             out_specs=(state_spec, _SPEC))
    def _run(state, act, n):
        return jax.lax.fori_loop(0, n, lambda _, c: gen(*c), (state, act))

    return _tracked(_run, runner, donate, nargs=2)


def make_multi_step_packed_deep(
    mesh: Mesh,
    rule: Rule,
    topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    donate: bool = False,
) -> Callable:
    """Communication-avoiding sharded stepping: one halo exchange per
    ``g = gens_per_exchange`` generations instead of per generation.

    The temporal-blocking idea of the Pallas kernel applied to the *comm*
    layer: each chunk exchanges a g-row-deep north/south halo plus the
    standard 1-word east/west halo (two-phase, corners correct), then
    advances the slab g generations locally with DEAD closure
    (ops/packed.py step_packed_slab). The slab shrinks 2 rows per
    generation, consuming the row halos exactly; horizontally, edge
    corruption from the open slab boundary creeps inward 1 cell per
    generation and is absorbed by the 32-cell halo *word* — the interior
    stays bit-exact for g <= 32 (the word width). Collective count drops
    from 4/gen to 4/g-gens: on DCN-crossing meshes (multi-slice,
    multi-host) this amortizes the per-collective latency g-fold for
    ~(2g/tile_rows) redundant compute.

    Measured caveat (results/weak_scaling_cpu8_G.json): XLA's CPU backend
    does not fuse the unrolled shrinking-slab chain the way it fuses the
    per-generation runner, materializing ~20 slab-sized intermediates per
    generation (~36x slower per-device on one CPU core). Use this runner
    when per-collective latency is the bottleneck, not for single-host
    throughput; cross-process bit-identity is proven in
    tests/test_multihost.py.

    Returns jitted ``(grid, chunks) -> grid`` advancing ``chunks * g``
    generations (``chunks`` is a traced scalar; g is static). Bit-identity
    with make_multi_step_packed is enforced in tests/test_sharding.py.
    """
    g = int(gens_per_exchange)
    if not 1 <= g <= 32:
        raise ValueError(
            f"gens_per_exchange must be in [1, 32] (the 32-cell halo word "
            f"bounds how far edge corruption may creep), got {g}")
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def _zero_exterior(slab, ix, iy, depth):
        # DEAD topology: cells beyond the global grid are *permanently*
        # dead, but the slab advance would happily evolve them (a birth
        # just outside the edge feeds back from the 2nd generation on —
        # same failure mode ops/pallas_stencil.py's _zero_edge_rows guards).
        # Re-zero the remaining exterior rows/halo-words of global-edge
        # tiles before every in-slab generation.
        L = slab.shape[0]
        rows = jax.lax.broadcasted_iota(jnp.int32, slab.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, slab.shape, 1)
        mask = ((ix == 0) & (rows < depth)) | ((ix == nx - 1) & (rows >= L - depth))
        mask |= ((iy == 0) & (cols < 1)) | ((iy == ny - 1) & (cols >= slab.shape[1] - 1))
        return jnp.where(mask, jnp.uint32(0), slab)

    def chunk(tile):
        if tile.shape[0] < g:  # shapes are static: caught at trace time
            raise ValueError(
                f"gens_per_exchange={g} exceeds the per-device tile height "
                f"{tile.shape[0]} (exchange_rows needs depth <= tile rows); "
                "use a deeper tile or a smaller G")
        ext = exchange_cols(
            exchange_rows(tile, nx, topology, depth=g), ny, topology, depth=1)
        if topology is Topology.DEAD:
            ix = jax.lax.axis_index(ROW_AXIS)
            iy = jax.lax.axis_index(COL_AXIS)
        for k in range(g):  # unrolled: the slab shape shrinks every gen
            if topology is Topology.DEAD:
                ext = _zero_exterior(ext, ix, iy, g - k)
            ext = packed_ops.step_packed_slab(ext, rule, Topology.DEAD)
        return ext[:, 1:-1]  # drop the (partly corrupted) halo words

    @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
    def _run(tile, chunks):
        return jax.lax.fori_loop(0, chunks, lambda _, t: chunk(t), tile)

    return _tracked(_run, "sharded.multi_step_packed_deep", donate)


def deep_exchange_bytes(grid_shape, mesh: Mesh, topology: Topology,
                        gens_per_exchange: int) -> int:
    """Interconnect bytes ONE deep-chunk exchange moves fleet-wide for a
    packed (H, Wp) grid on ``mesh``: depth-g row strips (g rows × tile
    words) per row-neighbor pair, then 1-word column strips of the
    row-*extended* tile (h + 2g rows) per column-neighbor pair — exactly
    the ``exchange_cols(exchange_rows(tile, depth=g), depth=1)`` trip of
    :func:`make_multi_step_packed_deep`'s chunk. Self-sends on a size-1
    TORUS axis count zero, matching
    utils/profiling.collective_permute_bytes; the contract gate asserts
    this model equals the compiled HLO's byte total exactly."""
    g = int(gens_per_exchange)
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    h, wq = int(grid_shape[-2]) // nx, int(grid_shape[-1]) // ny
    itemsize = 4  # packed uint32 words
    wrap = topology is Topology.TORUS
    row_sends = (2 * ny * (nx if wrap else nx - 1)) if nx > 1 else 0
    col_sends = (2 * nx * (ny if wrap else ny - 1)) if ny > 1 else 0
    return (row_sends * g * wq * itemsize
            + col_sends * (h + 2 * g) * itemsize)


def ghost_exchange_bytes(grid_shape, mesh: Mesh, topology: Topology,
                         gens_per_exchange: int) -> int:
    """Interconnect bytes ONE ghost-zone exchange moves fleet-wide for a
    packed (H, Wp) grid on ``mesh``: 2 row strips of k rows per
    row-neighbor pair plus 2 column strips of ceil(k/32) words (of the
    row-extended tile) per column-neighbor pair. Self-sends on a
    size-1 TORUS axis stay on-device and count zero, matching
    utils/profiling.collective_permute_bytes. This is the model side of
    the byte-accounting tests and the ``halo_bytes_total`` counter."""
    from .mesh import ghost_halo_words

    k = int(gens_per_exchange)
    hw = ghost_halo_words(k)
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    h, wq = int(grid_shape[-2]) // nx, int(grid_shape[-1]) // ny
    itemsize = 4  # packed uint32 words
    wrap = topology is Topology.TORUS
    row_sends = (2 * ny * (nx if wrap else nx - 1)) if nx > 1 else 0
    col_sends = (2 * nx * (ny if wrap else ny - 1)) if ny > 1 else 0
    return (row_sends * k * wq * itemsize
            + col_sends * hw * (h + 2 * k) * itemsize)


def make_multi_step_packed_ghost(
    mesh: Mesh,
    rule: Rule,
    topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    donate: bool = False,
    *,
    unroll_chunks: "int | None" = None,
) -> Callable:
    """Width-k ghost-zone pipeline on the 2D mesh: ONE halo exchange per
    k generations, issued so the ppermutes overlap interior compute.

    Where :func:`make_multi_step_packed_deep` re-exchanges the whole tile
    every chunk (compute idles while halos fly), this runner splits each
    k-generation block into *boundary-first* dataflow:

    1. advance the four boundary rings of the NEXT tile first — four
       shrinking slabs (ops/packed.py step_packed_slab) over the halo-
       extended edges, each cropped to its proven-exact core;
    2. issue the NEXT block's exchange from those fresh rings
       (halo.exchange_rows_parts / exchange_cols_parts — same two-phase
       corner contract as exchange_halo, column strips assembled from
       the row-extended edges so corners ride phase 2);
    3. only then advance the tile interior k generations — a subgraph
       with no data dependency on the in-flight ppermutes, so XLA is
       free to run the collectives and the interior stencil
       concurrently.

    Ghost-zone widths: k halo rows vertically (the shrinking slab
    consumes them exactly) and ``ceil(k/32)`` halo *words* horizontally
    (edge corruption creeps 1 cell per generation; whole words keep the
    packed layout aligned). That word granularity removes deep's
    g <= 32 cap — k is bounded only by the tile: 2k rows and
    2·ceil(k/32) words must fit (refused at trace time otherwise, see
    parallel/mesh.ghost_fits). DEAD topology re-zeroes the permanently-
    dead exterior of global-edge tiles before every in-block generation,
    exactly like the deep runner.

    Exchange count is structural: a run of ``chunks`` blocks performs
    exactly ``chunks`` exchanges (one prologue + one per non-final
    block; the final block computes straight out of its halos) — exactly
    k× fewer than the lock-step runner over the same k·chunks
    generations, provable from compiled HLO via
    utils/profiling.collective_permute_count on an unrolled build
    (``unroll_chunks=c`` swaps the traced-chunks fori_loop for c static
    blocks so the collectives are countable).

    Every call bumps the fleet observability plane: ``halo_exchanges_
    total``, ``halo_bytes_total`` (modeled interconnect bytes, matching
    collective_permute_bytes) and the per-chip ``halo_overlap_ratio``
    gauge (fraction of each block's stencil work that is interior — the
    share eligible to hide behind the in-flight exchange).

    Returns jitted ``(grid, chunks) -> grid`` advancing ``chunks * k``
    generations (``chunks`` traced, k static; chunks >= 1).
    Bit-identity with the single-device oracle is enforced in
    tests/test_ghost.py for TORUS and DEAD on band and 2D meshes.
    """
    from .halo import exchange_cols_parts, exchange_rows_parts
    from .mesh import ghost_halo_words

    k = int(gens_per_exchange)
    if k < 1:
        raise ValueError(f"gens_per_exchange must be >= 1, got {k}")
    hw = ghost_halo_words(k)
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def _zero_exterior(slab, *, top=0, bottom=0, left=0, right=0):
        # DEAD topology: the exterior is *permanently* dead but a slab
        # advance would evolve it (same feedback failure the deep runner
        # guards). top/bottom/left/right = exterior rows/words still
        # present at each edge of THIS slab; masked by the device's
        # global-edge position so interior tiles evolve halos freely.
        rows = jax.lax.broadcasted_iota(jnp.int32, slab.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, slab.shape, 1)
        ix = jax.lax.axis_index(ROW_AXIS)
        iy = jax.lax.axis_index(COL_AXIS)
        mask = jnp.zeros(slab.shape, bool)
        if top:
            mask |= (ix == 0) & (rows < top)
        if bottom:
            mask |= (ix == nx - 1) & (rows >= slab.shape[0] - bottom)
        if left:
            mask |= (iy == 0) & (cols < left)
        if right:
            mask |= (iy == ny - 1) & (cols >= slab.shape[1] - right)
        return jnp.where(mask, jnp.uint32(0), slab)

    def _advance(slab, *, vtop=False, vbot=False, left=False, right=False):
        # k shrinking-slab generations with DEAD closure; the v*/left/
        # right flags say which edges of THIS slab carry exterior halo
        # (vertical depth shrinks with the slab, word depth is constant)
        for j in range(k):  # unrolled: the slab shape shrinks every gen
            if topology is Topology.DEAD:
                slab = _zero_exterior(
                    slab,
                    top=(k - j) if vtop else 0,
                    bottom=(k - j) if vbot else 0,
                    left=hw if left else 0,
                    right=hw if right else 0)
            slab = packed_ops.step_packed_slab(slab, rule, Topology.DEAD)
        return slab

    def _block(ext, exchange: bool):
        # one k-generation block: (h+2k, w+2hw) haloed tile -> next
        # haloed tile (exchange=True) or the bare (h, w) tile (final
        # block). Boundary rings first, sends next, interior last.
        eh, ew = ext.shape
        h, w = eh - 2 * k, ew - 2 * hw
        # -- 1. boundary rings of the NEXT tile -------------------------
        # N/S strips span the full extended width (3k rows in, k out);
        # W/E strips cover the remaining middle rows (3hw words in, hw
        # out). Crops drop exactly the corruption-creep bound.
        ring_n = _advance(ext[:3 * k, :], vtop=True,
                          left=True, right=True)[:, hw:hw + w]
        ring_s = _advance(ext[eh - 3 * k:, :], vbot=True,
                          left=True, right=True)[:, hw:hw + w]
        ring_w = _advance(ext[k:k + h, :3 * hw], left=True)[:, hw:2 * hw]
        ring_e = _advance(ext[k:k + h, ew - 3 * hw:], right=True)[:, hw:2 * hw]
        # -- 2. next exchange, fed ONLY by the rings --------------------
        if exchange:
            north, south = exchange_rows_parts(ring_n, ring_s, nx, topology)
            # column strips of the row-extended next tile, assembled
            # from boundary pieces so the (k, hw) corners ride phase 2
            wcol = jnp.concatenate([
                north[:, :hw], ring_n[:, :hw], ring_w,
                ring_s[:, :hw], south[:, :hw]], axis=0)
            ecol = jnp.concatenate([
                north[:, w - hw:], ring_n[:, w - hw:], ring_e,
                ring_s[:, w - hw:], south[:, w - hw:]], axis=0)
            west, east = exchange_cols_parts(wcol, ecol, ny, topology)
        # -- 3. interior: independent of the in-flight ppermutes --------
        interior = _advance(ext[k:k + h, hw:hw + w])[:, hw:w - hw]
        tile = jnp.concatenate([
            ring_n,
            jnp.concatenate([ring_w, interior, ring_e], axis=1),
            ring_s], axis=0)
        if not exchange:
            return tile
        return jnp.concatenate([
            west, jnp.concatenate([north, tile, south], axis=0), east],
            axis=1)

    def _prologue(tile):
        h, w = tile.shape  # static: caught at trace time
        if 2 * k > h or 2 * hw > w:
            raise ValueError(
                f"gens_per_exchange={k} needs a per-device tile of at "
                f"least ({2 * k} rows, {2 * hw} words); tile is ({h}, {w})"
                " — use a smaller k, a coarser mesh, or a bigger grid")
        return exchange_cols(
            exchange_rows(tile, nx, topology, depth=k), ny, topology,
            depth=hw)

    if unroll_chunks is not None:
        c = int(unroll_chunks)
        if c < 1:
            raise ValueError(f"unroll_chunks must be >= 1, got {c}")

        @partial(shard_map, mesh=mesh, in_specs=_SPEC, out_specs=_SPEC)
        def _run_static(tile):
            ext = _prologue(tile)
            for _ in range(c - 1):
                ext = _block(ext, exchange=True)
            return _block(ext, exchange=False)

        return _tracked(_run_static, "sharded.multi_step_packed_ghost",
                        donate)

    @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
    def _run(tile, chunks):
        ext = _prologue(tile)
        ext = jax.lax.fori_loop(
            0, chunks - 1, lambda _, e: _block(e, exchange=True), ext)
        return _block(ext, exchange=False)

    jitted = _tracked(_run, "sharded.multi_step_packed_ghost", donate)

    # local share of the fleet's per-exchange wire bytes: each process
    # accounts its own devices so fleet-wide sums don't multiply-count
    try:
        local_frac = sum(
            1 for d in mesh.devices.flat
            if d.process_index == jax.process_index()) / (nx * ny)
    except (AttributeError, RuntimeError):
        local_frac = 1.0

    def run_ghost(grid, chunks):
        from ..obs.registry import REGISTRY

        try:
            c = int(chunks)
        except TypeError:  # traced: no host-side accounting possible
            c = None
        if c is not None and c < 1:
            # chunks=0 would still run the prologue exchange + final
            # block (= one full chunk); refuse instead of surprising
            raise ValueError(f"chunks must be >= 1, got {chunks}")
        shape = grid.shape  # before the call: donation may free the buffer
        out = jitted(grid, chunks)
        if c is None:
            return out
        REGISTRY.counter(
            "halo_exchanges_total",
            "ghost-zone halo exchanges performed (one per k-generation "
            "block)").inc(c)
        REGISTRY.counter(
            "halo_bytes_total",
            "modeled interconnect bytes moved by ghost-zone halo "
            "exchanges").inc(
            c * ghost_exchange_bytes(shape, mesh, topology, k) * local_frac)
        h, w = shape[-2] // nx, shape[-1] // ny
        interior = sum((h - 2 * j) * w for j in range(k))
        boundary = sum(2 * (3 * k - 2 * j) * (w + 2 * hw)
                       + 2 * (h - 2 * j) * 3 * hw for j in range(k))
        REGISTRY.gauge(
            "halo_overlap_ratio",
            "fraction of each ghost block's stencil work that is "
            "interior (overlappable with the in-flight exchange); "
            "per-chip").set(interior / (interior + boundary))
        return out

    run_ghost.lower = jitted.lower  # profiling lowers the real computation
    return run_ghost


def make_multi_step_pallas(
    mesh: Mesh,
    rule: Rule,
    topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    block_rows: int | None = None,
    interpret: bool | None = None,
    donate: bool = False,
) -> Callable:
    """Sharded stepping through the native Mosaic kernel: the flagship
    single-chip path (ops/pallas_stencil.py, 1.78e12 cell-updates/s on
    v5e-1) composed with multi-chip scaling.

    Decomposition is full-width row *bands* — the band spans the full grid
    width, which is what lets the kernel keep its two structural
    assumptions: the lane dimension stays a multiple of 128 words (a 2D
    tile's ``w/ny + 2`` halo-extended width almost never is), and the
    in-VMEM horizontal TORUS roll remains *globally* correct. Per chunk,
    each device ppermutes a depth-``g`` row halo (4 sends, two-phase not
    needed — one axis), then the slab kernel advances the extended band g
    generations on-chip and the g corrupted halo rows are cropped. Unlike
    make_multi_step_packed_deep, g is NOT capped at 32: there is no
    horizontal halo word to creep through, so g is bounded only by the band
    height (and by redundant-compute appetite, 2g rows/band/chunk).

    A 2D (nx, ny > 1) mesh — e.g. config #3's v5e-8 — is served by
    FLATTENING both axes into one logical band axis of nx·ny bands
    (``P(('x', 'y'), None)``; ppermute and the edge code ride the
    flattened axis, x-major). VERDICT r3 Missing #4 weighed this against
    shipping column halos into the slab: column halos break the kernel's
    lane alignment (the extended width ``w/ny + 2`` words is never a
    multiple of 128) and its global in-VMEM wrap, for a comm saving that
    is marginal at stencil depth 1 — whereas flattened bands keep the
    measured kernel *byte-identical* (same Mosaic program as (nx·ny, 1))
    and scale until the band height H/(nx·ny) drops below the exchange
    depth, which at the BASELINE configs (8192²/8 devices = 1024-row
    bands) is nowhere near. The fallback for shapes the kernel cannot
    take stays the XLA packed path (engine auto gates on band_supported).

    DEAD topology: the permanently-dead exterior of a global-edge band must
    be re-zeroed inside every in-slab generation (a birth just outside the
    edge feeds back from the 2nd generation on). The compiled kernel is one
    program shared by all devices, so edge-ness travels as *data*: each
    device passes a (1, 1) SMEM edge code (bit0 = holds the global top,
    bit1 = bottom) from its ``axis_index``, and the kernel's
    ``_zero_band_exterior`` realizes the dead exterior only where the code
    says so — interior bands evolve their halos freely, exactly as TORUS.

    Returns jitted ``(grid, chunks) -> grid`` advancing ``chunks * g``
    generations (``chunks`` traced, g static), grid sharded P('x', None).
    """
    from ..ops.pallas_stencil import default_interpret, make_pallas_slab_step

    axis, nb = _band_axis(mesh)
    g = int(gens_per_exchange)
    if interpret is None:
        interpret = default_interpret()

    band_spec = P(axis, None)

    dead = topology is Topology.DEAD

    def chunk(tile):
        if g > tile.shape[0]:  # static shapes: caught at trace time
            raise ValueError(
                f"gens_per_exchange={g} exceeds the per-device band height "
                f"{tile.shape[0]} (exchange_rows needs depth <= band rows)")
        ext = exchange_rows(tile, nb, topology, axis=axis, depth=g)
        call = make_pallas_slab_step(
            rule, topology, ext.shape, gens=g, block_rows=block_rows,
            interpret=interpret, dead_band=dead)
        if dead:
            return call(ext, band_edge_code(nb, axis=axis))[g:-g]
        return call(ext)[g:-g]

    # check_vma=False: jax's varying-manual-axes checker cannot type the
    # kernel's scratch-DMA primitives (dynamic_slice over a vma-free scratch
    # ref) and rejects the program on both the interpret and native paths;
    # correctness is carried by the bit-identity suite instead
    @partial(shard_map, mesh=mesh, in_specs=(band_spec, P()),
             out_specs=band_spec, check_vma=False)
    def _run(tile, chunks):
        return jax.lax.fori_loop(0, chunks, lambda _, t: chunk(t), tile)

    return _tracked(_run, "sharded.multi_step_pallas", donate)


def make_multi_step_banded(
    mesh: Mesh,
    rule,
    topology: Topology = Topology.TORUS,
    donate: bool = False,
) -> Callable:
    """Per-generation XLA stepping on full-width row bands over the
    flattened mesh axis: the remainder companion of the band-kernel
    runners. Where a 2D-tile runner would need the width to divide over
    the mesh's column axis, this runner keeps the band layout the kernel
    runners use (``P(('x', 'y'), None)`` on 2D meshes), so the n % g
    remainder generations of a band-kernel engine never force a reshard
    or a width constraint the bulk path doesn't have.

    Per generation: one ppermute trip of depth-d row strips along the
    flattened axis (d = rule radius; one stacked trip for Generations
    planes), then one shrinking-slab step — the band spans the full grid
    width, so the horizontal closure is the *global* topology applied
    in-tile and no column phase exists. Vertical DEAD closure at the slab
    edge coincides with ppermute's zero-fill for absent sources, which is
    exactly the all-dead global boundary. Dispatches on rule family:
    binary bitboard (H, W/32), Generations plane stack (b, H, W/32),
    radius-r LtL bitboard. Returns jitted ``(state, n) -> state``."""
    from ..models.generations import GenRule
    from ..models.ltl import LtLRule

    axis, nb = _band_axis(mesh)

    def _need(tile_rows: int, depth: int) -> None:
        if depth > tile_rows:  # static shapes: caught at trace time
            raise ValueError(
                f"band height {tile_rows} smaller than the exchange depth "
                f"{depth}; use fewer devices")

    if isinstance(rule, LtLRule):
        from ..ops.packed_ltl import step_ltl_packed_slab

        r = rule.radius
        spec = P(axis, None)

        def generation(tile):
            _need(tile.shape[0], r)
            ext = exchange_rows(tile, nb, topology, axis=axis, depth=r)
            return step_ltl_packed_slab(ext, rule, topology)
    elif isinstance(rule, GenRule):
        from ..ops.packed_generations import n_planes, step_planes_slab

        b = n_planes(rule.states)
        spec = P(None, axis, None)

        def generation(planes):
            _need(planes.shape[1], 1)
            ext = exchange_rows_stack(planes, nb, topology, axis=axis)
            return jnp.stack(step_planes_slab(
                tuple(ext[i] for i in range(b)), rule, topology))
    else:
        spec = P(axis, None)

        def generation(tile):
            _need(tile.shape[0], 1)
            ext = exchange_rows(tile, nb, topology, axis=axis)
            return packed_ops.step_packed_slab(ext, rule, topology)

    @partial(shard_map, mesh=mesh, in_specs=(spec, P()), out_specs=spec)
    def _run(state, n):
        return jax.lax.fori_loop(0, n, lambda _, t: generation(t), state)

    return _tracked(_run, "sharded.multi_step_banded", donate)


def make_multi_step_generations_packed_sparse(
    mesh: Mesh, rule, topology: Topology = Topology.TORUS,
    donate: bool = False,
) -> Callable:
    """Per-device activity skipping for the Generations plane stack: the
    multi-state face of :func:`make_multi_step_packed_sparse` (same
    1-element changed-flag per device, same 3×3 flag-neighborhood wake
    rule — exact for Generations too, since a cell's next state depends
    only on its own state and its 3×3 alive neighborhood; decaying tiles
    keep themselves awake by changing). Returns jitted
    ``(planes, flags, n) -> (planes, flags)`` on a (b, H, W/32) stack
    sharded P(None, 'x', 'y')."""
    from ..ops.packed_generations import n_planes, step_planes_ext

    b = n_planes(rule.states)
    return _make_flagged_sparse(
        mesh, P(None, ROW_AXIS, COL_AXIS),
        lambda planes, nx, ny: exchange_halo_stack(planes, nx, ny, topology),
        lambda ext: jnp.stack(step_planes_ext(
            [ext[i] for i in range(b)], rule)),
        topology, donate,
        runner="sharded.multi_step_generations_packed_sparse")


def make_multi_step_generations_pallas(
    mesh: Mesh,
    rule,
    topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    block_rows: int | None = None,
    interpret: bool | None = None,
    donate: bool = False,
) -> Callable:
    """Row-band sharding over the Generations bit-plane kernel: the
    multi-state twin of :func:`make_multi_step_pallas` (same
    full-width-band contract — incl. the flattened band axis on 2D meshes
    — same depth-g exchange/crop scheme, same SMEM edge-code realization
    of DEAD vertical closure; see that docstring), with ONE stacked
    ppermute per side per chunk carrying all b planes
    (halo.exchange_rows_stack). Returns jitted ``(planes, chunks) ->
    planes`` on a (b, H, W/32) stack sharded P(None, 'x', None) /
    P(None, ('x', 'y'), None), advancing ``chunks * g`` generations."""
    from ..ops.pallas_stencil import default_interpret, make_pallas_gen_slab_step

    axis, nb = _band_axis(mesh)
    g = int(gens_per_exchange)
    if interpret is None:
        interpret = default_interpret()

    spec3 = P(None, axis, None)

    dead = topology is Topology.DEAD

    def chunk(planes):
        if g > planes.shape[1]:  # static shapes: caught at trace time
            raise ValueError(
                f"gens_per_exchange={g} exceeds the per-device band height "
                f"{planes.shape[1]}")
        ext = exchange_rows_stack(planes, nb, topology, axis=axis, depth=g)
        call = make_pallas_gen_slab_step(
            rule, topology, ext.shape, gens=g, block_rows=block_rows,
            interpret=interpret, dead_band=dead)
        if dead:
            return call(ext, band_edge_code(nb, axis=axis))[:, g:-g]
        return call(ext)[:, g:-g]

    # check_vma=False: same scratch-DMA typing limitation as the binary
    # band runner
    @partial(shard_map, mesh=mesh, in_specs=(spec3, P()), out_specs=spec3,
             check_vma=False)
    def _run(planes, chunks):
        return jax.lax.fori_loop(0, chunks, lambda _, t: chunk(t), planes)

    return _tracked(_run, "sharded.multi_step_generations_pallas", donate)


def make_multi_step_elementary_sharded(
    mesh: Mesh,
    rule,
    topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    donate: bool = False,
) -> Callable:
    """Sharded 1D (elementary Wolfram) stepping: context parallelism for
    the family's "long context" — a huge row, or an ensemble of them.

    Layout: (H, W/32) packed, rows = independent universes (pure data
    parallelism over the mesh's row axis — zero communication), width
    sharded over the column axis. Per chunk each device ppermutes ONE halo
    word (32 cells) per side along the column axis, then advances
    ``g = gens_per_exchange`` generations locally with open (DEAD) closure
    at the slab ends: corruption creeps inward 1 cell per generation from
    the cropped slab edge, so the 32-cell halo word absorbs it exactly for
    g <= 32 — the 1D face of make_multi_step_packed_deep's horizontal
    trick. Collectives drop from 2/generation to 2/chunk.

    Global DEAD topology: the leftmost/rightmost devices' halo words are
    permanently-dead exterior, re-zeroed before every in-slab generation
    (a birth just outside the edge would otherwise feed back from the 2nd
    generation on) — gated by the same runtime edge code the band kernels
    use (halo.band_edge_code, column-axis form).

    Returns jitted ``(grid, chunks) -> grid`` advancing ``chunks * g``
    generations, sharded P('x', 'y').
    """
    from ..ops.elementary import step_elementary

    g = int(gens_per_exchange)
    if not 1 <= g <= 32:
        raise ValueError(
            f"gens_per_exchange must be in [1, 32] (the 32-cell halo word "
            f"bounds how far edge corruption may creep), got {g}")
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def chunk(tile):
        # one word per side along the column axis (corner phases don't
        # exist in 1D; rows never talk to each other) — the same public
        # trip the 2D runners use
        ext = exchange_cols(tile, ny, topology)
        if topology is Topology.DEAD:
            code = band_edge_code(ny, axis=COL_AXIS)[0, 0]
            cols = jax.lax.broadcasted_iota(jnp.int32, ext.shape, 1)
            exterior = ((((code & 1) == 1) & (cols == 0))
                        | (((code & 2) == 2) & (cols == ext.shape[1] - 1)))

            def body(_, s):
                s = jnp.where(exterior, jnp.uint32(0), s)
                return step_elementary(s, rule=rule, topology=Topology.DEAD)
        else:
            def body(_, s):
                return step_elementary(s, rule=rule, topology=Topology.DEAD)
        ext = jax.lax.fori_loop(0, g, body, ext)
        return ext[:, 1:-1]

    @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
    def _run(tile, chunks):
        return jax.lax.fori_loop(0, chunks, lambda _, t: chunk(t), tile)

    return _tracked(_run, "sharded.multi_step_elementary_sharded", donate)


def initial_flags(mesh: Mesh) -> jax.Array:
    """All-active (nx, ny) flag array, sharded one element per device."""
    from jax.sharding import NamedSharding

    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    return jax.device_put(
        jnp.ones((nx, ny), jnp.uint32), NamedSharding(mesh, _SPEC)
    )


def make_multi_step_generations(mesh: Mesh, rule, topology: Topology = Topology.TORUS,
                                donate: bool = False) -> Callable:
    """Jitted (grid, n) -> grid for multi-state Generations rules: the same
    halo machinery, a different per-tile step (ops/generations.py)."""
    from ..ops.generations import step_generations_ext

    return _make_runner(mesh, rule, topology, step_generations_ext, multi=True,
                        donate=donate,
                        runner="sharded.multi_step_generations")


def make_multi_step_ltl_packed(mesh: Mesh, rule, topology: Topology = Topology.TORUS,
                               donate: bool = False) -> Callable:
    """Sharded bit-sliced LtL on packed bitboards: per generation, each
    tile exchanges r halo *rows* and one halo *word* (32 >= r cells — the
    same asymmetric depth trick the communication-avoiding runner uses),
    then steps via ops/packed_ltl.step_ltl_packed_ext. Jitted
    ``(grid, n) -> grid`` on a (H, W/32) uint32 sharded grid."""
    from ..ops.packed_ltl import step_ltl_packed_ext

    r = rule.radius
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def generation(tile):
        if tile.shape[0] < r:  # static shapes: caught at trace time
            raise ValueError(
                f"per-device tile height {tile.shape[0]} smaller than the "
                f"rule radius {r}; use fewer mesh rows")
        ext = exchange_cols(
            exchange_rows(tile, nx, topology, depth=r), ny, topology, depth=1)
        return step_ltl_packed_ext(ext, rule)

    @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
    def _run(tile, n):
        return jax.lax.fori_loop(0, n, lambda _, t: generation(t), tile)

    return _tracked(_run, "sharded.multi_step_ltl_packed", donate)


def make_multi_step_generations_packed(
    mesh: Mesh, rule, topology: Topology = Topology.TORUS,
    donate: bool = False,
) -> Callable:
    """Sharded bit-plane Generations: the (b, H, W/32) plane stack shards
    as P(None, 'x', 'y'); each generation moves ONE four-send halo trip
    for all b planes (halo.exchange_halo_stack) and steps via
    ops/packed_generations.step_planes_ext. Jitted ``(planes, n) -> planes``."""
    from ..ops.packed_generations import n_planes, step_planes_ext

    b = n_planes(rule.states)
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]
    spec3 = P(None, ROW_AXIS, COL_AXIS)

    def generation(planes):
        ext = exchange_halo_stack(planes, nx, ny, topology)
        return jnp.stack(step_planes_ext([ext[i] for i in range(b)], rule))

    @partial(shard_map, mesh=mesh, in_specs=(spec3, P()), out_specs=spec3)
    def _run(planes, n):
        return jax.lax.fori_loop(0, n, lambda _, t: generation(t), planes)

    return _tracked(_run, "sharded.multi_step_generations_packed", donate)


def make_multi_step_ltl(mesh: Mesh, rule, topology: Topology = Topology.TORUS,
                        donate: bool = False) -> Callable:
    """Jitted (grid, n) -> grid for radius-r Larger-than-Life rules: the
    halo exchange ships depth-r strips (halo.py's two-phase trip keeps the
    r×r corner blocks correct with 4 sends), the per-tile step is the
    log-tree window-sum path (ops/ltl.py). Tiles must be at least r cells in each dim."""
    from ..ops.ltl import step_ltl_ext

    return _make_runner(
        mesh, rule, topology, step_ltl_ext, multi=True, depth=rule.radius,
        donate=donate, runner="sharded.multi_step_ltl",
    )


def make_step_dense(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
                    donate: bool = False) -> Callable:
    """Jitted sharded step on an unpacked (H, W) uint8 grid (debug path)."""
    return _make_runner(mesh, rule, topology, _dense_ext_step, multi=False,
                        donate=donate, runner="sharded.step_dense")


def make_multi_step_dense(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
                          donate: bool = False) -> Callable:
    return _make_runner(mesh, rule, topology, _dense_ext_step, multi=True,
                        donate=donate, runner="sharded.multi_step_dense")


# -- contract-gate registrations (ops/_jit.py BUILDERS) ----------------------
#
# Zero-arg factories the HLO contract gate (analysis/contracts.py,
# scripts/contract_check.py) enumerates: each builds a donation-enabled
# runner on a small mesh with a deterministically-seeded example grid
# (the tests/test_ghost.py harness idiom) and states the invariants to
# prove against its compiled HLO. Registration is a dict insert; meshes
# and grids are built only when the gate calls the factory.


def _contract_example(mesh_shape=(2, 2), grid=(64, 128), *,
                      packed=True, banded=False, seed=7):
    import numpy as np

    from ..ops import bitpack
    from . import mesh as mesh_lib

    n = mesh_shape[0] * mesh_shape[1]
    m = mesh_lib.make_mesh(mesh_shape, jax.devices()[:n])
    rng = np.random.default_rng(seed)
    g = jnp.asarray(rng.integers(0, 2, size=grid, dtype=np.uint8))
    placed = mesh_lib.device_put_sharded_grid(
        bitpack.pack(g) if packed else g, m, banded=banded)
    return m, placed


@register_builder("sharded.step_packed", tags=("sharded", "packed"))
def _contract_step_packed():
    m, p = _contract_example()
    return BuiltRunner(
        lowerable=make_step_packed(m, CONWAY, Topology.TORUS, donate=True),
        example_args=(p,), donated_argnums=(0,), mesh=m, out_spec=_SPEC)


@register_builder("sharded.multi_step_packed", tags=("sharded", "packed"))
def _contract_multi_step_packed():
    m, p = _contract_example()
    return BuiltRunner(
        lowerable=make_multi_step_packed(m, CONWAY, Topology.TORUS,
                                         donate=True),
        example_args=(p, 8), donated_argnums=(0,), mesh=m, out_spec=_SPEC)


@register_builder("sharded.step_dense", tags=("sharded", "dense"))
def _contract_step_dense():
    m, g = _contract_example(packed=False)
    return BuiltRunner(
        lowerable=make_step_dense(m, CONWAY, Topology.TORUS, donate=True),
        example_args=(g,), donated_argnums=(0,), mesh=m, out_spec=_SPEC)


@register_builder("sharded.multi_step_dense", tags=("sharded", "dense"))
def _contract_multi_step_dense():
    m, g = _contract_example(packed=False)
    return BuiltRunner(
        lowerable=make_multi_step_dense(m, CONWAY, Topology.TORUS,
                                        donate=True),
        example_args=(g, 8), donated_argnums=(0,), mesh=m, out_spec=_SPEC)


@register_builder("sharded.multi_step_packed_sparse",
                  tags=("sharded", "packed", "sparse"))
def _contract_multi_step_packed_sparse():
    m, p = _contract_example()
    return BuiltRunner(
        lowerable=make_multi_step_packed_sparse(m, CONWAY, Topology.TORUS,
                                                donate=True),
        example_args=(p, initial_flags(m), 8), donated_argnums=(0, 1),
        mesh=m, out_spec=_SPEC)


@register_builder("sharded.multi_step_packed_deep",
                  tags=("sharded", "packed", "comm-avoiding"))
def _contract_multi_step_packed_deep():
    g = 8
    m, p = _contract_example()
    return BuiltRunner(
        lowerable=make_multi_step_packed_deep(
            m, CONWAY, Topology.TORUS, gens_per_exchange=g, donate=True),
        example_args=(p, 1), donated_argnums=(0,), mesh=m, out_spec=_SPEC,
        # the fori_loop body carries exactly one chunk exchange, so the
        # whole program's collective bytes equal one exchange's model
        expected_collective_bytes=deep_exchange_bytes(
            p.shape, m, Topology.TORUS, g),
        collective_model=f"deep_exchange_bytes(k={g})")


@register_builder("sharded.multi_step_packed_ghost",
                  tags=("sharded", "packed", "comm-avoiding"))
def _contract_multi_step_packed_ghost():
    k = 4
    m, p = _contract_example()
    return BuiltRunner(
        # unroll_chunks=1: the prologue is the program's only exchange
        # (the final block computes straight out of its halos), so the
        # byte model covers the whole HLO
        lowerable=make_multi_step_packed_ghost(
            m, CONWAY, Topology.TORUS, gens_per_exchange=k, donate=True,
            unroll_chunks=1),
        example_args=(p,), donated_argnums=(0,), mesh=m, out_spec=_SPEC,
        expected_collective_bytes=ghost_exchange_bytes(
            p.shape, m, Topology.TORUS, k),
        collective_model=f"ghost_exchange_bytes(k={k})")


@register_builder("sharded.multi_step_banded", tags=("sharded", "packed"))
def _contract_multi_step_banded():
    m, p = _contract_example(banded=True)
    # band out_spec depends on the mesh's flattened axis: no injection
    # seam, the pinned-count contract still applies
    return BuiltRunner(
        lowerable=make_multi_step_banded(m, CONWAY, Topology.TORUS,
                                         donate=True),
        example_args=(p, 8), donated_argnums=(0,), mesh=m)
