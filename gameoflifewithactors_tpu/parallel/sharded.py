"""SPMD sharded stepping: ``shard_map`` over a 2D mesh + halo exchange.

One jitted call = one (or n) global generations: each device holds a (h/nx,
w/ny) tile, exchanges halos over ICI (halo.py), and runs the same fused
stencil the single-device path uses (ops/packed.py, ops/stencil.py). The
generation barrier the reference implements by counting N·M actor replies in
GridCoordinator (SURVEY.md §4b) is implicit in the SPMD dataflow — the next
ppermute cannot start before the previous step's tiles exist.

Builders return jitted callables closed over (mesh, rule, topology); the
multi-step variants keep the whole generation loop on-device (halo exchange
inside ``lax.fori_loop``), so scaling runs pay zero host round-trips per
generation. All four builders share one per-tile generation body, so halo
ordering and stencil math exist in exactly one place per format.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..models.rules import Rule
from ..ops import packed as packed_ops
from ..ops import stencil as stencil_ops
from ..ops.stencil import Topology
from .halo import exchange_halo
from .mesh import COL_AXIS, ROW_AXIS

_SPEC = P(ROW_AXIS, COL_AXIS)


def _dense_ext_step(ext: jax.Array, rule: Rule) -> jax.Array:
    """One generation from a halo-extended unpacked tile."""
    return stencil_ops.apply_rule(
        ext[1:-1, 1:-1], stencil_ops.neighbor_counts_ext(ext), rule
    )


def _make_runner(
    mesh: Mesh,
    rule: Rule,
    topology: Topology,
    ext_step: Callable[[jax.Array, Rule], jax.Array],
    multi: bool,
) -> Callable:
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def generation(tile):
        return ext_step(exchange_halo(tile, nx, ny, topology), rule)

    if multi:
        @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
        def _run(tile, n):
            return jax.lax.fori_loop(0, n, lambda _, t: generation(t), tile)
    else:
        @partial(shard_map, mesh=mesh, in_specs=_SPEC, out_specs=_SPEC)
        def _run(tile):
            return generation(tile)

    return jax.jit(_run, donate_argnums=0)


def make_step_packed(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS) -> Callable:
    """Jitted one-generation step on a 2D-sharded packed grid."""
    return _make_runner(mesh, rule, topology, packed_ops.step_packed_ext, multi=False)


def make_multi_step_packed(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS) -> Callable:
    """Jitted (grid, n) -> grid running n sharded generations on-device."""
    return _make_runner(mesh, rule, topology, packed_ops.step_packed_ext, multi=True)


def make_step_dense(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS) -> Callable:
    """Jitted sharded step on an unpacked (H, W) uint8 grid (debug path)."""
    return _make_runner(mesh, rule, topology, _dense_ext_step, multi=False)


def make_multi_step_dense(mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS) -> Callable:
    return _make_runner(mesh, rule, topology, _dense_ext_step, multi=True)
