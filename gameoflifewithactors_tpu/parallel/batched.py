"""Batch-of-universes data parallelism (the DP axis of SURVEY.md §3).

The reference has no batch concept [ABSENT] — one actor system is one
universe. Here a leading batch axis turns the framework into a rule-sweep /
ensemble machine: (B, H, W/32) grids shard as P('b', 'x', 'y') over a 3D
mesh — batch members are embarrassingly parallel (pure DP, no collectives
on 'b'), while each member's tiles still exchange halos over the spatial
axes. Inside the per-device tile the spatial step is vmapped over the local
batch, so the same core plane-extraction code serves 1 universe or 1000.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from ._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.rules import CONWAY, Rule
from ..ops.packed import step_packed_ext
from ..ops.stencil import Topology
from ..ops._jit import BuiltRunner, register_builder, tracked_jit
from .halo import exchange_halo
from .mesh import COL_AXIS, ROW_AXIS

BATCH_AXIS = "b"
_SPEC = P(BATCH_AXIS, ROW_AXIS, COL_AXIS)


def make_batch_mesh(
    shape: Tuple[int, int, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A (b, x, y) mesh: batch-parallel replicas of spatial tile grids."""
    devices = list(devices if devices is not None else jax.devices())
    nb, nx, ny = shape
    if nb * nx * ny != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {nb * nx * ny} devices, have {len(devices)}"
        )
    return Mesh(
        np.asarray(devices).reshape(nb, nx, ny), (BATCH_AXIS, ROW_AXIS, COL_AXIS)
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, _SPEC)


def batch_band_sharding(mesh: Mesh) -> NamedSharding:
    """Placement for the DP × band-kernel runner on a (nb, nx, ny > 1)
    mesh: rows of every universe split into nx·ny full-width bands over
    the flattened spatial axes (mirrors mesh.device_put_sharded_grid's
    ``banded`` layout, batch axis in front)."""
    return NamedSharding(mesh, P(BATCH_AXIS, (ROW_AXIS, COL_AXIS), None))


def make_multi_step_packed_batched(
    mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
    donate: bool = False, masked: bool = False,
) -> Callable:
    """Jitted (grids, n) -> grids over a (B, H, W/32) packed batch.

    With ``masked=True`` the runner takes ``(grids, n, mask)`` where
    ``mask`` is a (B,) uint32 occupancy vector: universes with mask 0 are
    frozen (their words pass through every generation unchanged) while
    the rest step normally. This is the serving layer's lane contract —
    dead/idle session slots ride along in the batch at zero semantic
    cost, so a lane never needs a retrace to change which slots are live
    (the mask is a runtime operand, not part of the jit signature)."""
    nx, ny = mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS]

    def universe_gen(tile):
        return step_packed_ext(exchange_halo(tile, nx, ny, topology), rule)

    if masked:
        @partial(shard_map, mesh=mesh,
                 in_specs=(_SPEC, P(), P(BATCH_AXIS)), out_specs=_SPEC)
        def _run_masked(tiles, n, mask):
            gen = jax.vmap(universe_gen)
            live = mask[:, None, None] != 0

            def body(_, t):
                # frozen slots still pay the stencil FLOPs (branch-free
                # dataflow); the select keeps their words bit-identical
                return jax.numpy.where(live, gen(t), t)

            return jax.lax.fori_loop(0, n, body, tiles)

        return tracked_jit(
            _run_masked, runner="batched.multi_step_packed_batched_masked",
            donate_argnums=(0,) if donate else ())

    @partial(shard_map, mesh=mesh, in_specs=(_SPEC, P()), out_specs=_SPEC)
    def _run(tiles, n):
        gen = jax.vmap(universe_gen)
        return jax.lax.fori_loop(0, n, lambda _, t: gen(t), tiles)

    # donation opt-in: see ops/_jit.py for why consuming the caller's batch
    # by default is a TPU-only footgun
    return tracked_jit(_run, runner="batched.multi_step_packed_batched",
                       donate_argnums=(0,) if donate else ())


# the paged runner's neighbor-gather order: the 8 tile neighbors of a
# pool slot, row-major. OPPOSITE[i] == 7 - i (the reciprocal direction) —
# the page-table maintenance in memory/paged.py leans on that symmetry
# when it back-links a freshly allocated page into its neighbors' rows.
PAGED_NEIGHBORS = ((-1, -1), (-1, 0), (-1, 1), (0, -1),
                   (0, 1), (1, -1), (1, 0), (1, 1))


def make_multi_step_paged(
    rule, tile_rows: Optional[int] = None, tile_words: Optional[int] = None,
    *, donate: bool = True,
) -> Callable:
    """The paged tile-pool runner: jitted ``(tiles, n, neighbors, mask)
    -> (tiles, changed, occupied)`` stepping ONE batch of physical tiles
    per generation regardless of which logical session owns them.

    - ``tiles`` is the pool's (B, planes, tile_rows, tile_words) uint32
      slab (memory/pool.py; planes = ops.sparse.rule_layout(rule)[0]).
      Slot 0 is the canonical dead tile: all-zero, never masked live.
    - ``neighbors`` is the on-device face of the page tables: (B, 8)
      int32 slot ids in :data:`PAGED_NEIGHBORS` order. Halos are
      resolved by *gathering* the 8 neighbor tiles' edge strips —
      missing pages point at slot 0, whose zero content IS the DEAD
      closure, and TORUS sessions simply wrap their coordinates when
      building the table, so topology (and universe bounds, including
      "none") is runtime data: one executable serves every geometry.
    - ``mask`` is the (B,) uint32 occupancy vector of
      :func:`make_multi_step_packed_batched` — slots not being stepped
      (free, dead, or owned by a session with no debt) pass through
      bit-identical, so page allocation/retirement never retraces.

    Returns the advanced pool plus two (B,) bool vectors: ``changed``
    (slot differed from its input in ANY generation — the
    changed-last-chunk wake flag that drives page activation) and
    ``occupied`` (slot holds any live bit at exit — all-dead AND
    unchanged pages outside the wake ring are reclaimable). The caller
    reads both back between chunks; that one small fetch is the paged
    analogue of the sparse engine's generations-completed scalar.
    """
    import jax.numpy as jnp

    from ..ops import sparse as _sp

    tile_rows = int(tile_rows or _sp.DEFAULT_TILE_ROWS)
    tile_words = int(tile_words or _sp.DEFAULT_TILE_WORDS)
    planes, ndim = _sp.rule_layout(rule)
    r, rw = _sp.rule_halo(rule)
    if r > tile_rows or rw > tile_words:
        raise ValueError(
            f"rule halo ({r} rows, {rw} words) exceeds the slab geometry "
            f"({tile_rows} rows, {tile_words} words): a neighbor gather "
            "can reach one tile ring, no further — grow the slab")

    ext_step = _sp._step_fns(rule, ndim)[0]

    def _window(t, nbr):
        # slice each direction's edge strip FIRST, then gather rows by
        # neighbor slot: the gather moves (B, planes, r, ·) strips, not
        # whole tiles
        def take(i, strip):
            return strip[nbr[:, i]]

        top = jnp.concatenate(
            [take(0, t[..., -r:, -rw:]), take(1, t[..., -r:, :]),
             take(2, t[..., -r:, :rw])], axis=-1)
        mid = jnp.concatenate(
            [take(3, t[..., :, -rw:]), t, take(4, t[..., :, :rw])], axis=-1)
        bot = jnp.concatenate(
            [take(5, t[..., :r, -rw:]), take(6, t[..., :r, :]),
             take(7, t[..., :r, :rw])], axis=-1)
        return jnp.concatenate([top, mid, bot], axis=-2)

    def _gen(t, nbr):
        w = _window(t, nbr)
        if ndim == 2:
            return jax.vmap(ext_step)(w[:, 0])[:, None]
        return jax.vmap(ext_step)(w)

    def _run(tiles, n, neighbors, mask):
        live = (mask != 0)[:, None, None, None]

        def body(_, carry):
            t, ch = carry
            out = jnp.where(live, _gen(t, neighbors), t)
            return out, ch | (out != t).any(axis=(1, 2, 3))

        changed0 = jnp.zeros((tiles.shape[0],), bool)
        tiles, changed = jax.lax.fori_loop(0, n, body, (tiles, changed0))
        occupied = (tiles != 0).any(axis=(1, 2, 3))
        return tiles, changed, occupied

    return tracked_jit(_run, runner="batched.multi_step_paged",
                       donate_argnums=(0,) if donate else ())


def make_multi_step_pallas_batched(
    mesh: Mesh, rule: Rule, topology: Topology = Topology.TORUS,
    gens_per_exchange: int = 8,
    block_rows: Optional[int] = None,
    interpret: Optional[bool] = None,
    donate: bool = False, masked: bool = False,
) -> Callable:
    """The DP × native-kernel corner of the parallelism matrix: a
    (nb, nx, ny) mesh where every device advances its universes'
    full-width row bands through the Mosaic slab kernel
    (parallel/sharded.py make_multi_step_pallas has the band rationale
    and the SMEM edge-code DEAD closure; the same restrictions apply).
    A 2D spatial submesh flattens into nx·ny bands exactly like the
    unbatched runner (``P('b', ('x', 'y'), None)``). One depth-g ppermute
    per side per chunk carries ALL local universes
    (halo.exchange_rows_stack); each universe then runs its own kernel
    call — a static loop, not vmap, because vmapping a manual-DMA
    pallas_call is unsupported territory.

    Returns jitted ``(grids, chunks) -> grids`` over a (B, H, W/32) packed
    batch advancing ``chunks * g`` generations. With ``masked=True`` the
    signature is ``(grids, chunks, mask)`` — same (B,) uint32 occupancy
    contract as :func:`make_multi_step_packed_batched`: mask-0 universes
    come out bit-identical to their input (the select is applied per
    chunk, after the kernel, so frozen slots never drift even though
    their bands still flow through the DMA pipeline).
    """
    from ..ops.pallas_stencil import default_interpret, make_pallas_slab_step
    from .halo import band_edge_code, exchange_rows_stack

    from .mesh import band_axis

    axis, nbands = band_axis(mesh)
    g = int(gens_per_exchange)
    if interpret is None:
        interpret = default_interpret()
    spec = P(BATCH_AXIS, axis, None)

    dead = topology is Topology.DEAD

    def chunk(tiles):
        if g > tiles.shape[1]:  # static shapes: caught at trace time
            raise ValueError(
                f"gens_per_exchange={g} exceeds the per-device band height "
                f"{tiles.shape[1]}")
        ext = exchange_rows_stack(tiles, nbands, topology, axis=axis, depth=g)
        call = make_pallas_slab_step(
            rule, topology, ext.shape[1:], gens=g, block_rows=block_rows,
            interpret=interpret, dead_band=dead)
        if dead:
            edge = band_edge_code(nbands, axis=axis)
            out = [call(ext[i], edge)[g:-g] for i in range(ext.shape[0])]
        else:
            out = [call(ext[i])[g:-g] for i in range(ext.shape[0])]
        return jax.numpy.stack(out)

    if masked:
        @partial(shard_map, mesh=mesh,
                 in_specs=(spec, P(), P(BATCH_AXIS)), out_specs=spec,
                 check_vma=False)
        def _run_masked(tiles, n, mask):
            live = mask[:, None, None] != 0

            def body(_, t):
                return jax.numpy.where(live, chunk(t), t)

            return jax.lax.fori_loop(0, n, body, tiles)

        return tracked_jit(
            _run_masked, runner="batched.multi_step_pallas_batched_masked",
            donate_argnums=(0,) if donate else ())

    # check_vma=False: same scratch-DMA typing limitation as
    # sharded.make_multi_step_pallas
    @partial(shard_map, mesh=mesh, in_specs=(spec, P()), out_specs=spec,
             check_vma=False)
    def _run(tiles, n):
        return jax.lax.fori_loop(0, n, lambda _, t: chunk(t), tiles)

    return tracked_jit(_run, runner="batched.multi_step_pallas_batched",
                       donate_argnums=(0,) if donate else ())


# -- contract-gate registrations (ops/_jit.py BUILDERS) ----------------------


def _contract_batch_example(mesh_shape=(2, 2, 2), grid=(64, 128), seed=7):
    import jax.numpy as jnp

    from ..ops import bitpack

    nb, nx, ny = mesh_shape
    m = make_batch_mesh(mesh_shape, jax.devices()[: nb * nx * ny])
    rng = np.random.default_rng(seed)
    soup = rng.integers(0, 2, size=(nb,) + grid, dtype=np.uint8)
    packed = jnp.stack([bitpack.pack(jnp.asarray(u)) for u in soup])
    return m, jax.device_put(packed, batch_sharding(m))


@register_builder("batched.multi_step_packed_batched",
                  tags=("batched", "packed"))
def _contract_multi_step_packed_batched():
    m, grids = _contract_batch_example()
    return BuiltRunner(
        lowerable=make_multi_step_packed_batched(m, CONWAY, Topology.TORUS,
                                                 donate=True),
        example_args=(grids, 8), donated_argnums=(0,), mesh=m,
        out_spec=_SPEC)


@register_builder("batched.multi_step_packed_batched_masked",
                  tags=("batched", "packed", "serving"))
def _contract_multi_step_packed_batched_masked():
    import jax.numpy as jnp

    m, grids = _contract_batch_example()
    mask = jnp.ones((grids.shape[0],), jnp.uint32)
    return BuiltRunner(
        lowerable=make_multi_step_packed_batched(
            m, CONWAY, Topology.TORUS, donate=True, masked=True),
        example_args=(grids, 8, mask), donated_argnums=(0,), mesh=m,
        out_spec=_SPEC)


@register_builder("batched.multi_step_paged",
                  tags=("batched", "paged", "serving"))
def _contract_multi_step_paged():
    import jax.numpy as jnp

    # a 64-slot pool of Conway tiles with a scrambled page table: the
    # contract is about the runner's shape (donated slab, gathered halos,
    # no host round-trips), not about any particular universe
    B, tr, tw = 64, 32, 4
    rng = np.random.default_rng(11)
    tiles = jnp.asarray(
        rng.integers(0, 1 << 32, size=(B, 1, tr, tw), dtype=np.uint64)
        .astype(np.uint32))
    nbr = jnp.asarray(rng.integers(0, B, size=(B, 8), dtype=np.int32))
    mask = jnp.ones((B,), jnp.uint32).at[0].set(0)  # slot 0 stays dead
    return BuiltRunner(
        lowerable=make_multi_step_paged(CONWAY, tr, tw, donate=True),
        example_args=(tiles, 8, nbr, mask), donated_argnums=(0,),
        require_gather=True)
