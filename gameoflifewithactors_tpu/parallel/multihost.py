"""Multi-process (multi-host) runtime: the distributed communication backend.

The reference's only "communication backend" is the in-process Akka.NET
mailbox (SURVEY.md §2/§6 — no NCCL/MPI/Gloo anywhere); scaling past one
host is where this framework must exceed it. The design stays pure XLA:
every cross-chip byte still moves as a ``ppermute``/``psum`` collective
inside ``shard_map`` (parallel/halo.py) — ICI within a slice, DCN across
slices/hosts — and this module only adds the *runtime* pieces
multi-controller JAX needs:

- :func:`initialize` — bring up the distributed runtime (coordinator
  handshake; on real TPU pods every argument comes from the environment).
- :func:`global_mesh` — a 2D mesh over ALL processes' devices, with the
  same slice-banded ordering single-process meshes get (parallel/mesh.py),
  so halos cross DCN on one axis only.
- :func:`put_global_grid` — place a host grid onto a mesh that spans
  non-addressable devices (``jax.device_put`` only handles addressable
  ones; this routes through ``make_array_from_callback``, each process
  materialising only its own shards).
- :func:`gather_global` — the inverse, for snapshot/checkpoint/render on
  multi-host: an allgather that returns the full array on every process.
- :func:`local_shards` — this process's contribution to a sharded
  checkpoint: (global_index, host_data) for every addressable shard,
  deduplicated, so each process persists only what its devices own
  (utils/checkpoint.py sharded-v2 format; no host ever pays O(grid)).
- :func:`shutdown` — idempotent teardown of the distributed runtime, so
  an elastic worker can leave the fleet cleanly before exiting.

Proven end-to-end in tests/test_multihost.py: N real OS processes form
the distributed system over localhost, step a torus-sharded grid with
cross-process halo exchange, and every process's gathered result is
bit-identical to the single-device engine.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from .mesh import Mesh, check_divisible, grid_sharding, make_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    initialization_timeout: Optional[float] = None,
) -> None:
    """Bring up the multi-controller runtime (idempotent).

    On a real TPU pod slice all three arguments are discovered from the
    environment (``jax.distributed.initialize()`` with no args); explicit
    values serve CPU rigs and tests. Safe to call twice — a second call is
    a no-op instead of the RuntimeError jax raises. (The check must not
    touch ``jax.process_count()``: that would initialise the XLA backend,
    which is exactly what must not happen before the handshake.)

    ``initialization_timeout`` bounds the coordinator handshake — the
    elastic runtime passes a finite value so a fleet whose coordinator
    died during relaunch errors out instead of waiting forever."""
    import os

    from jax._src import distributed as _dist

    if _dist.global_state.client is not None:
        return
    # CPU rigs need an explicit cross-process collectives backend: the
    # default CPU client refuses multi-process computations outright
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"), and the env var alone is not honored on this jaxlib —
    # the config must be set in-process before the backend exists. On
    # TPU this never fires (collectives ride ICI/DCN natively).
    platforms = (getattr(jax.config, "jax_platforms", None)
                 or os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in str(platforms):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — unknown option on other jaxlibs
            pass
    kwargs = {}
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = int(initialization_timeout)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def shutdown() -> None:
    """Tear the distributed runtime down (idempotent — a no-op when
    :func:`initialize` never ran or already shut down). An elastic
    worker that detected peer loss calls this on its way out so the
    coordination service is not left waiting on a zombie client."""
    from jax._src import distributed as _dist

    if _dist.global_state.client is None:
        return
    jax.distributed.shutdown()


def global_mesh(shape: Optional[Tuple[int, int]] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 2D mesh over every device of every process (``jax.devices()`` is
    global after :func:`initialize`), slice-banded like any other mesh."""
    return make_mesh(shape, list(devices if devices is not None else jax.devices()))


def global_mesh_for_grid(
    grid_shape: Tuple[int, int],
    preferred: Optional[Tuple[int, int]] = None,
    *,
    gens_per_exchange: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """The fleet's mesh for a packed (rows, words) grid: ``preferred``
    when it fits the current global roster (device count, divisibility,
    and — for a width-k ghost pipeline — tile capacity), else the
    most-square valid factorization, else lock-step (n, 1) bands.

    This is THE re-tiling decision of the elastic runtime: every
    surviving process calls it with the same global inputs after a
    shrink/replace epoch, so all controllers deterministically agree on
    where the 2D tiles land before ``put_global_grid`` re-places them.
    """
    from .mesh import best_mesh_shape, ghost_fits

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    k = int(gens_per_exchange)
    rows, words = int(grid_shape[0]), int(grid_shape[1])
    if preferred is not None:
        mx, my = preferred
        if (mx * my == n and rows % mx == 0 and words % my == 0
                and (k <= 1 or ghost_fits(rows // mx, words // my, k))):
            return global_mesh((mx, my), devices)
    shape = None
    if k > 1:
        shape = best_mesh_shape(n, rows, words, gens_per_exchange=k)
    if shape is None:
        # no ghost-capable tiling: fall back to plain divisibility
        # (lock-step per-gen exchange), then to legacy (n, 1) bands
        shape = best_mesh_shape(n, rows, words, gens_per_exchange=0)
    return global_mesh(shape if shape is not None else (n, 1), devices)


def put_global_grid(grid: np.ndarray, mesh: Mesh,
                    banded: bool = False) -> jax.Array:
    """Place a host grid (same full copy on every process) onto ``mesh``.

    Each process materialises only the shards its addressable devices own,
    so the host copy is the only O(grid) cost — nothing is sent twice.
    ``banded=True`` uses the flattened full-width row-band layout the
    band-kernel runners take on 2D meshes (mesh.device_put_sharded_grid's
    contract); 3D (b, H, Wp) plane stacks replicate the leading axis."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import COL_AXIS, ROW_AXIS

    grid = np.asarray(grid)
    if banded:
        nb = mesh.shape[ROW_AXIS] * mesh.shape[COL_AXIS]
        if grid.shape[-2] % nb:
            raise ValueError(
                f"grid rows {grid.shape[-2]} not divisible into {nb} "
                "full-width bands over the flattened mesh")
        spec = (P(None, (ROW_AXIS, COL_AXIS), None) if grid.ndim == 3
                else P((ROW_AXIS, COL_AXIS), None))
        sharding = NamedSharding(mesh, spec)
    elif grid.ndim == 3:
        check_divisible(grid.shape[1:], mesh)
        sharding = NamedSharding(
            mesh, P(None, ROW_AXIS, COL_AXIS))
    else:
        check_divisible(grid.shape, mesh)
        sharding = grid_sharding(mesh)
    return jax.make_array_from_callback(grid.shape, sharding,
                                        lambda idx: grid[idx])


def local_shards(arr: jax.Array) -> List[Tuple[tuple, np.ndarray]]:
    """``[(global_index, host_data), ...]`` for every shard this
    process's devices own — the per-process write set of a sharded
    checkpoint (utils/checkpoint.py ``write_shards``).

    Replicated axes make several devices hold the same global index;
    those duplicates are dropped so the union across processes tiles the
    global array exactly once (what ``commit_manifest`` verifies). Each
    shard moves device→host locally; nothing crosses the interconnect."""
    out: List[Tuple[tuple, np.ndarray]] = []
    seen = set()
    for sh in arr.addressable_shards:
        key = tuple(sl.indices(dim)[:2]
                    for sl, dim in zip(sh.index, arr.shape))
        if key in seen:
            continue
        seen.add(key)
        out.append((sh.index, np.asarray(sh.data)))
    return out


def gather_global(arr: jax.Array) -> np.ndarray:
    """Full array on every process (allgather across hosts), as NumPy.

    The multi-host answer to Engine.snapshot: addressable shards move
    device->host locally, the rest arrive over the interconnect."""
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return np.asarray(arr)
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
