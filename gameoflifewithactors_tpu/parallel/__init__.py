"""parallel subpackage."""
