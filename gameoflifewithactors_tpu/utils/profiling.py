"""Tracing/profiling hooks (SURVEY.md §6 'Tracing/profiling').

The reference has none [ABSENT]; here the step loop can be wrapped in
``jax.profiler`` traces (perfetto-compatible dumps readable in TensorBoard
or ui.perfetto.dev) with named annotations around the phases that matter —
step dispatch, device sync, snapshot readback — plus a lightweight
wall-clock timer that needs no trace viewer.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import jax


def annotate(name: str):
    """Named region that shows up on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profile into ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_steps(engine, generations: int, log_dir: str, chunk: int = 1) -> None:
    """Trace a short stepped run: one annotated region per chunk, one sync
    at the end (so the trace shows pipelined dispatch, not sync stalls)."""
    with trace(log_dir):
        done = 0
        while done < generations:
            n = min(chunk, generations - done)
            with annotate(f"gol_step x{n}"):
                engine.step(n)
            done += n
        with annotate("gol_sync"):
            engine.block_until_ready()


@dataclass
class PhaseTimer:
    """Wall-clock phase accumulator: ``with timer.phase("step"): ...``.

    Per-phase totals/counts land in ``summary()`` — the no-dependencies
    answer to "where did the wall-clock go" (device time needs trace()).
    """

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, dict]:
        return {
            name: {
                "total_s": self.totals[name],
                "count": self.counts[name],
                "mean_s": self.totals[name] / self.counts[name],
            }
            for name in self.totals
        }
