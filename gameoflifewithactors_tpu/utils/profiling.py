"""Tracing/profiling hooks (SURVEY.md §6 'Tracing/profiling').

The reference has none [ABSENT]; here the step loop can be wrapped in
``jax.profiler`` traces (perfetto-compatible dumps readable in TensorBoard
or ui.perfetto.dev) with named annotations around the phases that matter —
step dispatch, device sync, snapshot readback — plus a lightweight
wall-clock timer that needs no trace viewer.
"""

from __future__ import annotations

import contextlib
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional

import jax


def annotate(name: str):
    """Named region that shows up on the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profile into ``log_dir``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_steps(engine, generations: int, log_dir: str, chunk: int = 1) -> None:
    """Trace a short stepped run: one annotated region per chunk, one sync
    at the end (so the trace shows pipelined dispatch, not sync stalls)."""
    with trace(log_dir):
        done = 0
        while done < generations:
            n = min(chunk, generations - done)
            with annotate(f"gol_step x{n}"):
                engine.step(n)
            done += n
        with annotate("gol_sync"):
            engine.block_until_ready()


@dataclass
class PhaseTimer:
    """Wall-clock phase accumulator: ``with timer.phase("step"): ...``.

    Per-phase totals/counts land in ``summary()`` — the no-dependencies
    answer to "where did the wall-clock go" (device time needs trace()).
    """

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, dict]:
        return {
            name: {
                "total_s": self.totals[name],
                "count": self.counts[name],
                "mean_s": self.totals[name] / self.counts[name],
            }
            for name in self.totals
        }


# ---------------------------------------------------------------------------
# Interconnect accounting measured from the compiled program
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

# the result type is either the operand shape ("= u32[1,8]{1,0} ...") or,
# for async -start ops on TPU, a tuple whose FIRST element is the operand
# ("= (u8[3,66]{1,0}, u8[3,66]{1,0}, u32[], u32[]) collective-permute-start");
# the optional "(" + non-greedy tail covers both while counting the operand once
_CP_RE = re.compile(
    r"=\s*\(?(?P<dtype>[a-z0-9]+)\[(?P<dims>[\d,]*)\][^=]*?"
    r"\scollective-permute(?:-start)?\("
    r".*?source_target_pairs=\{\{(?P<pairs>.*?)\}\}",
)


def collective_permute_bytes(hlo_text: str) -> int:
    """Interconnect bytes one execution of a compiled program moves via
    collective-permute: Σ over instructions of (per-device operand bytes ×
    number of source→target pairs).

    This is *measured from the SPMD-partitioned HLO the compiler actually
    emits* — the cross-check for ``Engine.halo_bytes_per_gen``'s arithmetic
    estimate (VERDICT.md round-1 Weak #5). Counting is invariant under
    XLA's collective-combining passes: merged permutes carry the summed
    operand bytes. ``collective-permute-done`` ops are skipped (their
    operand is the in-flight token of the matching -start).
    """
    total = 0
    for m in _CP_RE.finditer(hlo_text):
        dtype = m.group("dtype")
        if dtype not in _DTYPE_BYTES:
            raise ValueError(
                f"collective-permute over unlisted dtype {dtype!r}; extend "
                "_DTYPE_BYTES rather than miscounting interconnect traffic")
        nbytes = _DTYPE_BYTES[dtype]
        for d in filter(None, m.group("dims").split(",")):
            nbytes *= int(d)
        # src == dst pairs are device-local self-copies (XLA emits them for
        # the wrap "send" on a size-1 mesh axis) — bytes that never touch
        # the interconnect, so they must not count as halo traffic
        n_pairs = 0
        for pair in m.group("pairs").split("},{"):
            src, dst = pair.split(",")
            if src.strip() != dst.strip():
                n_pairs += 1
        total += nbytes * n_pairs
    return total


def collective_permute_count(hlo_text: str) -> int:
    """Number of collective-permute instructions in compiled HLO that
    move data between distinct devices (instructions whose pairs are all
    src == dst self-copies don't count — nothing touched the wire).

    This is the *exchange-count* side of the accounting story: the ghost
    pipeline's k× claim is that a statically-unrolled c-chunk build
    (``make_multi_step_packed_ghost(..., unroll_chunks=c)``) compiles to
    exactly 1/k the permutes of c·k unrolled lock-step generations —
    proven from the HLO the compiler emits, not from the source. Unlike
    :func:`collective_permute_bytes` this figure is NOT invariant under
    XLA's collective-combining passes; compare builds compiled with the
    same pipeline (as tests/test_ghost.py does)."""
    count = 0
    for m in _CP_RE.finditer(hlo_text):
        for pair in m.group("pairs").split("},{"):
            src, dst = pair.split(",")
            if src.strip() != dst.strip():
                count += 1
                break
    return count


def measured_halo_bytes_per_gen(engine) -> int:
    """Compile the engine's *one-generation* sharded step and account its
    collective-permute traffic from the optimized HLO. Returns 0 for
    unsharded engines (nothing crosses the interconnect)."""
    from ..parallel import sharded

    if engine.mesh is None:
        return 0
    if engine.backend == "pallas":
        # band engines amortize the depth-(r·g) chunk exchange over its g
        # generations, which lands exactly on the banded per-generation
        # runner's rate (r rows/gen, full width, × b planes) — lower THAT
        # for the per-generation measured figure; the chunk itself is a
        # pallas kernel whose exchange XLA cannot lower on CPU
        step1 = sharded.make_multi_step_banded(
            engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, 1)
    elif getattr(engine, "_sparse_tiles", None):
        # per-tile sharded sparse (any layout, incl. radius-r LtL): the
        # flag-map halo rides along, so lower the same runner the engine
        # steps with — before the per-family branches, which would miss it
        tr, tw = engine._sparse_tiles
        make = (sharded.make_multi_step_generations_packed_sparse_tiled
                if getattr(engine, "_gen_packed", False)
                else sharded.make_multi_step_packed_sparse_tiled)
        step1 = make(engine.mesh, engine.rule, engine.topology,
                     tile_rows=tr, tile_words=tw)
        lowered = step1.lower(engine.state, engine._flags, 1)
    elif getattr(engine, "_ltl_planes", False):
        step1 = sharded.make_multi_step_ltl_planes(
            engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, 1)
    elif getattr(engine, "_ltl_packed", False):
        step1 = sharded.make_multi_step_ltl_packed(
            engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, 1)
    elif getattr(engine, "_ltl", False):
        step1 = sharded.make_multi_step_ltl(engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, 1)
    elif getattr(engine, "_gen_packed", False):
        step1 = sharded.make_multi_step_generations_packed(
            engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, 1)
    elif getattr(engine, "_generations", False):
        step1 = sharded.make_multi_step_generations(
            engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, 1)
    elif engine._flags is not None:
        step1 = sharded.make_multi_step_packed_sparse(
            engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state, engine._flags, 1)
    elif engine._packed and getattr(engine, "gens_per_exchange", 1) > 1:
        # communication-avoiding runner: lower ONE depth-g chunk and
        # amortize over its g generations (ceil, like the model) — the
        # per-generation runner's figure would overstate what this engine
        # actually moves
        g = engine.gens_per_exchange
        if getattr(engine, "_ghost_pipeline", False):
            # statically-unrolled single chunk: the dynamic-chunks build's
            # HLO carries the exchange twice (prologue + fori_loop body),
            # which would double-count one chunk's traffic
            step1 = sharded.make_multi_step_packed_ghost(
                engine.mesh, engine.rule, engine.topology,
                gens_per_exchange=g, unroll_chunks=1)
            lowered = step1.lower(engine.state)
        else:
            step1 = sharded.make_multi_step_packed_deep(
                engine.mesh, engine.rule, engine.topology,
                gens_per_exchange=g)
            lowered = step1.lower(engine.state, 1)
        return -(-collective_permute_bytes(lowered.compile().as_text()) // g)
    elif engine._packed:
        step1 = sharded.make_step_packed(engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state)
    else:
        step1 = sharded.make_step_dense(engine.mesh, engine.rule, engine.topology)
        lowered = step1.lower(engine.state)
    return collective_permute_bytes(lowered.compile().as_text())


def _union_intervals(intervals: list) -> list:
    """Merge (start, end) intervals into a disjoint sorted list."""
    merged: list = []
    for s, e in sorted(intervals):
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return merged


def _union_len(intervals: list) -> float:
    return sum(e - s for s, e in _union_intervals(intervals))


def _intersect_len(a: list, b: list) -> float:
    """Length of the intersection of two interval unions (sweep)."""
    a, b = _union_intervals(a), _union_intervals(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def perfetto_summary(trace_path: str) -> dict:
    """Measured device-activity summary from a perfetto/chrome trace
    (``jax.profiler.start_trace(..., create_perfetto_trace=True)`` writes
    ``perfetto_trace.json.gz``; plain ``.json`` is accepted too).

    Per (process, thread) track: interval-union busy time (robust to the
    nested/overlapping slices a profiler emits), the track's wall span,
    and the top slice names by summed duration. Device tracks are the
    ones whose process or thread name mentions the accelerator — on a
    host-only capture there simply are none, and the caller can tell:
    ``source`` is ``"device_tracks"`` when any exist, ``"host_tracks"``
    when only host activity was captured, ``None`` for an empty trace.
    This turns the roofline story from arithmetic into measurement
    (VERDICT round-2 item #6): measured busy seconds of the kernel's
    device track is the denominator for the measured in-kernel rate.

    ``device_busy_us``/``device_span_us`` describe the single busiest
    device track, NOT a sum: TPU profiler dumps mirror one device's
    activity across several stacked track layers (XLA Modules / XLA Ops /
    step lines), so summing across them would count the same wall time
    several times over and could push a duty cycle past 1.0.

    Op-class attribution (ISSUE 18): ``op_class_us`` buckets one track's
    busy time into {collective_permute, stencil, copy_reshape,
    infeed_host, other} by slice-name classification
    (``obs.profiler.classify_slice``), each bucket an interval union so
    nested same-class slices don't double count. The attribution track
    is the device track with the most classified (non-``other``) busy
    time — the op-level layer, not the module mirror — falling back to
    the busiest track. ``overlap`` measures comms/compute overlap as the
    interval intersection of collective-class against stencil-class
    slices across ALL device tracks (async collectives land on their own
    track lines); it is ``None`` on a host-only capture — absent, never
    a fabricated 0.0.
    """
    import gzip
    import json as _json

    from ..obs.profiler import OTHER_CLASS, classify_slice

    opener = gzip.open if trace_path.endswith(".gz") else open
    with opener(trace_path, "rt") as f:
        data = _json.load(f)
    events = data["traceEvents"] if isinstance(data, dict) else data

    proc_names: dict = {}
    thread_names: dict = {}
    slices: dict = {}
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            if ev.get("name") == "process_name":
                proc_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
            elif ev.get("name") == "thread_name":
                thread_names[(ev.get("pid"), ev.get("tid"))] = (
                    ev.get("args", {}).get("name", ""))
        elif ph == "X" and "dur" in ev:
            key = (ev.get("pid"), ev.get("tid"))
            slices.setdefault(key, []).append(
                (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]),
                 ev.get("name", "")))

    tracks = []
    class_intervals: dict = {}  # track label -> {op_class: [(s, e)]}
    for (pid, tid), evs in slices.items():
        evs.sort()
        busy = 0.0
        cur_s, cur_e = evs[0][0], evs[0][1]
        max_end = evs[0][1]  # sort is by start: a nested slice sorts last
        # but can end before its parent, so the span needs the max end
        by_name: dict = {}
        by_class: dict = {}
        for s, e, name in evs:
            by_name[name] = by_name.get(name, 0.0) + (e - s)
            by_class.setdefault(classify_slice(name), []).append((s, e))
            max_end = max(max_end, e)
            if s > cur_e:
                busy += cur_e - cur_s
                cur_s, cur_e = s, e
            else:
                cur_e = max(cur_e, e)
        busy += cur_e - cur_s
        pname = proc_names.get(pid, "")
        tname = thread_names.get((pid, tid), "")
        label = f"{pname}/{tname}".strip("/") or f"pid{pid}/tid{tid}"
        class_intervals[label] = by_class
        tracks.append({
            "track": label,
            "busy_us": round(busy, 1),
            "span_us": round(max_end - evs[0][0], 1),
            "n_slices": len(evs),
            "top": sorted(by_name.items(), key=lambda kv: -kv[1])[:4],
            "op_class_us": {cls: round(_union_len(iv), 1)
                            for cls, iv in sorted(by_class.items())},
        })
    tracks.sort(key=lambda t: -t["busy_us"])

    def _is_device(t: dict) -> bool:
        lbl = t["track"].lower()
        return any(k in lbl for k in ("tpu", "device", "xla:#global", "/device:"))

    dev = [t for t in tracks if _is_device(t)]  # already busiest-first
    source = "device_tracks" if dev else ("host_tracks" if tracks else None)

    def _classified_us(t: dict) -> float:
        return sum(v for cls, v in t["op_class_us"].items()
                   if cls != OTHER_CLASS)

    attribution_track = None
    op_class_us: dict = {}
    candidates = dev or tracks
    if candidates:
        attribution_track = max(
            candidates, key=lambda t: (_classified_us(t), t["busy_us"]))
        op_class_us = dict(attribution_track["op_class_us"])

    overlap = None
    if dev:
        coll: list = []
        comp: list = []
        for t in dev:
            coll.extend(class_intervals[t["track"]].get(
                "collective_permute", []))
            comp.extend(class_intervals[t["track"]].get("stencil", []))
        coll_us = _union_len(coll)
        overlap = {
            "collective_us": round(coll_us, 1),
            "compute_us": round(_union_len(comp), 1),
            "overlapped_us": round(_intersect_len(coll, comp), 1),
        }
        overlap["ratio"] = (overlap["overlapped_us"] / coll_us
                            if coll_us > 0 else None)

    return {
        "tracks": tracks[:12],
        "source": source,
        "device_tracks": len(dev),
        "device_track": dev[0]["track"] if dev else None,
        "device_busy_us": dev[0]["busy_us"] if dev else 0.0,
        "device_span_us": dev[0]["span_us"] if dev else 0.0,
        "attribution_track": (attribution_track["track"]
                              if attribution_track else None),
        "op_class_us": op_class_us,
        "overlap": overlap,
    }
