"""Small platform helpers shared by the CLI, bench, and engine entry points."""

from __future__ import annotations

import os


def honor_jax_platforms_env() -> None:
    """Apply JAX_PLATFORMS via config: some PJRT plugins (e.g. this image's
    tunneled TPU) register regardless of the env var, so the env alone
    cannot steer a process onto CPU; the config update can."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass  # backends already initialized; keep whatever we have


def on_tpu() -> bool:
    import jax

    return jax.devices()[0].platform == "tpu"
