"""utils subpackage."""
