"""Checkpoint/resume: exact simulation state, single-file or sharded
(SURVEY.md §6).

The reference has no persistence [ABSENT] — a crash loses the universe.
On TPU the whole simulation state is (packed grid, rule, topology,
generation), so checkpointing is trivially strong: save is one device→host
transfer of 1 bit/cell; resume is bit-exact. Files are self-describing so a
checkpoint can be reloaded onto a different mesh/backend than it was saved
from (sharding is an execution detail, not simulation state).

Two on-disk families:

- **single-file** (:func:`save` / :func:`load_grid`): one NPZ holding the
  whole grid — what one host can hold. Internal NPZ versions 1–3 all load.
- **sharded v2** (:func:`write_shards` / :func:`commit_manifest` /
  :func:`load_sharded`): a per-generation *directory* where each process
  writes only the shards its devices own, each with a CRC32, committed
  atomically by a ``MANIFEST.json`` rename. Restore verifies every
  checksum and refuses torn or corrupt shards
  (:class:`CheckpointCorruptError`); :func:`load_latest_verified` falls
  back generation by generation to the newest *complete* one. This is the
  multi-host format: no single process ever materialises (or trusts) the
  whole grid on the write path. Cross-process sequencing (everyone's
  shards durable before the manifest) is the caller's job — the elastic
  runtime (resilience/distributed.py) brackets these calls with its
  deadline-bounded barriers.

Any unreadable checkpoint — truncated zip, corrupt member, bad metadata —
surfaces as :class:`CheckpointCorruptError` (a ``ValueError``), never a raw
``zipfile``/``zlib`` traceback, so recovery layers can treat "checkpoint
rotted" as a routine fall-back-to-previous event instead of a crash.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import shutil
import zipfile
import zlib
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh

from ..engine import Engine
from ..models.generations import parse_any
from ..ops import bitpack
from ..ops.stencil import Topology

FORMAT_VERSION = 3  # v3 adds device-layout checkpoints (no dense detour)
_READABLE_VERSIONS = (1, 2, 3)  # older files load unchanged


class CheckpointCorruptError(ValueError):
    """A checkpoint exists but cannot be trusted: truncated archive,
    CRC mismatch, missing shard, or undecodable metadata. Subclasses
    ``ValueError`` so pre-existing ``except ValueError`` call sites keep
    working; recovery layers catch it specifically and fall back to the
    previous checkpoint (resilience/supervisor.py,
    resilience/distributed.py) instead of dying on a raw
    ``zipfile``/``zlib`` error."""


def save(engine: Engine, path: "str | Path") -> Path:
    """Write the engine's exact state; returns the path written.

    Packed engines (binary bitboards and Generations bit-plane stacks)
    save their device layout directly — the v3 "packed32"/"genplanes32"
    layouts — so no dense copy is ever materialised: checkpointing a
    65536² universe moves 512 MB of words, not a 4.3 GB byte grid
    (device-side unpack + host gather, which is what snapshot() costs).
    Byte-layout engines keep the v1 (packbits) / v2 (multistate cells)
    forms. All versions reload onto any mesh/backend.

    Crash-safe: the bytes land in a temp file in the same directory and
    are ``os.replace``d into place, so a SIGKILL mid-save (the soak
    harness does exactly this) can never leave a truncated NPZ where a
    loadable checkpoint used to be — the previous checkpoint survives
    until the new one is durably whole.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    base = dict(
        rule=engine.rule.notation,
        topology=engine.topology.value,
        generation=engine.generation,
        shape=list(engine.shape),
    )
    # pid-qualified temp name: two processes checkpointing to the same
    # path (supervisor + an operator's manual save) must not interleave
    tmp = path.with_name(f"{path.name}.tmp{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            if engine._packed:
                meta = dict(version=FORMAT_VERSION, layout="packed32",
                            multistate=False, **base)
                np.savez_compressed(
                    f, words=np.asarray(engine.state), meta=json.dumps(meta))
            elif getattr(engine, "_gen_packed", False):
                meta = dict(version=FORMAT_VERSION, layout="genplanes32",
                            multistate=True, **base)
                np.savez_compressed(
                    f, planes=np.asarray(engine.state), meta=json.dumps(meta))
            else:
                grid = engine.snapshot()
                multistate = bool(grid.max(initial=0) > 1)  # Generations states
                # byte-layout files keep their historical stamps (v1 binary
                # packbits / v2 multistate cells) so old readers still load
                # them
                meta = dict(version=2 if multistate else 1,
                            multistate=multistate, **base)
                if multistate:
                    np.savez_compressed(f, cells=grid, meta=json.dumps(meta))
                else:
                    np.savez_compressed(f, bits=np.packbits(grid, axis=1),
                                        meta=json.dumps(meta))
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    return path


def load_grid(path: "str | Path") -> Tuple[np.ndarray, dict]:
    """Read (grid, metadata) from a checkpoint without building an engine.

    A missing file stays ``FileNotFoundError`` (absence is not damage);
    every other failure mode of an on-disk NPZ — truncated zip, corrupt
    deflate stream, missing member, undecodable meta — raises
    :class:`CheckpointCorruptError` so callers can route it to their
    previous-checkpoint fallback instead of crashing on a ``zipfile``
    internal."""
    path = Path(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            if "meta" not in z:
                raise CheckpointCorruptError(
                    f"checkpoint {path} has no 'meta' member — not a "
                    "goltpu checkpoint or a torn write")
            meta = json.loads(str(z["meta"]))
            if meta.get("version") not in _READABLE_VERSIONS:
                raise CheckpointCorruptError(
                    f"unsupported checkpoint version "
                    f"{meta.get('version')!r} in {path}")
            h, w = meta["shape"]
            layout = meta.get("layout")
            if layout == "packed32":
                grid = bitpack.unpack_np(
                    np.asarray(z["words"], dtype=np.uint32))[:, :w]
            elif layout == "genplanes32":
                from ..ops.packed_generations import unpack_generations_np

                grid = unpack_generations_np(
                    np.asarray(z["planes"], dtype=np.uint32))[:, :w]
            elif meta.get("multistate"):
                grid = np.asarray(z["cells"], dtype=np.uint8)
            else:
                grid = np.unpackbits(z["bits"], axis=1)[:, :w].astype(np.uint8)
    except FileNotFoundError:
        raise
    except CheckpointCorruptError:
        raise
    except (zipfile.BadZipFile, zlib.error, ValueError, KeyError, OSError,
            EOFError) as exc:
        raise CheckpointCorruptError(
            f"checkpoint {path} is unreadable "
            f"({type(exc).__name__}: {exc})") from exc
    return grid, meta


def load_engine(
    path: "str | Path",
    *,
    mesh: Optional[Mesh] = None,
    backend: str = "auto",
) -> Engine:
    """Rebuild an Engine bit-exactly from a checkpoint (any mesh/backend)."""
    grid, meta = load_grid(path)
    engine = Engine(
        grid,
        parse_any(meta["rule"]),
        topology=Topology(meta["topology"]),
        mesh=mesh,
        backend=backend,
    )
    engine.generation = meta["generation"]
    return engine


PAGED_FORMAT = "paged1"


def save_paged(grid, path: "str | Path") -> Path:
    """Checkpoint a paged grid (memory/paged.py) in its sparse form: the
    bound pages' coordinates and tile words, never a dense detour — an
    unbounded glider a million tiles out checkpoints as its handful of
    live pages, not a 10^12-cell rectangle. Accepts a
    :class:`~gameoflifewithactors_tpu.memory.PagedGrid` or anything
    carrying one as ``.grid`` (:class:`~gameoflifewithactors_tpu.memory.
    PagedUniverse`). Same crash-safety as :func:`save` (tmp +
    ``os.replace``)."""
    grid = getattr(grid, "grid", grid)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    pool = grid.pool
    host = pool.tiles_host()
    coords = sorted(grid.pages)
    tiles = (np.stack([host[grid.pages[c]] for c in coords])
             if coords else
             np.zeros((0, pool.planes, pool.tile_rows, pool.tile_words),
                      np.uint32))
    meta = dict(
        format=PAGED_FORMAT,
        rule=pool.rule.notation,
        topology=grid.topology.value,
        bounds=list(grid.bounds) if grid.bounds is not None else None,
        planes=pool.planes,
        tile_rows=pool.tile_rows,
        tile_words=pool.tile_words,
        generation=grid.generation,
        active=sorted([int(y), int(x)] for y, x in grid.active),
    )
    tmp = path.with_name(f"{path.name}.tmp{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f, coords=np.asarray(coords, np.int64).reshape(-1, 2),
                tiles=tiles, meta=json.dumps(meta))
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    return path


def load_paged(path: "str | Path", *, pool=None,
               capacity: Optional[int] = None,
               registry=None):
    """Rebuild a paged grid bit-exactly from a :func:`save_paged` file:
    returns ``(grid, meta)``. Pages re-allocate into ``pool`` (which must
    match the checkpoint's rule slab geometry) or into a fresh pool sized
    ``capacity`` (default: twice the checkpointed page count, so the
    restored universe has room to advance). Unreadable files raise
    :class:`CheckpointCorruptError`, like every other loader here."""
    from ..memory import PagedGrid, TilePool

    path = Path(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            if "meta" not in z:
                raise CheckpointCorruptError(
                    f"checkpoint {path} has no 'meta' member — not a "
                    "goltpu checkpoint or a torn write")
            meta = json.loads(str(z["meta"]))
            if meta.get("format") != PAGED_FORMAT:
                raise CheckpointCorruptError(
                    f"{path} is not a paged checkpoint "
                    f"(format={meta.get('format')!r})")
            coords = np.asarray(z["coords"], np.int64)
            tiles = np.asarray(z["tiles"], np.uint32)
            if tiles.shape != (len(coords), meta["planes"],
                               meta["tile_rows"], meta["tile_words"]):
                raise CheckpointCorruptError(
                    f"{path}: tiles shape {tiles.shape} does not match "
                    f"{len(coords)} pages of the declared slab geometry")
    except FileNotFoundError:
        raise
    except CheckpointCorruptError:
        raise
    except (zipfile.BadZipFile, zlib.error, ValueError, KeyError, OSError,
            EOFError) as exc:
        raise CheckpointCorruptError(
            f"checkpoint {path} is unreadable "
            f"({type(exc).__name__}: {exc})") from exc
    rule = parse_any(meta["rule"])
    if pool is None:
        kwargs = {} if registry is None else {"registry": registry}
        pool = TilePool(rule, int(capacity or max(2 * len(coords) + 1, 16)),
                        tile_rows=meta["tile_rows"],
                        tile_words=meta["tile_words"], **kwargs)
    elif (pool.planes != meta["planes"]
            or pool.tile_rows != meta["tile_rows"]
            or pool.tile_words != meta["tile_words"]):
        raise ValueError(
            f"pool slab ({pool.planes}, {pool.tile_rows}, "
            f"{pool.tile_words}) does not match checkpoint "
            f"({meta['planes']}, {meta['tile_rows']}, {meta['tile_words']})")
    bounds = tuple(meta["bounds"]) if meta["bounds"] is not None else None
    grid = PagedGrid(pool, topology=Topology(meta["topology"]),
                     bounds=bounds)
    cs = [tuple(int(v) for v in c) for c in coords]
    grid.ensure(cs)
    for c, tile in zip(cs, tiles):
        pool.write(grid.pages[c], tile)
    grid.active = {tuple(int(v) for v in c) for c in meta["active"]}
    grid.generation = int(meta["generation"])
    return grid, meta


def rotate_previous(path: "str | Path", suffix: str = ".prev") -> Optional[Path]:
    """Publish the current checkpoint at ``path`` as ``path + suffix``
    (atomically) so the next :func:`save` can overwrite ``path`` without
    destroying the last restore point. Hard-links where the filesystem
    allows (zero-copy), copies otherwise; a crash at any instant leaves
    both names pointing at *complete* files. Returns the previous-path,
    or None when ``path`` does not exist yet."""
    path = Path(path)
    if not path.exists():
        return None
    prev = path.with_name(path.name + suffix)
    tmp = path.with_name(f"{path.name}{suffix}.tmp{os.getpid()}")
    with contextlib.suppress(OSError):
        os.unlink(tmp)
    try:
        os.link(path, tmp)
    except OSError:  # cross-device / no-hardlink filesystem
        shutil.copyfile(path, tmp)
    os.replace(tmp, prev)
    return prev


# -- sharded v2: per-process shards + CRCs under an atomic manifest -----------

SHARDED_FORMAT = "goltpu-sharded"
SHARDED_FORMAT_VERSION = 2
MANIFEST_NAME = "MANIFEST.json"
_GEN_DIR_RE = re.compile(r"^gen-(\d{8})$")


def generation_dir(root: "str | Path", generation: int) -> Path:
    """``<root>/gen-<generation, zero-padded>`` — one directory per
    checkpointed generation; lexicographic order is generation order."""
    return Path(root) / f"gen-{int(generation):08d}"


def list_generations(root: "str | Path") -> List[Tuple[int, Path]]:
    """All generation dirs under ``root``, oldest first (committed or
    not — callers that need trust go through :func:`verify_sharded`)."""
    root = Path(root)
    if not root.is_dir():
        return []
    out = []
    for child in root.iterdir():
        m = _GEN_DIR_RE.match(child.name)
        if m and child.is_dir():
            out.append((int(m.group(1)), child))
    return sorted(out)


def _index_to_json(index: Sequence, shape: Sequence[int]) -> List[List[int]]:
    """Normalise a shard's global index (tuple of slices, possibly with
    None bounds) to JSON-plain ``[[start, stop], ...]``."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"strided shard index {sl} is not supported")
        out.append([start, stop])
    return out


def _check_index_bounds(index: Sequence[Sequence[int]],
                        shard_shape: Sequence[int],
                        global_shape: Sequence[int],
                        what: str) -> None:
    """A shard's ``[[start, stop], ...]`` index must lie inside the
    global array and span exactly the shard's own shape, per dimension.
    2D-mesh tiles shard BOTH axes, so a re-tiling bug (wrong word
    column after an elastic epoch, say) shows up as an extent/shape
    mismatch here instead of as silently clamped slices —
    ``slice.indices`` in :func:`_index_to_json` clamps out-of-range
    bounds, and :func:`_check_exact_cover` stops masking past 2^26
    elements."""
    if len(index) != len(global_shape) or len(shard_shape) != len(global_shape):
        raise CheckpointCorruptError(
            f"{what}: index {list(index)} / shape {list(shard_shape)} rank "
            f"!= global array rank {len(global_shape)}")
    for d, ((start, stop), n, dim) in enumerate(
            zip(index, shard_shape, global_shape)):
        if not (0 <= start <= stop <= dim):
            raise CheckpointCorruptError(
                f"{what}: dim {d} index [{start}, {stop}) out of bounds "
                f"for global extent {dim}")
        if stop - start != n:
            raise CheckpointCorruptError(
                f"{what}: dim {d} index [{start}, {stop}) covers "
                f"{stop - start} elements but shard shape has {n}")


def _crc32(data: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(data).tobytes()) & 0xFFFFFFFF


def _shard_npz(gen_dir: Path, process_id: int) -> Path:
    return gen_dir / f"shard-p{int(process_id):04d}.npz"


def _shard_sidecar(gen_dir: Path, process_id: int) -> Path:
    return gen_dir / f"shard-p{int(process_id):04d}.json"


def write_shards(
    gen_dir: "str | Path",
    process_id: int,
    shards: Sequence[Tuple[Sequence, np.ndarray]],
    *,
    global_shape: Sequence[int],
    dtype: "str | np.dtype",
) -> Path:
    """Write THIS process's shards of one global array: an NPZ with the
    shard payloads plus a JSON sidecar carrying per-shard CRC32s and
    global indices. ``shards`` is ``[(global_index, data), ...]`` — for
    a live ``jax.Array`` use ``parallel.multihost.local_shards``. Both
    files land via temp + ``os.replace`` (the sidecar last, so a visible
    sidecar implies a durable payload). Nothing here is a commit point:
    the generation only becomes loadable when :func:`commit_manifest`
    publishes the manifest."""
    gen_dir = Path(gen_dir)
    gen_dir.mkdir(parents=True, exist_ok=True)
    dtype = np.dtype(dtype)
    arrays, entries = {}, []
    for j, (index, data) in enumerate(shards):
        data = np.asarray(data)
        if data.dtype != dtype:
            raise ValueError(
                f"shard {j} dtype {data.dtype} != checkpoint dtype {dtype}")
        key = f"s{j}"
        arrays[key] = data
        idx = _index_to_json(index, global_shape)
        _check_index_bounds(idx, data.shape, global_shape,
                            f"process {process_id} shard {j}")
        entries.append({
            "key": key,
            "index": idx,
            "shape": list(data.shape),
            "crc32": _crc32(data),
        })
    npz = _shard_npz(gen_dir, process_id)
    tmp = npz.with_name(f"{npz.name}.tmp{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, npz)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    sidecar = _shard_sidecar(gen_dir, process_id)
    tmp = sidecar.with_name(f"{sidecar.name}.tmp{os.getpid()}")
    tmp.write_text(json.dumps({
        "process_id": int(process_id),
        "file": npz.name,
        "global_shape": list(global_shape),
        "dtype": dtype.name,
        "shards": entries,
    }))
    os.replace(tmp, sidecar)
    return npz


def commit_manifest(
    gen_dir: "str | Path",
    *,
    meta: dict,
    num_processes: int,
) -> Path:
    """Fold every process's sidecar into one ``MANIFEST.json`` and
    publish it atomically — THE commit point of a sharded generation.
    Exactly one process calls this, after a barrier has proven all
    ``num_processes`` sidecars durable. Verifies the shards jointly
    tile the global array exactly once (a silent gap would reassemble
    as zeros — worse than failing)."""
    gen_dir = Path(gen_dir)
    sidecars = []
    for p in range(num_processes):
        sc = _shard_sidecar(gen_dir, p)
        try:
            sidecars.append(json.loads(sc.read_text()))
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f"cannot commit {gen_dir}: process {p}'s shard sidecar "
                f"is missing ({num_processes} expected)")
        except (ValueError, OSError) as exc:
            raise CheckpointCorruptError(
                f"cannot commit {gen_dir}: sidecar {sc.name} unreadable "
                f"({exc})") from exc
    global_shape = tuple(sidecars[0]["global_shape"])
    dtype = sidecars[0]["dtype"]
    for sc in sidecars[1:]:
        if tuple(sc["global_shape"]) != global_shape or sc["dtype"] != dtype:
            raise CheckpointCorruptError(
                f"cannot commit {gen_dir}: processes disagree on the "
                f"global array ({sc['global_shape']}/{sc['dtype']} vs "
                f"{list(global_shape)}/{dtype})")
    _check_exact_cover(gen_dir, sidecars, global_shape)
    manifest = {
        "format": SHARDED_FORMAT,
        "version": SHARDED_FORMAT_VERSION,
        "meta": dict(meta),
        "global_shape": list(global_shape),
        "dtype": dtype,
        "num_processes": int(num_processes),
        "processes": sidecars,
    }
    path = gen_dir / MANIFEST_NAME
    tmp = path.with_name(f"{path.name}.tmp{os.getpid()}")
    tmp.write_text(json.dumps(manifest, indent=1))
    os.replace(tmp, path)
    return path


def _check_exact_cover(gen_dir: Path, sidecars: List[dict],
                       global_shape: Tuple[int, ...]) -> None:
    """Every element covered exactly once. Counted with a uint8 mask for
    grids a host can hold; beyond that (> 2^26 elements) only the total
    element count is checked — overlap and gap can then only cancel
    exactly, which a CRC-verified replay would still catch."""
    total = int(np.prod(global_shape))
    n_elems = sum(int(np.prod(e["shape"]))
                  for sc in sidecars for e in sc["shards"])
    if n_elems != total:
        raise CheckpointCorruptError(
            f"cannot commit {gen_dir}: shards cover {n_elems} elements, "
            f"global array has {total}")
    if total > (1 << 26):
        return
    mask = np.zeros(global_shape, np.uint8)
    for sc in sidecars:
        for e in sc["shards"]:
            mask[tuple(slice(a, b) for a, b in e["index"])] += 1
    if not (mask == 1).all():
        raise CheckpointCorruptError(
            f"cannot commit {gen_dir}: shard indices gap or overlap")


def read_manifest(gen_dir: "str | Path") -> dict:
    """The manifest of a committed generation; an absent manifest means
    an uncommitted (torn) generation — :class:`CheckpointCorruptError`."""
    path = Path(gen_dir) / MANIFEST_NAME
    try:
        manifest = json.loads(path.read_text())
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"{gen_dir} has no {MANIFEST_NAME} — generation was never "
            "committed (torn write)")
    except (ValueError, OSError) as exc:
        raise CheckpointCorruptError(
            f"{gen_dir}/{MANIFEST_NAME} unreadable ({exc})") from exc
    if manifest.get("format") != SHARDED_FORMAT:
        raise CheckpointCorruptError(
            f"{gen_dir}/{MANIFEST_NAME} is not a {SHARDED_FORMAT} manifest")
    if manifest.get("version") != SHARDED_FORMAT_VERSION:
        raise CheckpointCorruptError(
            f"unsupported sharded checkpoint version "
            f"{manifest.get('version')!r} in {gen_dir}")
    return manifest


def verify_sharded(gen_dir: "str | Path") -> dict:
    """Verify a committed generation end to end — manifest present,
    every shard file readable, every payload matching its manifest CRC32
    and shape — and return the manifest. Raises
    :class:`CheckpointCorruptError` naming the first bad shard."""
    gen_dir = Path(gen_dir)
    manifest = read_manifest(gen_dir)
    global_shape = tuple(manifest["global_shape"])
    for sc in manifest["processes"]:
        path = gen_dir / sc["file"]
        try:
            with np.load(path, allow_pickle=False) as z:
                for e in sc["shards"]:
                    _check_index_bounds(
                        e["index"], e["shape"], global_shape,
                        f"{path.name}[{e['key']}]")
                    data = np.asarray(z[e["key"]])
                    if list(data.shape) != list(e["shape"]):
                        raise CheckpointCorruptError(
                            f"{path.name}[{e['key']}] shape {data.shape} "
                            f"!= manifest {e['shape']}")
                    crc = _crc32(data)
                    if crc != e["crc32"]:
                        raise CheckpointCorruptError(
                            f"{path.name}[{e['key']}] CRC32 {crc:#010x} != "
                            f"manifest {e['crc32']:#010x} — shard is "
                            "corrupt")
        except CheckpointCorruptError:
            raise
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f"{gen_dir}: shard file {sc['file']} is missing")
        except (zipfile.BadZipFile, zlib.error, ValueError, KeyError,
                OSError, EOFError) as exc:
            raise CheckpointCorruptError(
                f"{gen_dir}: shard file {sc['file']} unreadable "
                f"({type(exc).__name__}: {exc})") from exc
    return manifest


def load_sharded(gen_dir: "str | Path",
                 *, verify: bool = True) -> Tuple[np.ndarray, dict]:
    """Reassemble the global array of one committed generation on the
    host; returns ``(array, meta)``. ``verify=True`` (the default)
    checks every CRC first — restore NEVER silently accepts a corrupt
    shard. Host cost is O(global array), same as ``gather_global``."""
    gen_dir = Path(gen_dir)
    manifest = verify_sharded(gen_dir) if verify else read_manifest(gen_dir)
    out = np.zeros(tuple(manifest["global_shape"]),
                   np.dtype(manifest["dtype"]))
    for sc in manifest["processes"]:
        with np.load(gen_dir / sc["file"], allow_pickle=False) as z:
            for e in sc["shards"]:
                out[tuple(slice(a, b) for a, b in e["index"])] = z[e["key"]]
    return out, dict(manifest["meta"])


def load_sharded_grid(gen_dir: "str | Path",
                      *, verify: bool = True) -> Tuple[np.ndarray, dict]:
    """:func:`load_sharded`, decoded to a dense cell grid per the meta's
    ``layout`` — the sharded counterpart of :func:`load_grid`."""
    arr, meta = load_sharded(gen_dir, verify=verify)
    layout = meta.get("layout")
    if layout == "packed32":
        w = meta["shape"][1]
        return bitpack.unpack_np(arr.astype(np.uint32))[:, :w], meta
    if layout == "genplanes32":
        from ..ops.packed_generations import unpack_generations_np

        w = meta["shape"][1]
        return unpack_generations_np(arr.astype(np.uint32))[:, :w], meta
    return arr, meta


def load_latest_verified(
    root: "str | Path",
) -> Tuple[np.ndarray, dict, Path, List[Tuple[Path, str]]]:
    """Newest generation that verifies clean, falling back generation by
    generation past torn or corrupt ones. Returns ``(array, meta,
    gen_dir, skipped)`` where ``skipped`` lists ``(dir, why)`` for every
    newer generation that was refused — callers surface those as
    fallback events (registry counters + flight notes). Raises
    :class:`CheckpointCorruptError` when no generation verifies."""
    gens = list_generations(root)
    skipped: List[Tuple[Path, str]] = []
    for _gen, gen_dir in reversed(gens):
        try:
            arr, meta = load_sharded(gen_dir, verify=True)
        except CheckpointCorruptError as exc:
            skipped.append((gen_dir, str(exc)))
            continue
        return arr, meta, gen_dir, skipped
    raise CheckpointCorruptError(
        f"no complete sharded checkpoint generation under {root} "
        f"({len(gens)} candidate dirs, all refused)")


def prune_sharded(root: "str | Path", keep: int = 2) -> List[Path]:
    """Delete all but the newest ``keep`` *committed* generations (and
    any uncommitted debris older than them). Never touches dirs newer
    than the newest manifest — those may be mid-write. Returns what was
    removed. ``keep >= 2`` preserves the corrupt-shard fallback target."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    gens = list_generations(root)
    committed = [(g, d) for g, d in gens if (d / MANIFEST_NAME).exists()]
    if len(committed) <= keep:
        return []
    cutoff = committed[-keep][0]
    removed = []
    for g, d in gens:
        if g < cutoff:
            shutil.rmtree(d, ignore_errors=True)
            removed.append(d)
    return removed
