"""Checkpoint/resume: exact simulation state as one NPZ file (SURVEY.md §6).

The reference has no persistence [ABSENT] — a crash loses the universe.
On TPU the whole simulation state is (packed grid, rule, topology,
generation), so checkpointing is trivially strong: save is one device→host
transfer of 1 bit/cell; resume is bit-exact. Files are self-describing so a
checkpoint can be reloaded onto a different mesh/backend than it was saved
from (sharding is an execution detail, not simulation state).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from ..engine import Engine
from ..models.generations import parse_any
from ..ops.stencil import Topology

FORMAT_VERSION = 2  # v2 adds the multistate (1 byte/cell) Generations layout
_READABLE_VERSIONS = (1, 2)  # v1 files (binary, packbits) load unchanged


def save(engine: Engine, path: "str | Path") -> Path:
    """Write the engine's exact state; returns the path written."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    grid = engine.snapshot()
    multistate = bool(grid.max(initial=0) > 1)  # Generations states
    meta = dict(
        # binary/packbits files keep the v1 stamp (layout unchanged, old
        # readers still load them); only the multistate layout gets the
        # current format version, so a future bump propagates from the
        # constant instead of silently drifting from it
        version=FORMAT_VERSION if multistate else 1,
        rule=engine.rule.notation,
        topology=engine.topology.value,
        generation=engine.generation,
        shape=list(engine.shape),
        multistate=multistate,
    )
    with open(path, "wb") as f:
        if multistate:
            # 1 byte/cell: Generations cells carry dying-state values
            np.savez_compressed(f, cells=grid, meta=json.dumps(meta))
        else:
            # packbits: 1 bit/cell on disk regardless of engine backend
            np.savez_compressed(f, bits=np.packbits(grid, axis=1), meta=json.dumps(meta))
    return path


def load_grid(path: "str | Path") -> Tuple[np.ndarray, dict]:
    """Read (grid, metadata) from a checkpoint without building an engine."""
    with np.load(Path(path), allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        if meta.get("version") not in _READABLE_VERSIONS:
            raise ValueError(
                f"unsupported checkpoint version {meta.get('version')!r} in {path}"
            )
        h, w = meta["shape"]
        if meta.get("multistate"):
            grid = np.asarray(z["cells"], dtype=np.uint8)
        else:
            grid = np.unpackbits(z["bits"], axis=1)[:, :w].astype(np.uint8)
    return grid, meta


def load_engine(
    path: "str | Path",
    *,
    mesh: Optional[Mesh] = None,
    backend: str = "packed",
) -> Engine:
    """Rebuild an Engine bit-exactly from a checkpoint (any mesh/backend)."""
    grid, meta = load_grid(path)
    engine = Engine(
        grid,
        parse_any(meta["rule"]),
        topology=Topology(meta["topology"]),
        mesh=mesh,
        backend=backend,
    )
    engine.generation = meta["generation"]
    return engine
