"""Checkpoint/resume: exact simulation state as one NPZ file (SURVEY.md §6).

The reference has no persistence [ABSENT] — a crash loses the universe.
On TPU the whole simulation state is (packed grid, rule, topology,
generation), so checkpointing is trivially strong: save is one device→host
transfer of 1 bit/cell; resume is bit-exact. Files are self-describing so a
checkpoint can be reloaded onto a different mesh/backend than it was saved
from (sharding is an execution detail, not simulation state).
"""

from __future__ import annotations

import contextlib
import json
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from ..engine import Engine
from ..models.generations import parse_any
from ..ops import bitpack
from ..ops.stencil import Topology

FORMAT_VERSION = 3  # v3 adds device-layout checkpoints (no dense detour)
_READABLE_VERSIONS = (1, 2, 3)  # older files load unchanged


def save(engine: Engine, path: "str | Path") -> Path:
    """Write the engine's exact state; returns the path written.

    Packed engines (binary bitboards and Generations bit-plane stacks)
    save their device layout directly — the v3 "packed32"/"genplanes32"
    layouts — so no dense copy is ever materialised: checkpointing a
    65536² universe moves 512 MB of words, not a 4.3 GB byte grid
    (device-side unpack + host gather, which is what snapshot() costs).
    Byte-layout engines keep the v1 (packbits) / v2 (multistate cells)
    forms. All versions reload onto any mesh/backend.

    Crash-safe: the bytes land in a temp file in the same directory and
    are ``os.replace``d into place, so a SIGKILL mid-save (the soak
    harness does exactly this) can never leave a truncated NPZ where a
    loadable checkpoint used to be — the previous checkpoint survives
    until the new one is durably whole.
    """
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    base = dict(
        rule=engine.rule.notation,
        topology=engine.topology.value,
        generation=engine.generation,
        shape=list(engine.shape),
    )
    # pid-qualified temp name: two processes checkpointing to the same
    # path (supervisor + an operator's manual save) must not interleave
    tmp = path.with_name(f"{path.name}.tmp{os.getpid()}")
    try:
        with open(tmp, "wb") as f:
            if engine._packed:
                meta = dict(version=FORMAT_VERSION, layout="packed32",
                            multistate=False, **base)
                np.savez_compressed(
                    f, words=np.asarray(engine.state), meta=json.dumps(meta))
            elif getattr(engine, "_gen_packed", False):
                meta = dict(version=FORMAT_VERSION, layout="genplanes32",
                            multistate=True, **base)
                np.savez_compressed(
                    f, planes=np.asarray(engine.state), meta=json.dumps(meta))
            else:
                grid = engine.snapshot()
                multistate = bool(grid.max(initial=0) > 1)  # Generations states
                # byte-layout files keep their historical stamps (v1 binary
                # packbits / v2 multistate cells) so old readers still load
                # them
                meta = dict(version=2 if multistate else 1,
                            multistate=multistate, **base)
                if multistate:
                    np.savez_compressed(f, cells=grid, meta=json.dumps(meta))
                else:
                    np.savez_compressed(f, bits=np.packbits(grid, axis=1),
                                        meta=json.dumps(meta))
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
    return path


def load_grid(path: "str | Path") -> Tuple[np.ndarray, dict]:
    """Read (grid, metadata) from a checkpoint without building an engine."""
    with np.load(Path(path), allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        if meta.get("version") not in _READABLE_VERSIONS:
            raise ValueError(
                f"unsupported checkpoint version {meta.get('version')!r} in {path}"
            )
        h, w = meta["shape"]
        layout = meta.get("layout")
        if layout == "packed32":
            grid = bitpack.unpack_np(np.asarray(z["words"], dtype=np.uint32))[:, :w]
        elif layout == "genplanes32":
            from ..ops.packed_generations import unpack_generations_np

            grid = unpack_generations_np(
                np.asarray(z["planes"], dtype=np.uint32))[:, :w]
        elif meta.get("multistate"):
            grid = np.asarray(z["cells"], dtype=np.uint8)
        else:
            grid = np.unpackbits(z["bits"], axis=1)[:, :w].astype(np.uint8)
    return grid, meta


def load_engine(
    path: "str | Path",
    *,
    mesh: Optional[Mesh] = None,
    backend: str = "auto",
) -> Engine:
    """Rebuild an Engine bit-exactly from a checkpoint (any mesh/backend)."""
    grid, meta = load_grid(path)
    engine = Engine(
        grid,
        parse_any(meta["rule"]),
        topology=Topology(meta["topology"]),
        mesh=mesh,
        backend=backend,
    )
    engine.generation = meta["generation"]
    return engine
