"""Structured per-step metrics with pluggable sinks (SURVEY.md §6).

The reference's only observability is its console renderer [META]; here
every tick can emit a structured record — generations/sec, cell-updates/sec,
optional population — to stdout JSONL, CSV, or an in-memory buffer (used by
tests and the bench harness). Sinks are deliberately dumb callables so a
profiler/trace exporter can be hung on the same bus.
"""

from __future__ import annotations

import csv
import dataclasses
import io
import json
import sys
from typing import Callable, List, Optional, TextIO


@dataclasses.dataclass(frozen=True)
class StepMetrics:
    generation: int                    # generation counter after the step
    generations_stepped: int           # generations covered by this record
    wall_seconds: float                # stepping time: excludes compile_seconds
    cell_updates_per_sec: float
    population: Optional[int] = None
    halo_bytes: Optional[int] = None   # est. interconnect bytes this record
    active_tiles: Optional[int] = None  # sparse backends: tiles computed
    # jit compile wall seconds this record's tick paid (obs/compile.py via
    # ops/_jit.py); split out so a first tick's XLA compile never
    # masquerades as step time — total tick wall = wall_seconds + this
    compile_seconds: Optional[float] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k in ("population", "halo_bytes", "active_tiles",
                  "compile_seconds"):
            if d[k] is None:
                d.pop(k)
        return d


Sink = Callable[[StepMetrics], None]


class JsonlSink:
    """One JSON object per record, e.g. for `tail -f` or log shipping."""

    def __init__(self, stream: Optional[TextIO] = None):
        self.stream = stream if stream is not None else sys.stdout

    def __call__(self, m: StepMetrics) -> None:
        self.stream.write(json.dumps(m.to_dict()) + "\n")
        self.stream.flush()


class CsvSink:
    def __init__(self, stream: TextIO):
        self.stream = stream
        self._writer = None

    def __call__(self, m: StepMetrics) -> None:
        row = dataclasses.asdict(m)
        if self._writer is None:
            self._writer = csv.DictWriter(self.stream, fieldnames=list(row))
            self._writer.writeheader()
        self._writer.writerow(row)
        self.stream.flush()


class BufferSink:
    """Keeps records in memory — tests and the bench harness read these."""

    def __init__(self):
        self.records: List[StepMetrics] = []

    def __call__(self, m: StepMetrics) -> None:
        self.records.append(m)


class MetricsLogger:
    def __init__(self, *sinks: Sink):
        self.sinks: List[Sink] = list(sinks)

    def add_sink(self, sink: Sink) -> None:
        self.sinks.append(sink)

    def log(self, m: StepMetrics) -> None:
        for s in self.sinks:
            s(m)
