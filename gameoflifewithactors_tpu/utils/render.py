"""Console renderer — the reference's Renderer role, off the critical path.

The reference prints every generation from its coordinator (SURVEY.md §4c)
and console I/O can dominate wall-clock; here the renderer is just another
subscriber fed already-downsampled frames (Engine.snapshot does device-side
block-max pooling), so a 16384² universe costs a ~2 KB transfer per drawn
frame. ANSI mode redraws in place; plain mode appends (pipe-friendly).
"""

from __future__ import annotations

import sys
from typing import Optional, TextIO

from ..coordinator import RenderFrame

_ANSI_HOME = "\x1b[H"
_ANSI_CLEAR = "\x1b[2J"


class ConsoleRenderer:
    """Draws frames as text. ``charset``: one glyph per cell state —
    (dead, alive) for binary rules; longer strings map Generations dying
    states to their own glyphs (values past the end reuse the last)."""

    def __init__(
        self,
        stream: Optional[TextIO] = None,
        *,
        ansi: Optional[bool] = None,
        charset: str = "·█",
    ):
        self.stream = stream if stream is not None else sys.stdout
        self.ansi = self.stream.isatty() if ansi is None else ansi
        if len(charset) < 2:
            raise ValueError("charset needs at least (dead, alive) glyphs")
        self.charset = charset
        self._first = True

    def __call__(self, frame: RenderFrame) -> None:
        out = []
        if self.ansi:
            out.append(_ANSI_CLEAR + _ANSI_HOME if self._first else _ANSI_HOME)
        chars, top = self.charset, len(self.charset) - 1
        for row in frame.grid:
            out.append("".join(chars[min(v, top)] for v in row))
            out.append("\n")
        status = f"gen {frame.generation}  grid {frame.full_shape[0]}x{frame.full_shape[1]}"
        if frame.grid.shape != frame.full_shape:
            status += f"  (view {frame.grid.shape[0]}x{frame.grid.shape[1]})"
        if frame.population is not None:
            status += f"  pop {frame.population}"
        out.append(status + "\n")
        self.stream.write("".join(out))
        self.stream.flush()
        self._first = False


class PpmSequenceWriter:
    """Numbered PPM frames for movie-making: ``stem_000123.ppm`` per write
    (ffmpeg consumes the pattern directly: ``ffmpeg -i stem_%06d.ppm``).
    Usable as a RenderFrame subscriber (writes the frame's possibly
    downsampled view) or via :meth:`write` with any grid — the CLI's
    ``--ppm-every`` feeds it full-resolution snapshots."""

    def __init__(self, path: str, *, scale: int = 1):
        import os

        base, ext = os.path.splitext(path)
        self._fmt = f"{base}_{{gen:06d}}{ext or '.ppm'}"
        self.scale = scale
        self.paths: list = []

    def write(self, grid, generation: int) -> str:
        path = self._fmt.format(gen=generation)
        save_ppm(grid, path, scale=self.scale)
        self.paths.append(path)
        return path

    def __call__(self, frame: RenderFrame) -> None:
        self.write(frame.grid, frame.generation)


def save_ppm(grid, path, *, scale: int = 1) -> None:
    """Write a state grid as a binary PPM (P6) image — the no-dependency
    image format every viewer and converter reads. State 0 is black, state
    1 white, dying Generations states fade through greys; ``scale`` scales
    pixels up for small universes. Also serves 1D spacetime
    diagrams (rows = time) straight from ops.elementary.evolve_spacetime.
    """
    import numpy as np

    g = np.asarray(grid)
    if g.ndim != 2:
        raise ValueError(f"grid must be 2D, got shape {g.shape}")
    top = max(1, int(g.max()))
    # alive (1) brightest; higher (dying) states darker but visible. Float
    # fade: integer 160 // top collapses to 0 past 160 states (every dying
    # state would render alive-white) and quantizes coarsely below that
    # float32 keeps peak memory at 2 full-grid temporaries of 4 B/cell
    # (a 16384² export stays ~2 GB, not ~4 GB in float64); exact for the
    # 8-bit output range
    fade = np.float32(255) - (g.astype(np.float32) - 1) * np.float32(160.0 / top)
    lum = np.rint(np.where(g == 0, np.float32(0), fade)).astype(np.uint8)
    if scale > 1:
        lum = np.repeat(np.repeat(lum, scale, axis=0), scale, axis=1)
    h, w = lum.shape
    with open(path, "wb") as f:
        f.write(b"P6\n%d %d\n255\n" % (w, h))
        f.write(np.stack([lum] * 3, axis=-1).tobytes())
