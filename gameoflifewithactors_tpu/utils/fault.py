"""Fault injection + checkpoint-based recovery (SURVEY.md §6).

The reference's fault story is Akka supervision restarting a crashed cell
actor — which silently loses that cell's state [RECON]. The SPMD
equivalent of "a crashed actor" is a corrupted/lost shard, and the honest
recovery story is checkpoint-based restart: GuardedRun snapshots every k
generations and, when a validator rejects the state (or stepping raises),
rolls back to the last good checkpoint and replays. Tests use the
injectors to corrupt state mid-run and prove recovery is bit-exact.
"""

from __future__ import annotations

import tempfile
from pathlib import Path
from typing import Callable, Optional

import jax
import numpy as np

from ..engine import Engine
from ..obs import flight as obs_flight
from ..obs.registry import REGISTRY
from . import checkpoint as ckpt_lib

Validator = Callable[[Engine], bool]


def _record_injection(kind: str, **detail) -> None:
    """Every induced fault shows up in /metrics
    (``goltpu_faults_injected_total{kind=...}``) and on the flight tape —
    a crash dump that doesn't say "someone corrupted the grid at t-2s"
    sends the post-mortem chasing a phantom engine bug."""
    REGISTRY.counter("faults_injected_total",
                     "induced faults, by injector kind").inc(kind=kind)
    obs_flight.note_event("fault_injected", {"fault": kind, **detail})


# -- injectors (test hooks) --------------------------------------------------

def corrupt_region(engine: Engine, top: int, left: int, h: int, w: int, seed: int = 0) -> None:
    """Overwrite a rectangle with random bits — a 'shard went bad' fault."""
    grid = engine.snapshot().copy()
    rng = np.random.default_rng(seed)
    grid[top : top + h, left : left + w] = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    engine.set_grid(grid)
    _record_injection("corrupt_region", top=top, left=left, h=h, w=w,
                      at_gen=engine.generation)


def drop_region(engine: Engine, top: int, left: int, h: int, w: int) -> None:
    """Zero a rectangle — a 'lost shard / restarted actor' fault (what Akka
    supervision's restart would leave behind: default-initialized state)."""
    grid = engine.snapshot().copy()
    grid[top : top + h, left : left + w] = 0
    engine.set_grid(grid)
    _record_injection("drop_region", top=top, left=left, h=h, w=w,
                      at_gen=engine.generation)


def _rewrite_shard(engine: Engine, shard_index: int, fn) -> None:
    """Replace one device shard of a sharded engine's state with
    ``fn(shard_data)``, leaving every other device buffer untouched.

    Unlike the region injectors above (which round-trip the WHOLE grid
    through the host via snapshot/set_grid), this touches O(shard) host
    memory and reassembles the global array from the existing per-device
    buffers — the honest model of one device's state going bad *in flight*
    while the rest of the mesh is still good (SURVEY.md §6: "corrupts/
    drops a shard"). Host-local: shard_index indexes
    ``state.addressable_shards``."""
    if engine.mesh is None:
        raise ValueError("shard injectors need a sharded engine (mesh=None)")
    if engine.backend == "sparse":
        # sparse-tiled state pairs the grid with an activity map; mutating
        # the grid behind the map's back would "corrupt" cells inside
        # sleeping tiles that then never evolve — not a recoverable-fault
        # model but an engine-invariant violation
        raise ValueError("shard injectors do not support the sparse backend")
    state = engine.state
    shards = state.addressable_shards
    if not 0 <= shard_index < len(shards):
        raise IndexError(
            f"shard_index {shard_index} out of range ({len(shards)} shards)")
    arrays = []
    for i, sh in enumerate(shards):
        data = np.asarray(sh.data)
        arrays.append(jax.device_put(fn(data) if i == shard_index else data,
                                     sh.device))
    # Engine.state is a read-only property; the injector is a privileged
    # test hook and writes the backing attribute directly — set_grid would
    # defeat the point (full-grid host round-trip + re-device_put)
    engine._state = jax.make_array_from_single_device_arrays(
        state.shape, state.sharding, arrays)


def drop_shard(engine: Engine, shard_index: int) -> None:
    """Zero one device shard in flight — the SPMD 'device lost its state'
    fault. All-dead is a valid state in every grid representation, so this
    works on packed, dense, and bit-plane engines alike."""
    _rewrite_shard(engine, shard_index, np.zeros_like)
    _record_injection("drop_shard", shard=shard_index,
                      at_gen=engine.generation)


def corrupt_shard(engine: Engine, shard_index: int, seed: int = 0) -> None:
    """Overwrite one device shard with random words in flight. Packed
    binary (2D uint32 bitboard) engines only: arbitrary bits are a valid
    state there, while dense uint8 or bit-plane stacks would need
    representation-aware noise to stay in-domain."""
    state = engine.state
    if state.ndim != 2 or state.dtype != np.uint32:
        raise ValueError(
            "corrupt_shard supports 2D packed uint32 state only; "
            "use drop_shard for other representations")
    rng = np.random.default_rng(seed)

    def scramble(data: np.ndarray) -> np.ndarray:
        return rng.integers(0, 2 ** 32, size=data.shape, dtype=np.uint32)

    _rewrite_shard(engine, shard_index, scramble)
    _record_injection("corrupt_shard", shard=shard_index,
                      at_gen=engine.generation)


def corrupt_checkpoint_file(path: "str | Path", *, seed: int = 0,
                            nbytes: int = 64) -> None:
    """Flip ``nbytes`` bytes of an on-disk checkpoint file in place — the
    torn-write/bitrot model for the durability layer (deliberately NOT
    temp+replace: damaged-in-place is the fault). A sharded-v2 restore
    must refuse the file (CRC mismatch / unreadable archive →
    ``CheckpointCorruptError``) and fall back to the previous complete
    generation; a single-file load must surface the same clean error."""
    path = Path(path)
    data = bytearray(path.read_bytes())
    if not data:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    rng = np.random.default_rng(seed)
    for i in rng.integers(0, len(data), size=min(int(nbytes), len(data))):
        data[i] ^= 0xFF
    path.write_bytes(bytes(data))
    _record_injection("checkpoint_corrupt", path=str(path))


# -- validators --------------------------------------------------------------

def population_bounds_validator(min_pop: int = 0, max_pop: Optional[int] = None) -> Validator:
    """Reject states whose population leaves [min_pop, max_pop] — the cheap
    invariant check (exact popcount is one device reduction)."""

    def validate(engine: Engine) -> bool:
        pop = engine.population()
        if pop < min_pop:
            return False
        if max_pop is not None and pop > max_pop:
            return False
        return True

    return validate


# -- guarded execution -------------------------------------------------------

class GuardedRun:
    """Checkpoint-every-k stepping with rollback-and-replay on failure.

    ``validator`` is consulted after each chunk; a False verdict (or an
    exception from the engine) triggers restore from the last good
    checkpoint. ``on_recover`` is called with the generation rolled back
    to (observability hook).
    """

    def __init__(
        self,
        engine: Engine,
        *,
        checkpoint_every: int = 100,
        checkpoint_path: Optional[str] = None,
        validator: Optional[Validator] = None,
        on_recover: Optional[Callable[[int], None]] = None,
        max_retries: int = 3,
    ):
        self.engine = engine
        self.checkpoint_every = checkpoint_every
        self.validator = validator
        self.on_recover = on_recover
        self.max_retries = max_retries
        self.recoveries = 0
        if checkpoint_path is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="gol_guard_")
            checkpoint_path = str(Path(self._tmp.name) / "guard.npz")
        self.checkpoint_path = checkpoint_path
        ckpt_lib.save(self.engine, self.checkpoint_path)  # gen-0 restore point

    def _restore(self) -> None:
        grid, meta = ckpt_lib.load_grid(self.checkpoint_path)
        self.engine.set_grid(grid, generation=meta["generation"])
        self.recoveries += 1
        obs_flight.note_event("guard_restore",
                              {"to_gen": self.engine.generation})
        if self.on_recover is not None:
            self.on_recover(self.engine.generation)

    def run(self, generations: int) -> None:
        target = self.engine.generation + generations
        retries = 0
        while self.engine.generation < target:
            chunk = min(self.checkpoint_every, target - self.engine.generation)
            last_exc: Optional[Exception] = None
            try:
                self.engine.step(chunk)
                ok = self.validator(self.engine) if self.validator else True
            except Exception as exc:  # surfaced at sync time under async dispatch
                last_exc = exc
                ok = False
            if ok:
                ckpt_lib.save(self.engine, self.checkpoint_path)
                retries = 0
            else:
                if last_exc is None:
                    REGISTRY.counter(
                        "validator_trips_total",
                        "state-validator rejections (guard + supervisor)"
                    ).inc(where="guard")
                    obs_flight.note_event(
                        "validator_trip",
                        {"where": "guard", "at_gen": self.engine.generation})
                if retries >= self.max_retries:
                    raise RuntimeError(
                        f"state validation failed {retries + 1}x in a row at "
                        f"generation {self.engine.generation}; giving up"
                    ) from last_exc
                self._restore()
                retries += 1
