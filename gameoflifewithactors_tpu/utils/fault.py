"""Fault injection + checkpoint-based recovery (SURVEY.md §6).

The reference's fault story is Akka supervision restarting a crashed cell
actor — which silently loses that cell's state [RECON]. The SPMD
equivalent of "a crashed actor" is a corrupted/lost shard, and the honest
recovery story is checkpoint-based restart: GuardedRun snapshots every k
generations and, when a validator rejects the state (or stepping raises),
rolls back to the last good checkpoint and replays. Tests use the
injectors to corrupt state mid-run and prove recovery is bit-exact.
"""

from __future__ import annotations

import tempfile
from pathlib import Path
from typing import Callable, Optional

import numpy as np

from ..engine import Engine
from . import checkpoint as ckpt_lib

Validator = Callable[[Engine], bool]


# -- injectors (test hooks) --------------------------------------------------

def corrupt_region(engine: Engine, top: int, left: int, h: int, w: int, seed: int = 0) -> None:
    """Overwrite a rectangle with random bits — a 'shard went bad' fault."""
    grid = engine.snapshot().copy()
    rng = np.random.default_rng(seed)
    grid[top : top + h, left : left + w] = rng.integers(0, 2, size=(h, w), dtype=np.uint8)
    engine.set_grid(grid)


def drop_region(engine: Engine, top: int, left: int, h: int, w: int) -> None:
    """Zero a rectangle — a 'lost shard / restarted actor' fault (what Akka
    supervision's restart would leave behind: default-initialized state)."""
    grid = engine.snapshot().copy()
    grid[top : top + h, left : left + w] = 0
    engine.set_grid(grid)


# -- validators --------------------------------------------------------------

def population_bounds_validator(min_pop: int = 0, max_pop: Optional[int] = None) -> Validator:
    """Reject states whose population leaves [min_pop, max_pop] — the cheap
    invariant check (exact popcount is one device reduction)."""

    def validate(engine: Engine) -> bool:
        pop = engine.population()
        if pop < min_pop:
            return False
        if max_pop is not None and pop > max_pop:
            return False
        return True

    return validate


# -- guarded execution -------------------------------------------------------

class GuardedRun:
    """Checkpoint-every-k stepping with rollback-and-replay on failure.

    ``validator`` is consulted after each chunk; a False verdict (or an
    exception from the engine) triggers restore from the last good
    checkpoint. ``on_recover`` is called with the generation rolled back
    to (observability hook).
    """

    def __init__(
        self,
        engine: Engine,
        *,
        checkpoint_every: int = 100,
        checkpoint_path: Optional[str] = None,
        validator: Optional[Validator] = None,
        on_recover: Optional[Callable[[int], None]] = None,
        max_retries: int = 3,
    ):
        self.engine = engine
        self.checkpoint_every = checkpoint_every
        self.validator = validator
        self.on_recover = on_recover
        self.max_retries = max_retries
        self.recoveries = 0
        if checkpoint_path is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="gol_guard_")
            checkpoint_path = str(Path(self._tmp.name) / "guard.npz")
        self.checkpoint_path = checkpoint_path
        ckpt_lib.save(self.engine, self.checkpoint_path)  # gen-0 restore point

    def _restore(self) -> None:
        grid, meta = ckpt_lib.load_grid(self.checkpoint_path)
        self.engine.set_grid(grid, generation=meta["generation"])
        self.recoveries += 1
        if self.on_recover is not None:
            self.on_recover(self.engine.generation)

    def run(self, generations: int) -> None:
        target = self.engine.generation + generations
        retries = 0
        while self.engine.generation < target:
            chunk = min(self.checkpoint_every, target - self.engine.generation)
            last_exc: Optional[Exception] = None
            try:
                self.engine.step(chunk)
                ok = self.validator(self.engine) if self.validator else True
            except Exception as exc:  # surfaced at sync time under async dispatch
                last_exc = exc
                ok = False
            if ok:
                ckpt_lib.save(self.engine, self.checkpoint_path)
                retries = 0
            else:
                if retries >= self.max_retries:
                    raise RuntimeError(
                        f"state validation failed {retries + 1}x in a row at "
                        f"generation {self.engine.generation}; giving up"
                    ) from last_exc
                self._restore()
                retries += 1
