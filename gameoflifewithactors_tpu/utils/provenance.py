"""Version provenance for persisted performance evidence.

VERDICT.md round-2 Weak #1: a persisted TPU measurement served by bench.py
after the measured code path was rewritten silently reports numbers for code
that no longer exists. Every persisted record therefore carries the git
commit of the tree it measured, and consumers call :func:`staleness` to
learn whether the record's measured paths changed since that stamp.

VERDICT.md round-4 Weak #1/#3: staleness precision. Records that named no
backend fell back to the everything-changed superset, so CPU-side feature
work (e.g. an ``ops/sparse.py`` edit) staled the binary Pallas kernel's
identity evidence whose measured files were untouched. Three fixes here:

- :data:`ITEM_PATHS` — the measured file set of every worklist item,
  derived from the imports its child body actually exercises
  (``scripts/tpu_worklist.py``); consumers pass ``item=`` so old records
  without their own path list still get a precise set.
- New records carry a ``measured_paths`` field stamped at capture time,
  which :func:`staleness` prefers over any in-code map — the capture-time
  truth survives later refactors of this module.
- Timing-protocol files are part of the measured set (``bench.py`` for
  bench records): an edit to the measurement protocol flags the records
  it produced, not just kernel edits.

Additionally, *comment-only* edits no longer stale: when git reports a
measured ``.py`` file changed, :func:`staleness` compares the token stream
(comments and blank lines dropped) at the stamp vs the working tree, and
certifies the record fresh when the executable code is identical. This is
what lets hot-path files carry freeze-notice comments (VERDICT r4 #8)
without destroying the very evidence those notices protect. Docstrings are
STRING tokens and still count as code — only ``#`` comments are exempt.

Pure stdlib + ``git`` subprocess; degrades to "unknown provenance" (which
consumers treat as stale) when git is unavailable or the repo is absent —
evidence must never look *fresher* than it can be proven to be.
"""

from __future__ import annotations

import ast
import io
import os
import subprocess
import tokenize

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PKG = "gameoflifewithactors_tpu"
# Transitively shared substrate: the jit/donation wrapper, the Topology /
# shift plumbing, and the bit-packing layout feed every measured op.
_CORE = [f"{_PKG}/ops/_jit.py", f"{_PKG}/ops/stencil.py", f"{_PKG}/ops/bitpack.py"]
_RULES = f"{_PKG}/models/rules.py"          # B/S semantics (binary families)
_GENS = f"{_PKG}/models/generations.py"     # parse_any + Generations semantics
_LTL = f"{_PKG}/models/ltl.py"              # LtL rule semantics
_MESHY = [f"{_PKG}/parallel/sharded.py", f"{_PKG}/parallel/halo.py",
          f"{_PKG}/parallel/mesh.py"]

# The measured code path per bench backend: if any of these files changed
# after a record's commit stamp, the record describes a predecessor kernel
# and must be flagged. bench.py is in every set because it IS the timing
# protocol of the records that carry a "(backend, ...)" metric string
# (VERDICT r4 Weak #3) — a sync/repetition edit there changes what the
# number means as surely as a kernel edit does.
BACKEND_PATHS = {
    "pallas": [f"{_PKG}/ops/pallas_stencil.py", f"{_PKG}/ops/packed.py",
               *_CORE, _RULES, "bench.py"],
    "packed": [f"{_PKG}/ops/packed.py", f"{_PKG}/ops/packed_generations.py",
               f"{_PKG}/ops/packed_ltl.py", *_CORE, _RULES, _GENS, _LTL,
               "bench.py"],
    "dense": [f"{_PKG}/ops/generations.py", f"{_PKG}/ops/ltl.py",
              *_CORE, _RULES, _GENS, _LTL, "bench.py"],
    "sparse": [f"{_PKG}/ops/sparse.py", f"{_PKG}/ops/packed.py",
               *_CORE, _RULES, f"{_PKG}/models/seeds.py", "bench.py"],
}
# Fallback when the backend can't be parsed out of a record: everything.
ALL_OPS_PATHS = [f"{_PKG}/ops", f"{_PKG}/parallel", f"{_PKG}/models"]

_PALLAS_BINARY = [f"{_PKG}/ops/pallas_stencil.py", f"{_PKG}/ops/packed.py",
                  *_CORE, _RULES]
# Measured file set per worklist item (scripts/tpu_worklist.py child
# bodies): exactly the modules whose code the child's measurement
# exercises, so unrelated CPU-side work stops staling on-chip evidence
# (VERDICT r4 Weak #1). Sets of items whose results carry measured RATES
# also include the worklist script itself (appended below) — the
# children's timing protocol (_bench_rate, sync, gens sizing) lives
# there, and the same protocol-edit rule that puts bench.py in
# BACKEND_PATHS applies; the cost (an edit for one item stales all rate
# items until recapture) is the price of file-granularity honesty. The
# two pure-assertion items (_ASSERTION_ITEMS) are exempt: a bit-identity
# or HLO-structure verdict embeds the cases it checked in the record
# itself, and no timing-protocol edit can change an equality result.
# Keep in sync with the child imports when adding items; new captures
# embed this set as ``measured_paths`` so the record stays self-describing.
ITEM_PATHS = {
    "pallas_identity": [*_PALLAS_BINARY],
    "pallas_autotune": [*_PALLAS_BINARY],
    "pallas_band": [*_PALLAS_BINARY, *_MESHY],
    "profile_trace": [*_PALLAS_BINARY, f"{_PKG}/utils/profiling.py"],
    "bench_packed": [f"{_PKG}/ops/packed.py", *_CORE, _RULES, "bench.py"],
    "ltl_bosco": [f"{_PKG}/ops/ltl.py", f"{_PKG}/ops/packed_ltl.py",
                  *_CORE, _RULES, _GENS, _LTL],
    "generations_brain": [f"{_PKG}/ops/generations.py",
                          f"{_PKG}/ops/packed_generations.py",
                          *_CORE, _RULES, _GENS],
    "ltl_lowering": [f"{_PKG}/ops/ltl.py", *_CORE, _GENS, _LTL],
    "pallas_generations": [f"{_PKG}/ops/pallas_stencil.py",
                           f"{_PKG}/ops/packed_generations.py",
                           *_CORE, _RULES, _GENS],
    "ltl_pallas": [f"{_PKG}/ops/pallas_stencil.py", f"{_PKG}/ops/packed_ltl.py",
                   *_CORE, _RULES, _GENS, _LTL, *_MESHY],
    "ltl_planes": [f"{_PKG}/ops/packed_ltl.py", f"{_PKG}/ops/ltl.py",
                   f"{_PKG}/ops/packed_generations.py",
                   *_CORE, _RULES, _GENS, _LTL],
    "sparse_tiled": [f"{_PKG}/ops/sparse.py", f"{_PKG}/ops/packed.py",
                     *_CORE, _RULES, f"{_PKG}/models/seeds.py", *_MESHY],
    "elementary": [f"{_PKG}/ops/elementary.py", *_CORE,
                   f"{_PKG}/models/elementary.py"],
    "config5_sparse": [f"{_PKG}/ops/sparse.py", f"{_PKG}/ops/packed.py",
                       *_CORE, _RULES, f"{_PKG}/models/seeds.py",
                       "scripts/config5_sparse.py"],
}
_ASSERTION_ITEMS = ("pallas_identity", "ltl_lowering")
for _item, _paths in ITEM_PATHS.items():
    if _item not in _ASSERTION_ITEMS:
        _paths.append("scripts/tpu_worklist.py")


def repo_root() -> str:
    """Absolute path of the repository this package lives in — where the
    persisted evidence (results/) is found."""
    return _REPO


def _git(*args: str, repo: str | None = None) -> str | None:
    try:
        r = subprocess.run(["git", *args], cwd=repo or _REPO,
                           capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return r.stdout if r.returncode == 0 else None


def git_head(repo: str | None = None) -> str | None:
    """Short hash of HEAD, or None when unknowable."""
    out = _git("rev-parse", "--short", "HEAD", repo=repo)
    return out.strip() if out is not None else None


def changed_since(commit: str, paths: list[str], repo: str | None = None) -> list[str] | None:
    """Files under ``paths`` changed in ``commit..HEAD`` (committed changes),
    plus any with uncommitted modifications now. None = cannot determine."""
    log = _git("log", "--name-only", "--format=", f"{commit}..HEAD", "--", *paths,
               repo=repo)
    if log is None:
        return None
    dirty = _git("status", "--porcelain", "--", *paths, repo=repo)
    if dirty is None:
        # can't tell whether the tree is dirty -> can't certify freshness
        return None
    files = {ln.strip() for ln in log.splitlines() if ln.strip()}
    files |= {ln[3:].strip() for ln in dirty.splitlines() if ln.strip()}
    return sorted(files)


_EQUIV_CACHE: dict[tuple, bool] = {}


def _code_tokens(src: str) -> list[tuple[int, str]] | None:
    """Token stream with comments and non-logical newlines dropped; None
    when the source doesn't tokenize (treat as not-comparable)."""
    try:
        return [(t.type, t.string)
                for t in tokenize.generate_tokens(io.StringIO(src).readline)
                if t.type not in (tokenize.COMMENT, tokenize.NL)]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None


def code_equivalent(commit: str, path: str, repo: str | None = None) -> bool:
    """True when ``path``'s executable code is identical between ``commit``
    and the working tree — i.e. every difference git sees is a ``#`` comment
    or blank line. Only certifies ``.py`` files; anything else (or a file
    that fails to read/tokenize on either side) counts as really changed.

    Memoized: the report loop asks the same question once per record
    sharing a changed file, and each miss costs a ``git show`` subprocess
    plus two tokenizations. The commit side is immutable; the working-tree
    side is keyed by the file's (mtime_ns, size) so an edit mid-process
    invalidates the entry instead of serving the pre-edit answer."""
    try:
        st = os.stat(os.path.join(repo or _REPO, path))
        tree_key = (st.st_mtime_ns, st.st_size)
    except OSError:
        tree_key = None
    key = (commit, path, repo, tree_key)
    if key not in _EQUIV_CACHE:
        _EQUIV_CACHE[key] = _code_equivalent_uncached(commit, path, repo)
    return _EQUIV_CACHE[key]


def _code_equivalent_uncached(commit: str, path: str, repo: str | None) -> bool:
    if not path.endswith(".py"):
        return False
    old = _git("show", f"{commit}:{path}", repo=repo)
    if old is None:
        return False
    try:
        with open(os.path.join(repo or _REPO, path)) as f:
            new = f.read()
    except OSError:
        return False
    old_t, new_t = _code_tokens(old), _code_tokens(new)
    return old_t is not None and old_t == new_t


def _protocol_scope(path: str, item: str | None) -> tuple[str, ...] | None:
    """The functions within ``path`` that constitute a record's measurement
    protocol, or None when the whole file is the measured surface.

    Protocol files (bench.py, scripts/tpu_worklist.py) mix measurement
    code with serving/reporting/orchestration; an edit to the latter
    cannot change what a record measured. Scoping staleness to the
    protocol functions is what keeps a mid-window fix to ONE failing
    worklist child from re-staling every record captured minutes earlier
    in the same window (and so re-burning it). tpu_worklist scoping needs
    the record's item (each child function is its own protocol); with no
    item known the whole file stays the conservative surface. Module-
    level edits outside these functions (e.g. the _SMOKE default) are
    accepted as non-measurement by this contract."""
    if path == "bench.py":
        return ("run_bench",)
    if path == "scripts/tpu_worklist.py" and item:
        return ("_bench_rate", "_sync_scalar", "_device_equal",
                f"child_{item}")
    return None


def _fn_tokens(src: str, name: str) -> list | None:
    """Comparison key of top-level function ``name`` in ``src``: its token
    stream (comments/blank lines dropped) plus its decorator ASTs —
    get_source_segment excludes decorators, and a decorator swap changes
    behavior as surely as a body edit. None when absent/unparseable."""
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            seg = ast.get_source_segment(src, node)
            if seg is None:
                return None
            toks = _code_tokens(seg)
            if toks is None:
                return None
            return [tuple(ast.dump(d) for d in node.decorator_list), *toks]
    return None


def _scoped_equal(commit: str, path: str, repo: str | None,
                  names: tuple[str, ...]) -> bool:
    """True when every named function is token-identical between
    ``commit`` and the working tree; a function missing or unparseable on
    either side counts as changed."""
    old = _git("show", f"{commit}:{path}", repo=repo)
    if old is None:
        return False
    try:
        with open(os.path.join(repo or _REPO, path)) as f:
            new = f.read()
    except OSError:
        return False
    for name in names:
        old_t = _fn_tokens(old, name)
        if old_t is None or old_t != _fn_tokens(new, name):
            return False
    return True


def explicit_record_paths(record: dict, item: str | None = None) -> list[str] | None:
    """The measured file set a record can *specifically* claim, most
    specific source first: its own capture-time ``measured_paths``, the
    in-code per-item set, the metric-named backend's set. None when only
    the conservative superset would apply — callers embedding a set into
    a new record must not embed the superset (that would lock coarseness
    into the record and defeat later precision fixes)."""
    own = record.get("measured_paths")
    if isinstance(own, list) and own:
        return own
    if item and item in ITEM_PATHS:
        return ITEM_PATHS[item]
    metric = record.get("metric", "")
    if "(" in metric:  # "... (pallas, 50% soup, tpu)" names the resolved backend
        backend = metric.rsplit("(", 1)[1].split(",")[0].strip()
        if backend in BACKEND_PATHS:
            return BACKEND_PATHS[backend]
    return None


def record_paths(record: dict, item: str | None = None) -> list[str]:
    """Like :func:`explicit_record_paths` but falling back to the
    conservative everything-superset for staleness checking."""
    return explicit_record_paths(record, item=item) or ALL_OPS_PATHS


def head_stamp(paths: list[str] | None = None, repo: str | None = None) -> dict:
    """Provenance stamp for a measurement taken NOW: ``{"commit": <head>}``,
    plus ``"commit_dirty": True`` when the measured paths have uncommitted
    edits (or dirtiness can't be determined) — a dirty-tree measurement ran
    code that exists at no commit, so it must never get clean provenance.
    When ``paths`` is given the stamp also embeds it as ``measured_paths``
    so the record self-describes what it measured."""
    stamp: dict = {"commit": git_head(repo=repo)}
    dirty = _git("status", "--porcelain", "--", *(paths or ALL_OPS_PATHS), repo=repo)
    if dirty is None:
        stamp["commit_dirty"] = True
    elif dirty.strip():
        # comment-only uncommitted edits (e.g. a freeze notice awaiting its
        # commit) don't brand the capture dirty: the executable code IS the
        # stamped commit's, provable via the same token comparison
        # staleness() uses. Untracked files and non-.py edits fail the
        # equivalence check and keep the dirty brand.
        dirty_files = [ln[3:].strip() for ln in dirty.splitlines() if ln.strip()]
        head = stamp["commit"]
        if not head or not all(code_equivalent(head, f, repo=repo)
                               for f in dirty_files):
            stamp["commit_dirty"] = True
    if paths:
        stamp["measured_paths"] = list(paths)
    return stamp


def staleness(record: dict, repo: str | None = None, item: str | None = None) -> dict:
    """Classify a persisted measurement record's provenance.

    Returns ``{"stale": bool, "reason": str}`` — ``stale`` is True when the
    record has no commit stamp, the stamp can't be checked, or the measured
    code (see :func:`record_paths`; ``item`` selects the per-worklist-item
    set for records that predate ``measured_paths``) changed since the
    stamp. Comment-only edits to measured ``.py`` files do not stale.
    """
    commit = record.get("commit")
    if not commit:
        return {"stale": True, "reason": "record has no commit stamp"}
    if record.get("commit_dirty"):
        return {"stale": True,
                "reason": f"measured tree had uncommitted changes at record time ({commit})"}
    if record.get("commit_approx"):
        # hand-backfilled stamp: the true measured tree is a guess, so the
        # record can never be certified fresh even if paths look unchanged
        return {"stale": True,
                "reason": f"commit stamp {commit} is approximate (backfilled), "
                          "cannot certify the measured tree"}
    paths = record_paths(record, item=item)
    changed = changed_since(commit, paths, repo=repo)
    if changed is None:
        return {"stale": True, "reason": f"cannot verify commit {commit} (git unavailable)"}
    benign, really = [], []
    for f in changed:
        if code_equivalent(commit, f, repo=repo):
            benign.append(f"{f} (comment-only)")
            continue
        scope = _protocol_scope(f, item or record.get("worklist_item"))
        if scope and _scoped_equal(commit, f, repo, scope):
            benign.append(f"{f} (protocol functions unchanged)")
            continue
        really.append(f)
    if really:
        return {"stale": True,
                "reason": f"measured paths changed since {commit}: {', '.join(really[:4])}"
                          + (f" (+{len(really) - 4} more)" if len(really) > 4 else "")}
    if benign:
        return {"stale": False,
                "reason": f"measured code unchanged since {commit} "
                          f"(benign edits: {', '.join(benign[:4])})"}
    return {"stale": False, "reason": f"measured paths unchanged since {commit}"}
