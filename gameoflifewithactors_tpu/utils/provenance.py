"""Version provenance for persisted performance evidence.

VERDICT.md round-2 Weak #1: a persisted TPU measurement served by bench.py
after the measured code path was rewritten silently reports numbers for code
that no longer exists. Every persisted record therefore carries the git
commit of the tree it measured, and consumers call :func:`staleness` to
learn whether the record's measured paths changed since that stamp.

Pure stdlib + ``git`` subprocess; degrades to "unknown provenance" (which
consumers treat as stale) when git is unavailable or the repo is absent —
evidence must never look *fresher* than it can be proven to be.
"""

from __future__ import annotations

import os
import subprocess

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The measured code path per bench backend: if any of these files changed
# after a record's commit stamp, the record describes a predecessor kernel
# and must be flagged. Conservative supersets: transitively imported shared
# helpers (_jit donation wrapper, stencil's Topology/rule plumbing, bitpack)
# are in every set — a rewrite there changes every backend's measured code.
_SHARED = ["gameoflifewithactors_tpu/ops/_jit.py",
           "gameoflifewithactors_tpu/ops/stencil.py",
           "gameoflifewithactors_tpu/ops/bitpack.py",
           "gameoflifewithactors_tpu/models"]  # rule semantics feed every op
BACKEND_PATHS = {
    "pallas": ["gameoflifewithactors_tpu/ops/pallas_stencil.py",
               "gameoflifewithactors_tpu/ops/packed.py", *_SHARED],
    "packed": ["gameoflifewithactors_tpu/ops/packed.py",
               "gameoflifewithactors_tpu/ops/packed_generations.py",
               "gameoflifewithactors_tpu/ops/packed_ltl.py", *_SHARED],
    "dense": ["gameoflifewithactors_tpu/ops/generations.py",
              "gameoflifewithactors_tpu/ops/ltl.py", *_SHARED],
    "sparse": ["gameoflifewithactors_tpu/ops/sparse.py",
               "gameoflifewithactors_tpu/ops/packed.py", *_SHARED],
}
# Fallback when the backend can't be parsed out of a record: everything.
ALL_OPS_PATHS = ["gameoflifewithactors_tpu/ops", "gameoflifewithactors_tpu/parallel",
                 "gameoflifewithactors_tpu/models"]


def _git(*args: str, repo: str | None = None) -> str | None:
    try:
        r = subprocess.run(["git", *args], cwd=repo or _REPO,
                           capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return r.stdout.strip() if r.returncode == 0 else None


def git_head(repo: str | None = None) -> str | None:
    """Short hash of HEAD, or None when unknowable."""
    return _git("rev-parse", "--short", "HEAD", repo=repo)


def changed_since(commit: str, paths: list[str], repo: str | None = None) -> list[str] | None:
    """Files under ``paths`` changed in ``commit..HEAD`` (committed changes),
    plus any with uncommitted modifications now. None = cannot determine."""
    log = _git("log", "--name-only", "--format=", f"{commit}..HEAD", "--", *paths,
               repo=repo)
    if log is None:
        return None
    dirty = _git("status", "--porcelain", "--", *paths, repo=repo)
    if dirty is None:
        # can't tell whether the tree is dirty -> can't certify freshness
        return None
    files = {ln.strip() for ln in log.splitlines() if ln.strip()}
    files |= {ln[3:].strip() for ln in dirty.splitlines() if ln.strip()}
    return sorted(files)


def head_stamp(paths: list[str] | None = None, repo: str | None = None) -> dict:
    """Provenance stamp for a measurement taken NOW: ``{"commit": <head>}``,
    plus ``"commit_dirty": True`` when the measured paths have uncommitted
    edits (or dirtiness can't be determined) — a dirty-tree measurement ran
    code that exists at no commit, so it must never get clean provenance."""
    stamp: dict = {"commit": git_head(repo=repo)}
    dirty = _git("status", "--porcelain", "--", *(paths or ALL_OPS_PATHS), repo=repo)
    if dirty is None or dirty:
        stamp["commit_dirty"] = True
    return stamp


def staleness(record: dict, repo: str | None = None) -> dict:
    """Classify a persisted measurement record's provenance.

    Returns ``{"stale": bool, "reason": str}`` — ``stale`` is True when the
    record has no commit stamp, the stamp can't be checked, or the measured
    backend's code paths changed since the stamp.
    """
    commit = record.get("commit")
    if not commit:
        return {"stale": True, "reason": "record has no commit stamp"}
    if record.get("commit_dirty"):
        return {"stale": True,
                "reason": f"measured tree had uncommitted changes at record time ({commit})"}
    if record.get("commit_approx"):
        # hand-backfilled stamp: the true measured tree is a guess, so the
        # record can never be certified fresh even if paths look unchanged
        return {"stale": True,
                "reason": f"commit stamp {commit} is approximate (backfilled), "
                          "cannot certify the measured tree"}
    backend = None
    metric = record.get("metric", "")
    if "(" in metric:  # "... (pallas, 50% soup, tpu)" names the resolved backend
        backend = metric.rsplit("(", 1)[1].split(",")[0].strip()
    paths = BACKEND_PATHS.get(backend, ALL_OPS_PATHS)
    changed = changed_since(commit, paths, repo=repo)
    if changed is None:
        return {"stale": True, "reason": f"cannot verify commit {commit} (git unavailable)"}
    if changed:
        return {"stale": True,
                "reason": f"measured paths changed since {commit}: {', '.join(changed[:4])}"
                          + (f" (+{len(changed) - 4} more)" if len(changed) > 4 else "")}
    return {"stale": False, "reason": f"measured paths unchanged since {commit}"}
