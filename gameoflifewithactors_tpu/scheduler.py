"""TickScheduler — host-side generation driver (run/pause/step/rate).

The reference drives generations with Akka's timer sending periodic Tick
messages to the coordinator (SURVEY.md §2 [META]); the TPU-native analogue
is a host loop that *dispatches* device work and rate-limits with wall-clock
sleeps. Because Engine.step is async-dispatch, an unpaced scheduler keeps
the device pipeline full (the host is always one generation ahead); a paced
one (rate_hz) gives the reference's watchable-console behavior. Control
methods (pause/resume/stop/step_once) are thread-safe so an interactive
front-end can drive a running loop.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .coordinator import GridCoordinator
from .obs import spans as obs_spans


class TickScheduler:
    def __init__(
        self,
        coordinator: GridCoordinator,
        *,
        rate_hz: Optional[float] = None,
        generations_per_tick: int = 1,
    ):
        if rate_hz is not None and rate_hz <= 0:
            raise ValueError(f"rate_hz must be positive, got {rate_hz}")
        if generations_per_tick < 1:
            raise ValueError("generations_per_tick must be >= 1")
        self.coordinator = coordinator
        self.rate_hz = rate_hz
        self.generations_per_tick = generations_per_tick
        self._paused = threading.Event()
        self._stopped = threading.Event()
        self._wake = threading.Event()

    # -- control (thread-safe) ----------------------------------------------

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()
        self._wake.set()

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()

    @property
    def paused(self) -> bool:
        return self._paused.is_set()

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def step_once(self) -> None:
        """Single-step while paused (the reference's debug affordance)."""
        self.coordinator.tick(self.generations_per_tick)

    # -- the loop ------------------------------------------------------------

    def run(self, max_generations: Optional[int] = None) -> int:
        """Blocking tick loop; returns generations run. Use
        ``threading.Thread(target=scheduler.run)`` for a background driver.
        """
        done = 0
        period = 1.0 / self.rate_hz if self.rate_hz else 0.0
        next_due = time.perf_counter()
        # one enclosing span for the whole driver loop: rate-limit sleeps
        # and pause waits are scheduler.run time minus the nested
        # coordinator.tick time, with no extra per-iteration bookkeeping
        with obs_spans.span("scheduler.run",
                            max_generations=max_generations,
                            rate_hz=self.rate_hz):
            while not self._stopped.is_set():
                # quota check must precede the pause check: a completed run
                # should return even if someone paused it at the finish line
                if max_generations is not None and done >= max_generations:
                    break
                if self._paused.is_set():
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                n = self.generations_per_tick
                if max_generations is not None:
                    n = min(n, max_generations - done)
                self.coordinator.tick(n)
                done += n
                if period:
                    next_due += period
                    delay = next_due - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    else:
                        next_due = time.perf_counter()  # fell behind; don't burst
        return done
