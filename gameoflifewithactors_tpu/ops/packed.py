"""Bit-parallel (SWAR) Game-of-Life step on 32-cells-per-word grids.

Where the reference pays ~9 mailbox messages per cell per generation
(SURVEY.md §4b), this path pays roughly one bitwise VPU op per *word* per
adder stage: the 8 neighbor indicator planes are summed with a carry-save
adder network into 4 bit-planes of the neighbor count, and the B/S rule is
evaluated as a boolean function of those planes. Everything is uint32
bitwise ops on static shapes — XLA fuses the whole generation into a single
elementwise pass over ~9 shifted views of the packed grid, which is
memory-bound at ~1 bit/cell of traffic.

Two entry points:

- :func:`step_packed` — whole-grid step with TORUS or DEAD boundary
  (single-device path).
- :func:`step_packed_ext` — step on a halo-extended ``(h+2, wp+2)`` tile
  with *no* boundary logic, for a sharded engine that builds halos via
  ``lax.ppermute`` and calls this per tile. Keeping one core
  plane-extraction routine for both paths is what makes a multi-device
  bit-identity test (SURVEY.md §5) meaningful.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..models.rules import Rule
from ._jit import optionally_donated
from .stencil import Topology

_TOP_BIT = 31  # bit index holding the highest column of a word


def _csa(a, b, c):
    """Carry-save full adder on bit-planes: returns (sum, carry)."""
    s = a ^ b
    return s ^ c, (a & b) | (c & s)


def bit_sliced_sum(planes: Sequence[jax.Array]) -> List[jax.Array]:
    """Sum N one-bit planes into LSB-first count bit-planes (CSA network)."""
    level = list(planes)
    out: List[jax.Array] = []
    while level:
        carries: List[jax.Array] = []
        while len(level) >= 3:
            s, c = _csa(level.pop(), level.pop(), level.pop())
            level.append(s)
            carries.append(c)
        if len(level) == 2:
            a, b = level.pop(), level.pop()
            level.append(a ^ b)
            carries.append(a & b)
        out.append(level[0])
        level = carries
    return out


def _count_eq(bits: Sequence[jax.Array], n: int) -> jax.Array:
    """Plane that is all-ones where the bit-sliced count equals ``n``."""
    acc = None
    for k, b in enumerate(bits):
        term = b if (n >> k) & 1 else ~b
        acc = term if acc is None else acc & term
    return acc


def apply_rule_planes(alive: jax.Array, bits: Sequence[jax.Array], rule: Rule) -> jax.Array:
    """Next-generation plane from the alive plane + count bit-planes."""
    zero = jnp.zeros_like(alive)
    born = zero
    for n in sorted(rule.born):
        born = born | _count_eq(bits, n)
    keep = zero
    for n in sorted(rule.survive):
        keep = keep | _count_eq(bits, n)
    return (alive & keep) | (~alive & born)


def _shift_west(p: jax.Array, left_word: jax.Array) -> jax.Array:
    """Plane of west neighbors: bit i <- bit i-1, borrowing bit 31 of the
    word to the left (``left_word``) at each word boundary."""
    return (p << 1) | (left_word >> _TOP_BIT)


def _shift_east(p: jax.Array, right_word: jax.Array) -> jax.Array:
    """Plane of east neighbors: bit i <- bit i+1, borrowing bit 0 of the
    word to the right."""
    return (p >> 1) | (right_word << _TOP_BIT)


def _row_triplet(p: jax.Array, topology: Topology) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(north, self, south) row-aligned views of the packed grid."""
    north = jnp.roll(p, 1, axis=0)
    south = jnp.roll(p, -1, axis=0)
    if topology is Topology.DEAD:
        zero_row = jnp.zeros_like(p[:1])
        north = jnp.concatenate([zero_row, p[:-1]], axis=0)
        south = jnp.concatenate([p[1:], zero_row], axis=0)
    return north, p, south


def horizontal_planes(slab: jax.Array, topology: Topology) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(west, center, east) planes of a row-aligned slab, with cross-word
    carries; word columns wrap for TORUS and see zeros for DEAD.

    DEAD is a roll + edge-column mask rather than a concatenate of
    unaligned slices: a lane-dimension concat has no Mosaic lowering
    ("result/input offset mismatch on non-concat dimension"), while roll
    (tpu.rotate) + iota select compiles in the Pallas kernel and fuses
    just as well under plain XLA.
    """
    left = jnp.roll(slab, 1, axis=1)
    right = jnp.roll(slab, -1, axis=1)
    if topology is not Topology.TORUS:
        cols = jax.lax.broadcasted_iota(jnp.int32, slab.shape, 1)
        left = jnp.where(cols == 0, jnp.uint32(0), left)
        right = jnp.where(cols == slab.shape[1] - 1, jnp.uint32(0), right)
    return _shift_west(slab, left), slab, _shift_east(slab, right)


def neighbor_planes(p: jax.Array, topology: Topology) -> List[jax.Array]:
    """The 8 Moore-neighbor indicator planes of a packed grid."""
    planes: List[jax.Array] = []
    for dv, slab in zip((-1, 0, 1), _row_triplet(p, topology)):
        w, c, e = horizontal_planes(slab, topology)
        planes.extend([w, e] if dv == 0 else [w, c, e])
    return planes


@optionally_donated("p")
def step_packed(p: jax.Array, *, rule: Rule, topology: Topology = Topology.TORUS) -> jax.Array:
    """One generation on a (H, W/32) uint32 packed grid."""
    bits = bit_sliced_sum(neighbor_planes(p, topology))
    return apply_rule_planes(p, bits, rule)


@optionally_donated("p")
def multi_step_packed(
    p: jax.Array,
    n: jax.Array,
    *,
    rule: Rule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations in one jitted fori_loop over the fused SWAR step."""
    def body(_, s):
        return apply_rule_planes(s, bit_sliced_sum(neighbor_planes(s, topology)), rule)
    return jax.lax.fori_loop(0, n, body, p)


def step_packed_slab(slab: jax.Array, rule: Rule, topology: Topology) -> jax.Array:
    """One generation for the interior rows of a (L, Wp) slab -> (L-2, Wp).

    Rows shrink (vertical halos consumed); columns use ``topology`` across
    the slab's own width: TORUS when the slab spans the full grid width
    (the Pallas kernel's blocks), DEAD when cells beyond the slab are
    unknown-and-treated-dead (the communication-avoiding sharded runner,
    whose 32-cell halo words absorb the resulting edge corruption).
    """
    h = slab.shape[0] - 2
    planes = []
    alive = None
    for dv in (0, 1, 2):
        s = jax.lax.slice_in_dim(slab, dv, dv + h, axis=0)
        w, c, e = horizontal_planes(s, topology)
        if dv == 1:
            alive = c
            planes.extend([w, e])
        else:
            planes.extend([w, c, e])
    return apply_rule_planes(alive, bit_sliced_sum(planes), rule)


def neighbor_planes_ext(ext: jax.Array) -> Tuple[jax.Array, List[jax.Array]]:
    """(alive, 8 neighbor planes) from a halo-extended (h+2, wp+2) tile.

    The extended tile carries one halo row top/bottom and one halo *word*
    (32 columns) left/right — only 1 bit of each halo word is consumed, but
    shipping whole words keeps ppermute payloads aligned and the plane
    extraction uniform. No wraparound: all neighbors come from real slices.
    """
    h = ext.shape[0] - 2
    planes: List[jax.Array] = []
    center = None
    for dv in (0, 1, 2):
        slab = ext[dv:dv + h, :]                       # (h, wp+2)
        left = slab[:, :-2]                            # word to the left
        mid = slab[:, 1:-1]
        right = slab[:, 2:]
        w = _shift_west(mid, left)
        e = _shift_east(mid, right)
        if dv == 1:
            center = mid
            planes.extend([w, e])
        else:
            planes.extend([w, mid, e])
    return center, planes


def step_packed_ext(ext: jax.Array, rule: Rule) -> jax.Array:
    """One generation on a halo-extended tile; returns the (h, wp) interior."""
    alive, planes = neighbor_planes_ext(ext)
    return apply_rule_planes(alive, bit_sliced_sum(planes), rule)
