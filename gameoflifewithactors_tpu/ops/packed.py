"""Bit-parallel (SWAR) Game-of-Life step on 32-cells-per-word grids.

Where the reference pays ~9 mailbox messages per cell per generation
(SURVEY.md §4b), this path pays roughly one bitwise VPU op per *word* per
adder stage: each row's horizontal 2-bit sums (``T = w+c+e``, ``S = w+e``)
are computed once and the neighbor count assembled as ``T_north + S +
T_south`` — three 2-bit adds instead of an 8-plane carry-save network,
~25% fewer ops, because every T plane feeds BOTH vertical neighbors (reuse
a flat plane list cannot express). The B/S rule is then a boolean function
of the 4 count bit-planes. Everything is uint32 bitwise ops on static
shapes — XLA fuses the whole generation into a single elementwise pass,
memory-bound at ~1 bit/cell of traffic (the Pallas kernel lifts even
that via temporal blocking, making these op counts the bound that matters).

Two entry points:

- :func:`step_packed` — whole-grid step with TORUS or DEAD boundary
  (single-device path).
- :func:`step_packed_ext` — step on a halo-extended ``(h+2, wp+2)`` tile
  with *no* boundary logic, for a sharded engine that builds halos via
  ``lax.ppermute`` and calls this per tile. Keeping one core
  plane-extraction routine for both paths is what makes a multi-device
  bit-identity test (SURVEY.md §5) meaningful.
"""

# EVIDENCE FREEZE (VERDICT r4 #8): this file is a measured path of the
# serving on-chip records (the pallas kernel imports its stencil math
# from here) — see the matching notice in ops/pallas_stencil.py. Any
# non-comment edit re-stales the 2.20e12 headline and the pallas_identity
# record until recapture; comment-only edits are certified harmless by
# utils/provenance.py's token comparison.

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..models.rules import Rule
from ._jit import BuiltRunner, optionally_donated, register_builder
from .stencil import Topology

_TOP_BIT = 31  # bit index holding the highest column of a word


def _csa(a, b, c):
    """Carry-save full adder on bit-planes: returns (sum, carry)."""
    s = a ^ b
    return s ^ c, (a & b) | (c & s)


def bit_sliced_sum(planes: Sequence[jax.Array]) -> List[jax.Array]:
    """Sum N one-bit planes into LSB-first count bit-planes (CSA network)."""
    level = list(planes)
    out: List[jax.Array] = []
    while level:
        carries: List[jax.Array] = []
        while len(level) >= 3:
            s, c = _csa(level.pop(), level.pop(), level.pop())
            level.append(s)
            carries.append(c)
        if len(level) == 2:
            a, b = level.pop(), level.pop()
            level.append(a ^ b)
            carries.append(a & b)
        out.append(level[0])
        level = carries
    return out


def _count_eq(bits: Sequence[jax.Array], n: int) -> jax.Array:
    """Plane that is all-ones where the bit-sliced count equals ``n``."""
    acc = None
    for k, b in enumerate(bits):
        term = b if (n >> k) & 1 else ~b
        acc = term if acc is None else acc & term
    return acc


def apply_rule_planes(alive: jax.Array, bits: Sequence[jax.Array], rule: Rule) -> jax.Array:
    """Next-generation plane from the alive plane + count bit-planes.

    Counts shared between the born and survive sets (3 for Conway) are
    materialized once — the equality planes are the second-largest op block
    after the adder network."""
    eq = {n: _count_eq(bits, n) for n in set(rule.born) | set(rule.survive)}
    zero = jnp.zeros_like(alive)
    born = zero
    for n in sorted(rule.born):
        born = born | eq[n]
    keep = zero
    for n in sorted(rule.survive):
        keep = keep | eq[n]
    return (alive & keep) | (~alive & born)


def _shift_west(p: jax.Array, left_word: jax.Array) -> jax.Array:
    """Plane of west neighbors: bit i <- bit i-1, borrowing bit 31 of the
    word to the left (``left_word``) at each word boundary."""
    return (p << 1) | (left_word >> _TOP_BIT)


def _shift_east(p: jax.Array, right_word: jax.Array) -> jax.Array:
    """Plane of east neighbors: bit i <- bit i+1, borrowing bit 0 of the
    word to the right."""
    return (p >> 1) | (right_word << _TOP_BIT)


def _row_triplet(p: jax.Array, topology: Topology) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(north, self, south) row-aligned views of the packed grid."""
    north = jnp.roll(p, 1, axis=0)
    south = jnp.roll(p, -1, axis=0)
    if topology is Topology.DEAD:
        zero_row = jnp.zeros_like(p[:1])
        north = jnp.concatenate([zero_row, p[:-1]], axis=0)
        south = jnp.concatenate([p[1:], zero_row], axis=0)
    return north, p, south


def horizontal_planes(slab: jax.Array, topology: Topology) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(west, center, east) planes along the packed LAST axis, with
    cross-word carries; word columns wrap for TORUS and see zeros for DEAD.
    Serves 2D (rows, words) slabs and the 1D family's (..., words) rows
    alike — the word axis is always last.

    DEAD is a roll + edge-column mask rather than a concatenate of
    unaligned slices: a lane-dimension concat has no Mosaic lowering
    ("result/input offset mismatch on non-concat dimension"), while roll
    (tpu.rotate) + iota select compiles in the Pallas kernel and fuses
    just as well under plain XLA.
    """
    axis = slab.ndim - 1
    left = jnp.roll(slab, 1, axis=axis)
    right = jnp.roll(slab, -1, axis=axis)
    if topology is not Topology.TORUS:
        cols = jax.lax.broadcasted_iota(jnp.int32, slab.shape, axis)
        left = jnp.where(cols == 0, jnp.uint32(0), left)
        right = jnp.where(cols == slab.shape[-1] - 1, jnp.uint32(0), right)
    return _shift_west(slab, left), slab, _shift_east(slab, right)


def neighbor_planes(p: jax.Array, topology: Topology) -> List[jax.Array]:
    """The 8 Moore-neighbor indicator planes of a packed grid.

    Kept as a reference formulation (tests cross-check the row-sum path
    against it); the steppers below use :func:`_row_sum_bits`, which
    reaches the same count planes with ~25% fewer VPU ops.
    """
    planes: List[jax.Array] = []
    for dv, slab in zip((-1, 0, 1), _row_triplet(p, topology)):
        w, c, e = horizontal_planes(slab, topology)
        planes.extend([w, e] if dv == 0 else [w, c, e])
    return planes


def _row_sum_bits(w, c, e, north_south, center_rows):
    """Neighbor-count bit-planes via shared per-row horizontal sums.

    Instead of feeding 8 shifted planes to a CSA network (each row's
    horizontal triple re-derived for all 3 vertical offsets), compute per
    row ONCE the 2-bit sums ``T = w + c + e`` (0..3, feeds the rows above
    and below) and ``S = w + e`` (0..2, the center row's own contribution),
    then add three 2-bit numbers: count = T_north + S + T_south. The 3x
    reuse of T is what the naive plane list cannot express and XLA's CSE
    does not recover across differently-shifted slices.

    ``north_south(plane) -> (north_view, south_view)`` supplies the
    vertical alignment (wrap/zero roll for whole grids, row slices for
    slabs); ``center_rows(plane)`` selects the center-row window of a
    full-height plane (identity for whole grids).
    """
    t0, t1 = _csa(w, c, e)               # T = w + c + e in 2 bits
    s0, s1 = w ^ e, w & e                # S = w + e
    tn0, ts0 = north_south(t0)
    tn1, ts1 = north_south(t1)
    s0, s1 = center_rows(s0), center_rows(s1)
    # T_n + S + T_s: three 2-bit numbers -> 4 LSB-first count planes (<= 8)
    r0, k1 = _csa(tn0, s0, ts0)
    s, k2 = _csa(tn1, s1, ts1)
    r1 = s ^ k1
    k2b = s & k1
    return [r0, r1, k2 ^ k2b, k2 & k2b]


def count_bits(p: jax.Array, topology: Topology) -> List[jax.Array]:
    """Moore-neighbor count of a packed plane as 4 LSB-first bit-planes
    (the row-sum fast path; also serves the Generations alive plane)."""
    def north_south(plane):
        n, _, s = _row_triplet(plane, topology)
        return n, s

    w, c, e = horizontal_planes(p, topology)
    return _row_sum_bits(w, c, e, north_south, lambda plane: plane)


def count_bits_ext(ext: jax.Array) -> Tuple[jax.Array, List[jax.Array]]:
    """(interior alive plane, count bit-planes) from a halo-extended
    (h+2, wp+2) plane — the sharded-tile face of :func:`count_bits`."""
    h = ext.shape[0] - 2
    mid = ext[:, 1:-1]
    w = _shift_west(mid, ext[:, :-2])
    e = _shift_east(mid, ext[:, 2:])
    bits = _row_sum_bits(
        w, mid, e,
        lambda plane: (plane[:h], plane[2:h + 2]),
        lambda plane: plane[1:h + 1])
    return mid[1:h + 1], bits


@optionally_donated("p")
def step_packed(p: jax.Array, *, rule: Rule, topology: Topology = Topology.TORUS) -> jax.Array:
    """One generation on a (H, W/32) uint32 packed grid."""
    return _step_whole(p, rule, topology)


def _step_whole(p: jax.Array, rule: Rule, topology: Topology) -> jax.Array:
    return apply_rule_planes(p, count_bits(p, topology), rule)


@optionally_donated("p")
def multi_step_packed(
    p: jax.Array,
    n: jax.Array,
    *,
    rule: Rule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations in one jitted fori_loop over the fused SWAR step."""
    def body(_, s):
        return _step_whole(s, rule, topology)
    return jax.lax.fori_loop(0, n, body, p)


def step_packed_slab(slab: jax.Array, rule: Rule, topology: Topology) -> jax.Array:
    """One generation for the interior rows of a (L, Wp) slab -> (L-2, Wp).

    Rows shrink (vertical halos consumed); columns use ``topology`` across
    the slab's own width: TORUS when the slab spans the full grid width
    (the Pallas kernel's blocks), DEAD when cells beyond the slab are
    unknown-and-treated-dead (the communication-avoiding sharded runner,
    whose 32-cell halo words absorb the resulting edge corruption).
    """
    h = slab.shape[0] - 2
    w, c, e = horizontal_planes(slab, topology)
    bits = _row_sum_bits(
        w, c, e,
        lambda plane: (jax.lax.slice_in_dim(plane, 0, h, axis=0),
                       jax.lax.slice_in_dim(plane, 2, h + 2, axis=0)),
        lambda plane: jax.lax.slice_in_dim(plane, 1, h + 1, axis=0))
    return apply_rule_planes(jax.lax.slice_in_dim(slab, 1, h + 1, axis=0),
                             bits, rule)


def neighbor_planes_ext(ext: jax.Array) -> Tuple[jax.Array, List[jax.Array]]:
    """(alive, 8 neighbor planes) from a halo-extended (h+2, wp+2) tile.

    The extended tile carries one halo row top/bottom and one halo *word*
    (32 columns) left/right — only 1 bit of each halo word is consumed, but
    shipping whole words keeps ppermute payloads aligned and the plane
    extraction uniform. No wraparound: all neighbors come from real slices.
    Reference formulation, like :func:`neighbor_planes`.
    """
    h = ext.shape[0] - 2
    planes: List[jax.Array] = []
    center = None
    for dv in (0, 1, 2):
        slab = ext[dv:dv + h, :]                       # (h, wp+2)
        left = slab[:, :-2]                            # word to the left
        mid = slab[:, 1:-1]
        right = slab[:, 2:]
        w = _shift_west(mid, left)
        e = _shift_east(mid, right)
        if dv == 1:
            center = mid
            planes.extend([w, e])
        else:
            planes.extend([w, mid, e])
    return center, planes


def step_packed_ext(ext: jax.Array, rule: Rule) -> jax.Array:
    """One generation on a halo-extended tile; returns the (h, wp) interior."""
    alive, bits = count_bits_ext(ext)
    return apply_rule_planes(alive, bits, rule)


# -- contract-gate registration (ops/_jit.py BUILDERS) -----------------------


@register_builder("ops.multi_step_packed", tags=("ops", "packed"))
def _contract_ops_multi_step_packed():
    import numpy as np

    from ..models.rules import CONWAY
    from . import bitpack

    rng = np.random.default_rng(7)
    p = bitpack.pack(jnp.asarray(
        rng.integers(0, 2, size=(64, 128), dtype=np.uint8)))
    return BuiltRunner(
        lowerable=multi_step_packed.jitted_donating,
        example_args=(p, 3), example_kwargs={"rule": CONWAY},
        donated_argnums=(0,), expected_collective_bytes=0,
        collective_model="single-device: zero collectives")
