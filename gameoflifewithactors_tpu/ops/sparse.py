"""Activity-tiled sparse stepping: compute ∝ active area, not grid area.

BASELINE.json config #5 is a Gosper gun in a 65536² field — ~10² live
tiles out of ~10⁵. A dense step pays the whole grid every generation; this
engine keeps a per-tile *changed-last-generation* flag and steps only tiles
whose 3×3 tile-neighborhood changed (GoL locality makes that exact: a cell
can only change if something within distance 1 changed, so a tile can only
change if it or a neighbor tile changed). Still lifes fall asleep; ships
wake tiles as they travel.

XLA-friendly by construction (SURVEY.md §8 stage 6: "per-tile activity
flags … rather than a true sparse format, which stays XLA-friendly"):

- state is the packed grid *with a one-word/one-row zero ring* (the DEAD
  boundary is the ring itself, so edge tiles need no special-casing);
- each generation gathers a **static capacity** of K candidate tiles with
  ``jnp.nonzero(..., size=K)`` (static shapes: no recompilation), steps
  them as a vmapped batch of (T+2-row, Tw+2-word) windows, and scatters
  the interiors back;
- if more than K tiles are active, the generation falls back to a full
  dense step under ``lax.cond`` — correctness never depends on K.

v1 is single-device and DEAD-topology (the zero ring *is* the boundary);
a torus needs ring maintenance and is left to the dense/sharded paths.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..models.rules import Rule
from .packed import step_packed_ext

DEFAULT_TILE_ROWS = 32
DEFAULT_TILE_WORDS = 4
DEFAULT_CAPACITY = 256


def _tile_grid_shape(H: int, Wp: int, tile_rows: int, tile_words: int) -> Tuple[int, int]:
    if H % tile_rows or Wp % tile_words:
        raise ValueError(
            f"packed grid ({H}, {Wp}) not divisible into ({tile_rows}, {tile_words}) tiles"
        )
    return H // tile_rows, Wp // tile_words


def initial_activity(padded: jax.Array, tile_rows: int, tile_words: int) -> jax.Array:
    """All tiles containing any live cell are initially 'changed'."""
    interior = padded[1:-1, 1:-1]
    H, Wp = interior.shape
    nty, ntx = _tile_grid_shape(H, Wp, tile_rows, tile_words)
    tiles = interior.reshape(nty, tile_rows, ntx, tile_words)
    return (tiles != 0).any(axis=(1, 3))


def _dilate(active: jax.Array) -> jax.Array:
    """3×3 tile-neighborhood OR — which tiles must be stepped."""
    a = active
    a = a | jnp.pad(active, ((1, 0), (0, 0)))[:-1, :] | jnp.pad(active, ((0, 1), (0, 0)))[1:, :]
    a = a | jnp.pad(a, ((0, 0), (1, 0)))[:, :-1] | jnp.pad(a, ((0, 0), (0, 1)))[:, 1:]
    return a


@lru_cache(maxsize=32)
def _build_sparse_step(
    rule: Rule,
    shape: Tuple[int, int],
    tile_rows: int,
    tile_words: int,
    capacity: int,
):
    """Jitted (padded, active, n) -> (padded, active) n-generation step.

    The generation loop is an on-device ``fori_loop`` and the state buffers
    are donated: per-call cost is one dispatch for any ``n``, and XLA can
    update the (potentially ~0.5 GB at 65536²) padded grid in place instead
    of materializing a copy per generation.
    """
    H, Wp = shape
    nty, ntx = _tile_grid_shape(H, Wp, tile_rows, tile_words)

    def gather_window(padded, ty, tx):
        # window = tile + 1 halo ring; padded grid offset makes this exact
        return jax.lax.dynamic_slice(
            padded, (ty * tile_rows, tx * tile_words),
            (tile_rows + 2, tile_words + 2),
        )

    def sparse_path(padded, candidates):
        idx = jnp.nonzero(candidates.ravel(), size=capacity, fill_value=0)[0]
        valid = jnp.arange(capacity) < jnp.sum(candidates)
        tys, txs = idx // ntx, idx % ntx
        windows = jax.vmap(lambda ty, tx: gather_window(padded, ty, tx))(tys, txs)
        stepped = jax.vmap(lambda w: step_packed_ext(w, rule))(windows)
        olds = windows[:, 1:-1, 1:-1]
        changed_any = jnp.logical_and((stepped != olds).any(axis=(1, 2)), valid)

        def scatter_one(k, carry):
            # invalid (fill) slots alias tile 0 and must not touch state —
            # writing where(valid, ...) would clobber a real tile's fresh
            # content with its gathered-old copy
            def do(carry):
                padded_c, active_c = carry
                ty, tx = tys[k], txs[k]
                padded_c = jax.lax.dynamic_update_slice(
                    padded_c, stepped[k], (ty * tile_rows + 1, tx * tile_words + 1)
                )
                return padded_c, active_c.at[ty, tx].set(changed_any[k])

            return jax.lax.cond(valid[k], do, lambda c: c, carry)

        active0 = jnp.zeros((nty, ntx), dtype=bool)
        padded, active = jax.lax.fori_loop(
            0, capacity, scatter_one, (padded, active0)
        )
        return padded, active

    def dense_path(padded, _candidates):
        old = padded[1:-1, 1:-1]
        # the zero ring is the DEAD boundary: step the interior against it
        new = step_packed_ext(padded, rule)
        padded = jax.lax.dynamic_update_slice(padded, new, (1, 1))
        tiles_old = old.reshape(nty, tile_rows, ntx, tile_words)
        tiles_new = new.reshape(nty, tile_rows, ntx, tile_words)
        return padded, (tiles_old != tiles_new).any(axis=(1, 3))

    def one_gen(padded, active):
        candidates = _dilate(active)
        n_cand = jnp.sum(candidates)
        return jax.lax.cond(
            n_cand <= capacity, sparse_path, dense_path, padded, candidates
        )

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(padded, active, n):
        return jax.lax.fori_loop(0, n, lambda _, c: one_gen(*c), (padded, active))

    return step


class SparseEngineState:
    """Host-side wrapper holding (padded grid, activity map)."""

    def __init__(
        self,
        packed: jax.Array,
        rule: Rule,
        *,
        tile_rows: int = DEFAULT_TILE_ROWS,
        tile_words: int = DEFAULT_TILE_WORDS,
        capacity: int = DEFAULT_CAPACITY,
    ):
        H, Wp = packed.shape
        _tile_grid_shape(H, Wp, tile_rows, tile_words)  # validate
        if 0 in rule.born:
            raise ValueError(
                f"sparse backend cannot run B0 rules ({rule.notation}): every "
                "quiescent region births cells each generation, so nothing "
                "ever sleeps — use the packed backend"
            )
        self.rule = rule
        self.tile_rows = tile_rows
        self.tile_words = tile_words
        self.capacity = capacity
        self.shape = (H, Wp)
        self.padded = jnp.pad(packed, 1)
        self.active = initial_activity(self.padded, tile_rows, tile_words)
        self._step = _build_sparse_step(
            rule, (H, Wp), tile_rows, tile_words, capacity
        )

    def step(self, n: int = 1) -> None:
        if n <= 0:
            return
        self.padded, self.active = self._step(self.padded, self.active, n)

    @property
    def packed(self) -> jax.Array:
        return self.padded[1:-1, 1:-1]

    def active_tiles(self) -> int:
        return int(jnp.sum(self.active))
