"""Activity-tiled sparse stepping: compute ∝ active area, not grid area.

BASELINE.json config #5 is a Gosper gun in a 65536² field — ~10² live
tiles out of ~10⁵. A dense step pays the whole grid every generation; this
engine keeps a per-tile *changed-last-generation* flag and steps only
tiles whose wake-neighborhood changed. Rule locality makes that exact: a
cell can only change if something within its rule's influence radius r
changed (r = 1 for the 3×3 families, rule.radius for LtL), so a tile can
only change if a tile within ceil(r / tile_extent) tile rings did
(_wake_dilation). Still lifes fall asleep; ships wake tiles as they
travel.

XLA-friendly by construction (SURVEY.md §8 stage 6: "per-tile activity
flags … rather than a true sparse format, which stays XLA-friendly"):

- state is the packed grid *with an (r-row, rw-word) zero ring* sized by
  the rule (_rule_halo; the DEAD boundary is the ring itself, so edge
  tiles need no special-casing);
- each generation gathers a **static capacity** of K candidate tiles with
  ``jnp.nonzero(..., size=K)`` (static shapes: no recompilation), steps
  them as a vmapped batch of (T+2r-row, Tw+2rw-word) windows, and
  scatters the interiors back;
- if more than K tiles are active, the on-device loop exits early and the
  host dispatches one full-grid dense generation, then resumes sparse —
  correctness never depends on K (see _build_sparse_step for why this
  beats the earlier per-generation ``lax.cond`` design).

Single-device, both topologies: for DEAD the zero ring *is* the boundary;
for TORUS the ring is refreshed with wrapped interior edges every
generation and the activity dilation wraps (seam-crossing ships work).
Serves life-like bitboards, Generations plane stacks, and radius-r LtL
in both neighborhoods (the bit-sliced packed window step). The sharded
form lives in parallel/sharded.py.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..models.rules import Rule
from ._jit import tracked_jit
from .packed import step_packed_ext
from .stencil import Topology


def _step_fns(rule, ndim: int):
    """The ``(ext_step, slab_step)`` pair for a rule/layout — every
    family's two stepper variants selected in ONE place, so the per-gen
    (:func:`_step_window`) and chunked (:func:`_step_window_chunk`) paths
    cannot drift as layouts are added. ``ext`` consumes fixed (r, rw)
    halos and returns the interior; ``slab`` shrinks 2r rows with
    vertical-DEAD closure (the chunk loop's building block)."""
    from ..models.ltl import LtLRule

    def stacked(fn, *extra):
        return lambda w: jnp.stack(fn(
            tuple(w[i] for i in range(w.shape[0])), rule, *extra))

    if isinstance(rule, LtLRule):
        if ndim == 3:
            from .packed_ltl import step_ltl_planes_ext, step_ltl_planes_slab

            return (stacked(step_ltl_planes_ext),
                    stacked(step_ltl_planes_slab, Topology.DEAD))
        from .packed_ltl import step_ltl_packed_ext, step_ltl_packed_slab

        return (lambda w: step_ltl_packed_ext(w, rule),
                lambda w: step_ltl_packed_slab(w, rule, Topology.DEAD))
    if ndim == 2:
        from .packed import step_packed_slab

        return (lambda w: step_packed_ext(w, rule),
                lambda w: step_packed_slab(w, rule, Topology.DEAD))
    from .packed_generations import step_planes_ext, step_planes_slab

    return (stacked(step_planes_ext),
            stacked(step_planes_slab, Topology.DEAD))


def _step_window(window, rule):
    """One generation of a halo-extended window in any layout: a
    (tr+2r, tw+2) packed bitboard (binary 3x3 or radius-r LtL), a
    (b, tr+2, tw+2) Generations bit-plane stack, or a (b, tr+2r, tw+2)
    multi-state LtL plane stack (leading plane axis)."""
    return _step_fns(rule, window.ndim)[0](window)


def _wake_dilation(rule, tile_rows: int, tile_words: int,
                   gens: int = 1) -> Tuple[int, int]:
    """Wake radius in TILE units, (dy, dx): a rule's influence travels r
    cells per generation, so over a ``gens``-generation chunk a tile must
    wake when anything within ceil(r·gens / tile_extent) tile rings
    changed. The ONE definition shared by the on-device candidate
    dilation and the host capacity estimator — they must agree or
    adaptive escalation can under-provision."""
    r, _ = _rule_halo(rule)
    from . import bitpack

    hr = r * gens
    return -(-hr // tile_rows), -(-hr // (tile_words * bitpack.WORD))


def max_chunk_gens(rule) -> int:
    """The deepest legal temporal chunk for a rule: g·r <= 32 keeps the
    horizontal DEAD-closure creep inside the single halo WORD (the
    communication-avoiding runner's bound); capped at 8 — beyond that the
    extra halo rows outgrow the scan win."""
    r, _ = _rule_halo(rule)
    return max(1, min(8, 32 // r))


def _step_window_chunk(window, rule, gens: int, exterior=None):
    """Advance a halo-extended window ``gens`` generations entirely
    locally: the (r·gens)-row vertical halos are consumed slab-style
    (2r rows per generation), and the horizontal DEAD-closure corruption
    creeps r cells/generation into the halo word, absorbed for
    r·gens <= 32 — the communication-avoiding trick applied per window.

    Input (lead, tr + 2·r·gens, tw + 2rw); returns ``(interior,
    changed)``: the exact (lead, tr, tw) tile interior after ``gens``
    generations, and a scalar bool that is True if the interior changed
    at ANY generation of the chunk — NOT merely between the endpoints.
    The distinction is soundness, not taste: a period-p oscillator with
    p | gens is endpoint-identical while emitting changing influence
    every generation, so endpoint comparison would put it (and then,
    wrongly, its neighbors) to sleep. The tile interior is exact at
    every intermediate step (the remaining slab always covers it, and
    horizontal creep stays inside the halo word), so the per-step
    comparison is exact too.

    ``exterior`` (global DEAD topology): ``(row0, col0, ring, H, rw,
    Wp)`` — the window's origin in padded coordinates plus the grid
    bounds. Window cells beyond the global grid are PERMANENTLY dead,
    but the free slab evolution would birth cells there from the ring
    zeros and feed them back into the interior from the 2nd in-slab
    generation on (the exact failure mode the band kernels'
    _zero_band_exterior guards), so they are re-zeroed before every
    generation. TORUS needs no mask — the ring holds real wrapped data
    whose free evolution is exact."""
    r, rw = _rule_halo(rule)
    hr = r * gens
    step1 = _step_fns(rule, window.ndim)[1]

    def interior(w, k):
        off = hr - k * r            # halo rows remaining per side
        return w[..., off:w.shape[-2] - off, rw:w.shape[-1] - rw]

    def zero_exterior(w, k):
        row0, col0, ring, H, rw, Wp = exterior
        rows = jax.lax.broadcasted_iota(jnp.int32, w.shape, w.ndim - 2)
        cols = jax.lax.broadcasted_iota(jnp.int32, w.shape, w.ndim - 1)
        # padded coordinates of slab cell (row, col) after k shrinks
        grow = row0 + k * r + rows
        gcol = col0 + cols
        ext = ((grow < ring) | (grow >= ring + H)
               | (gcol < rw) | (gcol >= rw + Wp))
        return jnp.where(ext, jnp.uint32(0), w)

    prev = interior(window, 0)
    changed = jnp.zeros((), dtype=bool)
    for k in range(1, gens + 1):
        if exterior is not None and k >= 2:
            # before the FIRST step the exterior is already zero (the
            # ring is never scattered into), so masking starts when the
            # free evolution could first have birthed exterior cells
            window = zero_exterior(window, k - 1)
        window = step1(window)
        cur = interior(window, k)
        changed = changed | (cur != prev).any()
        prev = cur
    return prev, changed


def _rule_halo(rule) -> Tuple[int, int]:
    """The zero-ring depth a rule's windowed step needs: (rows, words).
    3x3 families use (1, 1); radius-r LtL uses (r, 1) — its packed
    step reads r halo rows but only one 32-cell halo word (r <= 7)."""
    from ..models.ltl import LtLRule

    if isinstance(rule, LtLRule):
        return rule.radius, 1
    return 1, 1


def _births_from_nothing(rule) -> bool:
    """True when an all-dead neighborhood births a cell — the property
    that makes activity tiling unsound (nothing ever sleeps)."""
    from ..models.ltl import LtLRule

    if isinstance(rule, LtLRule):
        # interval list over the window count: births at count 0 mean an
        # all-dead region births
        return any(lo == 0 for lo, _ in rule.born_intervals)
    return 0 in rule.born


def _pad_ring(packed, r: int = 1, rw: int = 1):
    """Depth-(r rows, rw words) zero ring around the SPATIAL dims only."""
    return jnp.pad(packed, [(0, 0)] * (packed.ndim - 2) + [(r, r), (rw, rw)])

DEFAULT_TILE_ROWS = 32
DEFAULT_TILE_WORDS = 4
_MAX_ADAPTIVE_CAPACITY = 4096
MAX_MAP_ENTRIES = 65536


def auto_tile(H: int, Wp: int, max_map: int = MAX_MAP_ENTRIES) -> Tuple[int, int]:
    """Tile shape whose activity map stays <= ``max_map`` entries.

    Every generation scans the whole tile map (dilate + count + nonzero):
    with the default 32x4-word tiles a 65536² grid carries a 2^20-entry
    map, and that scan dominated the measured on-chip step (26 ms/gen —
    slower than the CPU run). Doubling rows/words alternately from the
    defaults until the map fits keeps small grids exactly on the defaults
    while capping the scan for huge ones (65536² -> 128x16-word tiles,
    a 2^16 map). Divisibility of the grid is preserved at every step.
    """
    tr, tw = min(DEFAULT_TILE_ROWS, H), min(DEFAULT_TILE_WORDS, Wp)
    while tr > 1 and H % tr:
        tr -= 1
    while tw > 1 and Wp % tw:
        tw -= 1
    grow_rows = True
    while (H // tr) * (Wp // tw) > max_map:
        if grow_rows and H % (2 * tr) == 0 and 2 * tr <= H:
            tr *= 2
        elif Wp % (2 * tw) == 0 and 2 * tw <= Wp:
            tw *= 2
        elif H % (2 * tr) == 0 and 2 * tr <= H:
            tr *= 2
        else:
            break  # no divisible doubling left; keep the best we found
        grow_rows = not grow_rows
    return tr, tw


def _tile_grid_shape(H: int, Wp: int, tile_rows: int, tile_words: int) -> Tuple[int, int]:
    if H % tile_rows or Wp % tile_words:
        raise ValueError(
            f"packed grid ({H}, {Wp}) not divisible into ({tile_rows}, {tile_words}) tiles"
        )
    return H // tile_rows, Wp // tile_words


def tile_activity(packed: jax.Array, tile_rows: int, tile_words: int) -> jax.Array:
    """Per-tile any-live map of an UNPADDED packed grid — no full-grid
    padded temporary (at 65536² that copy is ~512 MB)."""
    H, Wp = packed.shape[-2:]
    nty, ntx = _tile_grid_shape(H, Wp, tile_rows, tile_words)
    tiles = packed.reshape(*packed.shape[:-2], nty, tile_rows, ntx, tile_words)
    return (tiles != 0).any(axis=tuple(range(packed.ndim - 2)) + (-3, -1))


def initial_activity(padded: jax.Array, tile_rows: int, tile_words: int,
                     r: int = 1, rw: int = 1) -> jax.Array:
    """All tiles containing any live cell are initially 'changed'."""
    return tile_activity(padded[..., r:-r, rw:-rw], tile_rows, tile_words)


def _dilate(active: jax.Array, wrap: bool = False, dy: int = 1,
            dx: int = 1) -> jax.Array:
    """(2dy+1)×(2dx+1) tile-neighborhood OR — which tiles must be stepped.
    dy/dx > 1 serve radius-r rules whose influence can cross more than one
    tile boundary per generation (dy = ceil(r / tile_rows), etc.).

    ``wrap`` makes the neighborhood toroidal: an edge tile's change wakes
    the opposite-edge tile (a glider crossing the seam must find its
    destination awake)."""
    a = active
    for _ in range(dy):
        if wrap:
            a = a | jnp.roll(a, 1, 0) | jnp.roll(a, -1, 0)
        else:
            a = (a | jnp.pad(a, ((1, 0), (0, 0)))[:-1, :]
                 | jnp.pad(a, ((0, 1), (0, 0)))[1:, :])
    for _ in range(dx):
        if wrap:
            a = a | jnp.roll(a, 1, 1) | jnp.roll(a, -1, 1)
        else:
            a = (a | jnp.pad(a, ((0, 0), (1, 0)))[:, :-1]
                 | jnp.pad(a, ((0, 0), (0, 1)))[:, 1:])
    return a


def _refresh_ring(padded: jax.Array, r: int = 1, rw: int = 1) -> jax.Array:
    """Torus: the (r rows, rw words) ring holds wrapped copies of the
    opposite interior edges (incl. corners), refreshed every generation so
    edge tiles see current cross-seam neighbors. O(r·(H + Wp)) words per
    generation."""
    inter = padded[..., r:-r, rw:-rw]
    padded = padded.at[..., :r, rw:-rw].set(inter[..., -r:, :])
    padded = padded.at[..., -r:, rw:-rw].set(inter[..., :r, :])
    padded = padded.at[..., r:-r, :rw].set(inter[..., :, -rw:])
    padded = padded.at[..., r:-r, -rw:].set(inter[..., :, :rw])
    padded = padded.at[..., :r, :rw].set(inter[..., -r:, -rw:])
    padded = padded.at[..., :r, -rw:].set(inter[..., -r:, :rw])
    padded = padded.at[..., -r:, :rw].set(inter[..., :r, -rw:])
    padded = padded.at[..., -r:, -rw:].set(inter[..., :r, :rw])
    return padded


@lru_cache(maxsize=32)
def _build_sparse_step(
    rule: Rule,
    shape: Tuple[int, int],
    tile_rows: int,
    tile_words: int,
    capacity: int,
    topology: Topology = Topology.DEAD,
    gens: int = 1,
    ring_rows: int = 0,
):
    """Build the jitted ``sparse_many`` runner for this config.

    DEAD: the zero ring *is* the boundary. TORUS: the ring is refreshed
    with wrapped interior edges each chunk (same whole-word halo
    mechanism as the sharded path's ppermute strips) and tile-activity
    dilation wraps, so seam-crossing ships work.

    ``gens`` > 1 is temporal chunking: each gathered window carries
    (r·gens)-row halos and advances gens generations locally
    (:func:`_step_window_chunk`) before one scatter — amortizing the
    per-iteration activity-map scan (the measured dominant cost) by
    gens×. ``ring_rows`` is the padded buffer's ring depth (>= r·gens;
    the engine sizes it once for its chunk_gens, and the gens=1
    remainder program gathers offset inside the same buffer).

    SparseEngineState.step orchestrates this with the capacity-independent
    :func:`_build_dense_once` fallback. The common all-sparse case runs
    entirely on-device in a ``while_loop`` that early-exits when the
    candidate count exceeds ``capacity``. The loop body is scatter-only,
    so XLA updates the (~0.5 GB at 65536²) grid in place — the earlier
    design's ``lax.cond(sparse, dense)`` per generation blocked output
    aliasing and paid a full-buffer copy every generation (measured
    45 ms/gen vs 3 ms/gen at 32768² on CPU; VERDICT.md round-1 Weak #6).
    """
    lead, (H, Wp) = shape[:-2], shape[-2:]
    if len(lead) > 1:
        # the batched scatter below hardcodes ONE leading plane axis
        # (padded.at[:, rows, cols]); a deeper stack would silently apply
        # the spatial indices to the wrong axes
        raise ValueError(f"at most one leading plane axis, got shape {shape}")
    nty, ntx = _tile_grid_shape(H, Wp, tile_rows, tile_words)
    wrap = topology is Topology.TORUS
    r, rw = _rule_halo(rule)
    hr = r * gens                       # this program's window halo rows
    ring = ring_rows or hr              # the buffer's ring depth
    off = ring - hr                     # window gather offset inside it

    def gather_window(padded, ty, tx):
        # window = tile + (r·gens rows, rw words) of halo; the padded
        # ring's matching offset makes this exact (leading plane axes,
        # if any, are taken whole)
        return jax.lax.dynamic_slice(
            padded,
            (0,) * len(lead) + (off + ty * tile_rows, tx * tile_words),
            lead + (tile_rows + 2 * hr, tile_words + 2 * rw),
        )

    def sparse_gen(padded, candidates, n_cand):
        if wrap:
            padded = _refresh_ring(padded, ring, rw)
        idx = jnp.nonzero(candidates.ravel(), size=capacity, fill_value=0)[0]
        valid = jnp.arange(capacity) < n_cand
        tys, txs = idx // ntx, idx % ntx
        windows = jax.vmap(lambda ty, tx: gather_window(padded, ty, tx))(tys, txs)
        if wrap or gens == 1:
            # TORUS: the ring holds real wrapped data (free evolution is
            # exact); a single generation never evolves the zero ring
            step_one = lambda w, ty, tx: _step_window_chunk(w, rule, gens)
        else:
            # global DEAD: mask the window's beyond-the-grid cells dead
            # before every in-slab generation (see _step_window_chunk)
            step_one = lambda w, ty, tx: _step_window_chunk(
                w, rule, gens,
                exterior=(off + ty * tile_rows, tx * tile_words,
                          ring, H, rw, Wp))
        stepped, changed = jax.vmap(step_one)(windows, tys, txs)
        changed_any = jnp.logical_and(changed, valid)

        # ONE batched scatter for all tiles (vs. a capacity-long serial
        # chain of dynamic_update_slice). Invalid (fill) slots alias tile 0
        # and must not touch state: they are routed out of bounds and
        # dropped; the remaining indices are distinct tiles, so
        # unique_indices is safe.
        row0 = jnp.where(valid, tys * tile_rows + ring, H + 2 * ring)
        col0 = jnp.where(valid, txs * tile_words + rw, Wp + 2 * rw)
        rows = row0[:, None, None] + jnp.arange(tile_rows)[None, :, None]
        cols = col0[:, None, None] + jnp.arange(tile_words)[None, None, :]
        if lead:
            # (K, b, tr, tw) -> (b, K, tr, tw): the spatial scatter is the
            # same for every plane of the stack
            padded = padded.at[:, rows, cols].set(
                jnp.moveaxis(stepped, 1, 0), mode="drop", unique_indices=True)
        else:
            padded = padded.at[rows, cols].set(stepped, mode="drop",
                                               unique_indices=True)
        active = jnp.zeros((nty, ntx), dtype=bool)
        active = active.at[jnp.where(valid, tys, nty),
                           jnp.where(valid, txs, ntx)].set(
            changed_any, mode="drop", unique_indices=True)
        return padded, active

    # the engine owns both buffers (SparseEngineState allocates and
    # re-threads them every step), so always-on donation is safe here —
    # this is not a caller-facing functional entry point
    # goltpu: ignore[GOL003] -- internal runner over engine-owned buffers
    @partial(tracked_jit, runner="sparse_many", donate_argnums=(0, 1))
    def sparse_many(padded, active, n):
        """Run up to ``n`` CHUNKS (of ``gens`` generations) on-device;
        stop early at the first chunk whose candidate set exceeds
        capacity. Returns (padded, active, chunks_actually_done)."""

        dy, dx = _wake_dilation(rule, tile_rows, tile_words, gens)

        def carry_of(padded, active, i):
            cand = _dilate(active, wrap, dy=dy, dx=dx)
            return padded, active, cand, jnp.sum(cand), i

        def cond_fn(c):
            _, _, _, n_cand, i = c
            return (i < n) & (n_cand <= capacity)

        def body(c):
            padded, _, cand, n_cand, i = c
            padded, active = sparse_gen(padded, cand, n_cand)
            return carry_of(padded, active, i + 1)

        padded, active, _, _, done = jax.lax.while_loop(
            cond_fn, body, carry_of(padded, active, 0))
        return padded, active, done

    return sparse_many


@lru_cache(maxsize=32)
def _build_dense_once(
    rule: Rule,
    shape: Tuple[int, int],
    tile_rows: int,
    tile_words: int,
    topology: Topology = Topology.DEAD,
    ring_rows: int = 0,
):
    """One full-grid generation (the overflow fallback). Deliberately NOT
    keyed on capacity: an adaptive engine that escalates must not
    re-compile this O(grid) step per capacity level. ``ring_rows`` is the
    buffer's ring depth (>= the rule's r; the chunked engine sizes its
    ring for r·chunk_gens, and this per-generation step reads the inner
    (r, rw) sub-ring of it)."""
    lead, (H, Wp) = shape[:-2], shape[-2:]
    nty, ntx = _tile_grid_shape(H, Wp, tile_rows, tile_words)
    wrap = topology is Topology.TORUS
    r, rw = _rule_halo(rule)
    ring = ring_rows or r

    # goltpu: ignore[GOL003] -- internal runner over engine-owned buffers
    @partial(tracked_jit, runner="sparse_dense_once", donate_argnums=(0,))
    def dense_once(padded):
        if wrap:
            padded = _refresh_ring(padded, ring, rw)
        old = padded[..., ring:-ring, rw:-rw]
        # step the interior against the ring (zero = DEAD boundary;
        # wrapped copies = torus), reading the inner (r, rw) sub-ring
        sub = padded[..., ring - r:padded.shape[-2] - (ring - r), :]
        new = _step_window(sub, rule)
        tiles_old = old.reshape(*lead, nty, tile_rows, ntx, tile_words)
        tiles_new = new.reshape(*lead, nty, tile_rows, ntx, tile_words)
        changed = (tiles_old != tiles_new).any(
            axis=tuple(range(len(lead))) + (-3, -1))
        padded = jax.lax.dynamic_update_slice(
            padded, new, (0,) * len(lead) + (ring, rw))
        return padded, changed

    return dense_once


class SparseEngineState:
    """Host-side wrapper holding (padded grid, activity map)."""

    def __init__(
        self,
        packed: jax.Array,
        rule: Rule,
        *,
        tile_rows: int | None = None,
        tile_words: int | None = None,
        capacity: int | None = None,
        topology: Topology = Topology.DEAD,
        chunk_gens: int | None = None,
    ):
        H, Wp = packed.shape[-2:]
        if tile_rows is None and tile_words is None:
            tile_rows, tile_words = auto_tile(H, Wp)
        tile_rows = tile_rows or DEFAULT_TILE_ROWS
        tile_words = tile_words or DEFAULT_TILE_WORDS
        _tile_grid_shape(H, Wp, tile_rows, tile_words)  # validate
        r0, _ = _rule_halo(rule)
        if chunk_gens is None:
            # Temporal chunking (windows carry (r·g)-row halos and advance
            # g generations per gather, amortizing the activity-map scan
            # g-fold) DEFAULTS OFF: the scan dominates a per-generation
            # step (measured ~100% of a 32768² CPU generation), but under
            # XLA's CPU lowering the unrolled shrinking-slab window chain
            # loses more than the scan win — the persisted config-#5-shape
            # A/B (results/config5_sparse_8192_cpu_chunk_ab.json) measured
            # g=8 at ~640 gens/s vs ~4790 unchunked (~7.5x slower) at 8192²,
            # the same non-fusion that makes the communication-avoiding
            # sharded runner CPU-slow. Built for the TPU, where the scan
            # was the measured 26 ms/gen bottleneck of config #5
            # (pre-auto-tiling); scripts/config5_sparse.py --chunk-gens
            # A/Bs it on chip before any default flips.
            chunk_gens = 1
        if chunk_gens < 1 or chunk_gens * r0 > 32:
            raise ValueError(
                f"chunk_gens must satisfy 1 <= g and g*radius <= 32 (the "
                f"halo word bounds horizontal creep), got g={chunk_gens} "
                f"for radius {r0}")
        if chunk_gens * r0 > H:
            raise ValueError(
                f"chunk_gens={chunk_gens} needs a ring of {chunk_gens * r0} "
                f"rows > the grid's {H}; use a smaller chunk")
        self.chunk_gens = chunk_gens
        # capacity policy: an explicit value is FIXED (overflow -> one dense
        # full-grid generation, as documented); None is adaptive — start
        # near the seeded activity and double on overflow (each escalation
        # is one extra compile, bounded by _MAX_ADAPTIVE_CAPACITY), so a
        # mostly-sleeping universe never pays a 256-tile window batch per
        # generation for 6 active tiles.
        self._adaptive = capacity is None
        from ..models.ltl import LtLRule

        if isinstance(rule, LtLRule) and rule.states != 2 and packed.ndim != 3:
            # C >= 3 LtL sparse runs on the (b, H, Wp) plane stack
            # (pack_generations_for with this rule); a 2D bitboard cannot
            # carry the decay states
            raise ValueError(
                f"sparse multi-state LtL ({rule.notation}, "
                f"{rule.states} states) takes a (b, H, W/32) bit-plane "
                "stack, not a 2D bitboard — pack with "
                "ops.packed_generations.pack_generations_for")
        if _births_from_nothing(rule):
            raise ValueError(
                f"sparse backend cannot run birth-from-nothing rules "
                f"({rule.notation}): every quiescent region births cells "
                "each generation, so nothing ever sleeps — use the packed "
                "backend"
            )
        self.rule = rule
        self.tile_rows = tile_rows
        self.tile_words = tile_words
        self.topology = topology
        self.shape = tuple(packed.shape)
        r, rw = _rule_halo(rule)
        self._halo = (r * chunk_gens, rw)   # (rows, words) ring depth
        ring, _ = self._halo
        self.padded = _pad_ring(packed, ring, rw)
        self.active = initial_activity(self.padded, tile_rows, tile_words,
                                       ring, rw)
        nty, ntx = _tile_grid_shape(H, Wp, tile_rows, tile_words)
        self._cap_ceiling = min(_MAX_ADAPTIVE_CAPACITY,
                                1 << (nty * ntx - 1).bit_length())
        if self._adaptive:
            # one dilation factor's worth of headroom over the seeded tiles
            # covers the first chunk ((2dy+1)(2dx+1) = 9 for unchunked 3x3
            # rules, more when r·chunk_gens crosses several tile rings);
            # pow2 keeps the lru-cached compile set small across
            # escalations; never batch more windows than tiles exist
            # (dense seeds would otherwise pay full compute on fill slots
            # forever)
            dy, dx = _wake_dilation(rule, tile_rows, tile_words, chunk_gens)
            factor = (2 * dy + 1) * (2 * dx + 1)
            want = max(32, factor * int(jnp.sum(self.active)))
            capacity = min(1 << (want - 1).bit_length(), self._cap_ceiling)
        self._set_capacity(capacity)

    def _set_capacity(self, capacity: int) -> None:
        self.capacity = capacity
        ring, _ = self._halo
        self._sparse_many = _build_sparse_step(
            self.rule, self.shape, self.tile_rows, self.tile_words,
            capacity, self.topology, gens=self.chunk_gens, ring_rows=ring
        )
        # the n % chunk_gens remainder program (same buffer, 1-gen windows)
        # is built lazily on first remainder use: a capacity escalation
        # triggered by the bulk program would otherwise pay a second
        # first-touch compile the run may never need (ADVICE r4)
        self._sparse_many_1_built = None
        self._dense_once = _build_dense_once(
            self.rule, self.shape, self.tile_rows, self.tile_words,
            self.topology, ring_rows=ring
        )

    @property
    def _sparse_many_1(self):
        if self.chunk_gens == 1:
            return self._sparse_many
        if self._sparse_many_1_built is None:
            ring, _ = self._halo
            self._sparse_many_1_built = _build_sparse_step(
                self.rule, self.shape, self.tile_rows, self.tile_words,
                self.capacity, self.topology, gens=1, ring_rows=ring)
        return self._sparse_many_1_built

    def step(self, n: int = 1) -> None:
        """Advance ``n`` generations: the on-device while_loop runs sparse
        CHUNKS (chunk_gens generations per gathered window; the n %
        chunk_gens remainder takes the 1-generation program over the same
        ring buffer) until done or a capacity overflow. Adaptive capacity
        (the default) handles overflow by doubling and retrying — the
        universe state is untouched (the loop's guard runs before the
        over-capacity chunk), so escalation costs one recompile, not a
        correctness risk; at _MAX_ADAPTIVE_CAPACITY, and always for an
        explicit fixed capacity, overflow falls back to one dense
        full-grid generation and resumes. The host reads one scalar
        (chunks completed) per dispatch — the price of keeping the common
        path copy-free; all-sparse runs cost at most two dispatches (bulk
        + remainder) + scalar fetches regardless of ``n``."""
        g = self.chunk_gens
        remaining = int(n)
        while remaining > 0:
            chunks = remaining // g
            if chunks:
                self.padded, self.active, done = self._sparse_many(
                    self.padded, self.active, chunks)
                remaining -= int(done) * g
                if int(done) == chunks:
                    continue            # bulk complete; loop for remainder
            else:
                self.padded, self.active, done = self._sparse_many_1(
                    self.padded, self.active, remaining)
                remaining -= int(done)
                if remaining == 0:
                    return
            # overflow: the next chunk/generation exceeds capacity
            if self._adaptive and self.capacity < self._cap_ceiling:
                # one cheap map reduction tells us the needed capacity:
                # jump straight there (one recompile) instead of
                # doubling through several zero-progress dispatches
                dy, dx = _wake_dilation(self.rule, self.tile_rows,
                                        self.tile_words,
                                        g if remaining >= g else 1)
                need = int(jnp.sum(_dilate(
                    self.active, self.topology is Topology.TORUS,
                    dy=dy, dx=dx)))
                want = max(2 * self.capacity, need)
                self._set_capacity(
                    min(1 << (want - 1).bit_length(), self._cap_ceiling))
                continue
            self.padded, self.active = self._dense_once(self.padded)
            remaining -= 1

    def reseed(self, packed: jax.Array) -> "SparseEngineState":
        """A fresh state over ``packed`` with this state's configuration,
        including whether capacity is adaptive — callers never need to
        reconstruct the policy themselves (Engine.set_grid uses this)."""
        return SparseEngineState(
            packed, self.rule,
            tile_rows=self.tile_rows, tile_words=self.tile_words,
            capacity=None if self._adaptive else self.capacity,
            topology=self.topology,
            chunk_gens=self.chunk_gens,
        )

    @property
    def packed(self) -> jax.Array:
        r, rw = self._halo
        return self.padded[..., r:-r, rw:-rw]

    def active_tiles(self) -> int:
        return int(jnp.sum(self.active))


# -- the paged-memory face -----------------------------------------------------
#
# memory/ (the paged tile-pool subsystem) drives page activation and
# retirement with the same changed-last-generation machinery this module
# uses for tile wake tracking. These public aliases plus the host-side
# coordinate dilation are that shared face: ONE definition of a rule's
# halo depth, its packed layout, and "how far can influence travel per
# chunk" for both consumers — the activity-map engine here and the
# page-table allocator there cannot drift on soundness-critical radii.

rule_halo = _rule_halo
wake_dilation = _wake_dilation
births_from_nothing = _births_from_nothing


def rule_layout(rule) -> Tuple[int, int]:
    """``(planes, window_ndim)`` of a rule's packed layout: binary
    life-like families and 2-state LtL run 2D bitboards ``(1, 2)``;
    Generations and C >= 3 LtL run ``(b, H, W/32)`` bit-plane stacks
    ``(n_planes(states), 3)``. The paged tile pool sizes its slab's
    leading plane axis from this, and the paged runner picks the matching
    :func:`_step_fns` variant — the same selection the sparse window
    steppers make from their operand's ndim."""
    from ..models.ltl import LtLRule
    from .packed_generations import n_planes

    if isinstance(rule, LtLRule):
        if rule.states == 2:
            return 1, 2
        return n_planes(rule.states), 3
    if isinstance(rule, Rule):
        return 1, 2
    return n_planes(rule.states), 3  # GenRule plane stack


def dilate_coords(coords, dy: int = 1, dx: int = 1, *, bounds=None,
                  wrap: bool = False):
    """Host-side tile-coordinate dilation: every (ty, tx) within a
    (2dy+1) x (2dx+1) tile neighborhood of the input set — exactly
    :func:`_dilate` lifted from a dense activity map to a sparse
    coordinate set, which is the form the paged page table needs (an
    unbounded universe has no dense map to dilate). ``bounds`` =
    (nty, ntx) clips out-of-range coords (the DEAD closure) or wraps
    them when ``wrap`` is set (TORUS: an edge page's change wakes the
    opposite-edge page); ``bounds=None`` is the unbounded plane, where
    every neighbor coordinate exists."""
    out = set()
    for ty, tx in coords:
        for oy in range(-dy, dy + 1):
            for ox in range(-dx, dx + 1):
                y, x = ty + oy, tx + ox
                if bounds is not None:
                    nty, ntx = bounds
                    if wrap:
                        y, x = y % nty, x % ntx
                    elif not (0 <= y < nty and 0 <= x < ntx):
                        continue
                out.add((y, x))
    return out
