"""Bit-packed Larger-than-Life: bit-sliced window sums, 32 cells per word.

The dense LtL path (ops/ltl.py) moves one int32 per cell through its
log-tree window sums; here the grid stays a packed binary bitboard and
the counts live in *bit-sliced* form — q uint32 planes holding bit q of
every cell's count — so one bitwise op advances 32 cells:

- the vertical (2r+1)-row window reuses the carry-save adder network of
  the 3x3 SWAR path (ops/packed.bit_sliced_sum) over row-shifted planes;
- the horizontal window is the same doubling tree ops/ltl.py uses, but
  each "add" is a plane-wise ripple adder over bit-sliced numbers and
  each "shift" is a cell shift with cross-word bit carries;
- von Neumann (diamond) neighborhoods are not (x, y)-separable but ARE
  per-row separable: r+1 shrinking sliding sums over pre-added ±d row
  pairs (diamond_counts_packed) — ~r× the box work, same bit-level
  vocabulary;
- the B/S interval tests are bit-sliced subtract-borrow comparators
  against the constant bounds.

Counts reach (2r+1)^2 <= 225 for r <= 7, so numbers stay within 8
planes. Cell shifts honor the topology exactly like the dense pad:
TORUS wraps (word rolls + bit carries), DEAD shifts in zeros.

Shards too: parallel/sharded.make_multi_step_ltl_packed exchanges r halo
rows plus one halo word per generation and steps via
:func:`step_ltl_packed_ext`. Bit-identity with ops/ltl.py is enforced in
tests/test_packed_ltl.py.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp

from ..models.ltl import LtLRule
from ._jit import optionally_donated
from .packed import bit_sliced_sum
from .stencil import Topology

_WORD = 32


def _zero_cols(p: jax.Array, n: int, side: str) -> jax.Array:
    """Zero the first/last ``n`` whole word-columns (DEAD shift fill)."""
    cols = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    mask = cols < n if side == "lo" else cols >= p.shape[1] - n
    return jnp.where(mask, jnp.uint32(0), p)


def _zero_rows(p: jax.Array, n: int, side: str) -> jax.Array:
    rows = jax.lax.broadcasted_iota(jnp.int32, p.shape, 0)
    mask = rows < n if side == "lo" else rows >= p.shape[0] - n
    return jnp.where(mask, jnp.uint32(0), p)


def vshift(p: jax.Array, d: int, topology: Topology) -> jax.Array:
    """Plane whose row r holds the cells of row r - d (d may be negative)."""
    if d == 0:
        return p
    out = jnp.roll(p, d, axis=0)
    if topology is not Topology.TORUS:
        out = _zero_rows(out, abs(d), "lo" if d > 0 else "hi")
    return out


def hshift_west(p: jax.Array, d: int, topology: Topology) -> jax.Array:
    """Plane whose column c holds the cell at column c - d (d >= 0): the
    value ``d`` cells to the west, with cross-word bit carries."""
    q, s = divmod(d, _WORD)
    if q:
        p = jnp.roll(p, q, axis=1)
        if topology is not Topology.TORUS:
            p = _zero_cols(p, q, "lo")
    if s:
        left = jnp.roll(p, 1, axis=1)
        if topology is not Topology.TORUS:
            left = _zero_cols(left, 1, "lo")
        p = (p << s) | (left >> (_WORD - s))
    return p


def hshift_east(p: jax.Array, d: int, topology: Topology) -> jax.Array:
    """Plane whose column c holds the cell at column c + d (d >= 0)."""
    q, s = divmod(d, _WORD)
    if q:
        p = jnp.roll(p, -q, axis=1)
        if topology is not Topology.TORUS:
            p = _zero_cols(p, q, "hi")
    if s:
        right = jnp.roll(p, -1, axis=1)
        if topology is not Topology.TORUS:
            right = _zero_cols(right, 1, "hi")
        p = (p >> s) | (right << (_WORD - s))
    return p


def bs_add(a: Sequence[jax.Array], b: Sequence[jax.Array]) -> List[jax.Array]:
    """Ripple add of two bit-sliced numbers (lists of planes, LSB first)."""
    zero = jnp.zeros_like(a[0] if len(a) else b[0])
    n = max(len(a), len(b))
    out: List[jax.Array] = []
    carry = zero
    for i in range(n):
        x = a[i] if i < len(a) else zero
        y = b[i] if i < len(b) else zero
        s = x ^ y
        out.append(s ^ carry)
        carry = (x & y) | (s & carry)
    out.append(carry)
    return out


def bs_sub_bit(a: Sequence[jax.Array], bit: jax.Array) -> List[jax.Array]:
    """a - bit for a one-plane subtrahend; caller guarantees no underflow."""
    out = [a[0] ^ bit]
    borrow = ~a[0] & bit
    for i in range(1, len(a)):
        out.append(a[i] ^ borrow)
        borrow = ~a[i] & borrow
    return out


def bs_ge(a: Sequence[jax.Array], c: int) -> jax.Array:
    """Plane set where the bit-sliced number a >= the Python constant c."""
    if c <= 0:
        return ~jnp.zeros_like(a[0])
    if c >= (1 << len(a)):
        return jnp.zeros_like(a[0])
    borrow = jnp.zeros_like(a[0])
    for i, p in enumerate(a):  # compute a - c; a >= c iff no final borrow
        if (c >> i) & 1:
            borrow = ~p | borrow
        else:
            borrow = ~p & borrow
    return ~borrow


def _one_sided_sum_bs(num: List[jax.Array], r: int, topology: Topology,
                      shift) -> List[jax.Array]:
    """sum_{d=1..r} shift(num, d): a doubling tree that only ever shifts in
    ONE direction. That one-sidedness is what makes DEAD topology exact:
    zero-fill from a shift then always coincides with a genuinely
    beyond-edge (all-dead) contribution. (A centered tree that pre-shifts
    east and recenters west drops real west-edge data first and back-fills
    zeros — the bug this replaced.)"""
    pows = {1: [shift(p, 1, topology) for p in num]}
    m = 1
    while 2 * m <= r:
        cur = pows[m]
        pows[2 * m] = bs_add(cur, [shift(p, m, topology) for p in cur])
        m *= 2
    acc = None
    offset = 0
    for p2 in sorted(pows, reverse=True):  # greedy binary decomposition of r
        while r - offset >= p2:
            piece = ([shift(pl, offset, topology) for pl in pows[p2]]
                     if offset else pows[p2])
            acc = piece if acc is None else bs_add(acc, piece)
            offset += p2
    return acc


def _sliding_sum_bs(num: List[jax.Array], k: int, topology: Topology) -> List[jax.Array]:
    """Width-``k`` horizontal sliding sum of a bit-sliced number, centered:
    output(c) = sum_{d=-r..r} num(c+d) for k = 2r+1."""
    r = (k - 1) // 2
    if r == 0:
        return list(num)
    west = _one_sided_sum_bs(num, r, topology, hshift_west)
    east = _one_sided_sum_bs(num, r, topology, hshift_east)
    return bs_add(bs_add(west, east), num)


def box_counts_packed(p: jax.Array, radius: int, topology: Topology,
                      h_topo: Topology | None = None) -> List[jax.Array]:
    """Bit-sliced (2r+1)^2 box sums (center included) of a packed plane.
    ``h_topo`` splits the horizontal closure off the vertical one (the
    slab form passes vertical DEAD + global horizontal); default equal."""
    k = 2 * radius + 1
    col = bit_sliced_sum([vshift(p, d, topology) for d in range(-radius, radius + 1)])
    return _sliding_sum_bs(col, k, topology if h_topo is None else h_topo)


def diamond_counts_packed(p: jax.Array, radius: int, v_topo: Topology,
                          h_topo: Topology) -> List[jax.Array]:
    """Bit-sliced von Neumann (diamond) sums: |dx| + |dy| <= radius.

    The diamond is not (x, y)-separable like the box, but it IS per-row
    separable: the rows at vertical offsets ±d contribute a centered
    horizontal window of width 2·(radius-d)+1, so the whole sum is r+1
    shrinking sliding sums (the ±d row pair is pre-added into one 2-plane
    number so each width is swept once) accumulated with bit-sliced adds —
    ~r× the box path's work, the price of non-separability, still 32
    cells per bitwise op. Split topologies serve the slab form (vertical
    DEAD on the slab, global horizontal closure)."""
    # counts never exceed the diamond's cell count, so planes past its
    # bit length are identically zero — truncating after every add keeps
    # the comparators and the pallas VMEM working set at ~log2(cells)
    # planes instead of growing a carry plane per accumulation
    nbits = (2 * radius * radius + 2 * radius + 1).bit_length()
    acc = None
    for d in range(radius + 1):
        if d == 0:
            planes: List[jax.Array] = [p]
        else:
            planes = bit_sliced_sum([vshift(p, -d, v_topo),
                                     vshift(p, d, v_topo)])
        term = _sliding_sum_bs(planes, 2 * (radius - d) + 1, h_topo)
        acc = term if acc is None else bs_add(acc, term)[:nbits]
    return acc


def neighborhood_counts_packed(p: jax.Array, rule: LtLRule, v_topo: Topology,
                               h_topo: Topology) -> List[jax.Array]:
    """The rule's neighborhood sum in bit-sliced form, with independent
    vertical/horizontal closures (equal for full grids; the slab form
    passes vertical DEAD + global horizontal)."""
    if rule.neighborhood == "M":
        return box_counts_packed(p, rule.radius, v_topo, h_topo)
    return diamond_counts_packed(p, rule.radius, v_topo, h_topo)


def _apply_intervals(p: jax.Array, counts: List[jax.Array], rule: LtLRule) -> jax.Array:
    """Next-generation plane from the alive plane + bit-sliced window
    counts; born/survive may be HROT interval lists (OR-fold of the
    bit-sliced comparator pairs)."""
    if not rule.middle:
        counts = bs_sub_bit(counts, p)  # window sum >= p, no underflow

    def in_any(intervals):
        hit = None
        for lo, hi in intervals:
            t = bs_ge(counts, lo) & ~bs_ge(counts, hi + 1)
            hit = t if hit is None else (hit | t)
        # an empty interval list (Golly allows e.g. empty survival) = never
        return jnp.zeros_like(p) if hit is None else hit

    born = ~p & in_any(rule.born_intervals)
    keep = p & in_any(rule.survive_intervals)
    return born | keep


def _require_binary(rule: LtLRule) -> None:
    """The packed layout is one bit per cell: multi-state (C >= 3) LtL
    needs the byte path (ops/ltl.py dense step handles the decay)."""
    if rule.states != 2:
        raise ValueError(
            f"the packed LtL path is binary (1 bit/cell); {rule.notation} "
            f"has {rule.states} states — use backend='dense'")


def step_ltl_packed(p: jax.Array, rule: LtLRule, topology: Topology) -> jax.Array:
    """One generation on a (H, W/32) packed binary grid (box or diamond)."""
    _require_binary(rule)
    return _apply_intervals(
        p, neighborhood_counts_packed(p, rule, topology, topology), rule)


def step_ltl_packed_slab(slab: jax.Array, rule: LtLRule,
                         topology: Topology) -> jax.Array:
    """(L, Wp) full-width slab -> (L - 2r, Wp): one generation with
    vertical DEAD closure (the outer r rows are halo, consumed and
    cropped — the radius-r face of packed.step_packed_slab) and GLOBAL
    horizontal closure ``topology`` (slab rows span the full grid width,
    so the horizontal wrap is globally correct). The per-axis closure
    split is exact for both neighborhoods: every vertical shift uses DEAD
    on the slab, every horizontal sliding sum the global topology."""
    _require_binary(rule)
    r = rule.radius
    counts = neighborhood_counts_packed(slab, rule, Topology.DEAD, topology)
    return _apply_intervals(slab[r:-r], [c[r:-r] for c in counts], rule)


def step_ltl_packed_ext(ext: jax.Array, rule: LtLRule) -> jax.Array:
    """One generation from a halo-extended packed tile -> (h, wp) interior.

    ``ext`` is (h + 2r, wp + 2): r halo *rows* top/bottom and one halo
    *word* (32 >= r cells) left/right, materialised by the caller (the
    sharded runner's ppermute exchange). Counts are computed with DEAD
    closure on the slab — every interior cell's neighborhood (box or
    diamond) lies inside the ext, so the closure never touches a real
    contribution."""
    _require_binary(rule)
    r = rule.radius
    counts = [c[r:-r, 1:-1] for c in neighborhood_counts_packed(
        ext, rule, Topology.DEAD, Topology.DEAD)]
    return _apply_intervals(ext[r:-r, 1:-1], counts, rule)


@optionally_donated("p")
def multi_step_ltl_packed(
    p: jax.Array,
    n: jax.Array,
    *,
    rule: LtLRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations on a packed grid in one jitted fori_loop."""
    body = lambda _, s: step_ltl_packed(s, rule, topology)
    return jax.lax.fori_loop(0, n, body, p)


# ---------------------------------------------------------------------------
# multi-state (C >= 3) LtL on a bit-plane stack: the Generations decay
# state machine (ops/packed_generations.transition_planes) driven by the
# radius-r bit-sliced window counts of the ALIVE plane — the dense byte
# path (ops/ltl.py step_ltl_ext multistate branch) bit-sliced, ~b/1 bytes
# per cell instead of 1, every op 32 cells wide on the VPU.
# ---------------------------------------------------------------------------


def _interval_masks(alive, counts, rule: LtLRule):
    """Raw (born_p, keep_p) predicate planes over the bit-sliced window
    counts — the interval-comparator face of packed_generations'
    count-equality masks; masking to dead/alive cells happens inside
    transition_planes."""
    if not rule.middle:
        counts = bs_sub_bit(counts, alive)

    def in_any(intervals):
        hit = None
        for lo, hi in intervals:
            t = bs_ge(counts, lo) & ~bs_ge(counts, hi + 1)
            hit = t if hit is None else (hit | t)
        return jnp.zeros_like(alive) if hit is None else hit

    return in_any(rule.born_intervals), in_any(rule.survive_intervals)


def _require_multistate(rule: LtLRule) -> None:
    if rule.states < 3:
        raise ValueError(
            f"the plane-stack LtL path serves C >= 3 decay rules; "
            f"{rule.notation} is binary — use the 1-bit packed path "
            "(step_ltl_packed)")


def step_ltl_planes(plist, rule: LtLRule, topology: Topology):
    """One generation on a tuple of b (H, W/32) state planes (the
    Generations plane encoding, ops/packed_generations.pack_generations_for
    with this rule): only state 1 excites, so the window counts run over
    the alive plane; decay rides transition_planes."""
    from .packed_generations import _alive_of, transition_planes

    _require_multistate(rule)
    alive = _alive_of(plist)
    counts = neighborhood_counts_packed(alive, rule, topology, topology)
    born_p, keep_p = _interval_masks(alive, counts, rule)
    return transition_planes(plist, alive, born_p, keep_p, rule.states)


def step_ltl_planes_slab(plist, rule: LtLRule, topology: Topology):
    """b (L, Wp) planes -> b (L - 2r, Wp): one generation of a multi-state
    slab (vertical DEAD closure consuming r halo rows per side, horizontal
    closure ``topology`` across the slab's own width) — the radius-r
    plane-stack face of packed.step_packed_slab, serving the chunked
    sparse windows (ops/sparse.py) like its binary twin."""
    from .packed_generations import _alive_of, transition_planes

    _require_multistate(rule)
    r = rule.radius
    alive = _alive_of(plist)
    counts = [c[r:-r] for c in neighborhood_counts_packed(
        alive, rule, Topology.DEAD, topology)]
    interior = tuple(p[r:-r] for p in plist)
    born_p, keep_p = _interval_masks(alive[r:-r], counts, rule)
    return transition_planes(interior, alive[r:-r], born_p, keep_p,
                             rule.states)


def step_ltl_planes_ext(ext_list, rule: LtLRule):
    """One generation from b halo-extended (h + 2r, wp + 2) planes ->
    interior (h, wp) plane tuple — r halo rows and one halo word per side
    (32 >= r cells), same contract as :func:`step_ltl_packed_ext`; halos
    come from the caller (sharded ppermute, or the sparse window gather)."""
    from .packed_generations import _alive_of, transition_planes

    _require_multistate(rule)
    r = rule.radius
    alive_ext = _alive_of(ext_list)
    counts = [c[r:-r, 1:-1] for c in neighborhood_counts_packed(
        alive_ext, rule, Topology.DEAD, Topology.DEAD)]
    interior = tuple(p[r:-r, 1:-1] for p in ext_list)
    born_p, keep_p = _interval_masks(alive_ext[r:-r, 1:-1], counts, rule)
    return transition_planes(interior, alive_ext[r:-r, 1:-1], born_p,
                             keep_p, rule.states)


@optionally_donated("planes")
def multi_step_ltl_planes(
    planes: jax.Array,
    n: jax.Array,
    *,
    rule: LtLRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations on a (b, H, W/32) plane stack in one fori_loop."""
    b = planes.shape[0]
    body = lambda _, s: step_ltl_planes(s, rule, topology)
    out = jax.lax.fori_loop(0, n, body, tuple(planes[i] for i in range(b)))
    return jnp.stack(out)
