"""Bit-plane packed stepper for the Generations (multi-state) family.

The dense Generations path (ops/generations.py) pays one byte per cell of
HBM traffic; this path stores the state number in ``b = ceil(log2(C))``
bit-planes packed 32 cells/word — Brian's Brain (C=3) moves 4x fewer
bytes per generation and every operation is 32-cell-wide uint32 bitwise
arithmetic on the VPU, exactly like the binary SWAR path it reuses:

- the *alive* plane (state == 1: low bit set, all higher bits clear) runs
  through the same neighbor-plane extraction + carry-save adder network
  as ops/packed.py (only state 1 excites neighbors);
- birth/survival masks come from the same count bit-plane equality nets;
- dying cells age by a plane-wise increment (half-adder carry chain, +1
  per generation) with an equality net zeroing cells that reach C — the
  ``(state + 1) % C`` of the dense path, bit-sliced.

Shards too: parallel/sharded.make_multi_step_generations_packed moves the
whole (b, h, wp) stack through ONE four-send halo trip per generation
(halo.exchange_halo_stack) and steps via :func:`step_planes_ext`.
Bit-identity with the dense stepper is enforced in
tests/test_packed_generations.py.
"""

from __future__ import annotations

from functools import reduce
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generations import GenRule
from . import bitpack
from ._jit import optionally_donated
from .packed import _count_eq, count_bits, count_bits_ext
from .stencil import Topology


def n_planes(states: int) -> int:
    """Bit-planes needed to store states 0..C-1."""
    return max(1, (states - 1).bit_length())


def pack_generations_for(grid: jax.Array, rule: GenRule) -> jax.Array:
    """(H, W) uint8 state grid -> (b, H, W/32) uint32 bit-plane stack.

    The plane count comes from the rule (b = n_planes(rule.states)), not
    from the values present, so the stack shape is static per rule.
    """
    grid = jnp.asarray(grid, dtype=jnp.uint8)
    b = n_planes(rule.states)
    planes = [bitpack.pack((grid >> i) & 1) for i in range(b)]
    return jnp.stack(planes)


def unpack_generations(planes: jax.Array) -> jax.Array:
    """(b, H, W/32) bit-plane stack -> (H, W) uint8 state grid."""
    b = planes.shape[0]
    out = None
    for i in range(b):
        part = bitpack.unpack(planes[i]).astype(jnp.uint8) << i
        out = part if out is None else out | part
    return out


def unpack_generations_np(planes: np.ndarray) -> np.ndarray:
    """Host-side (b, H, W/32) stack -> (H, W) uint8, the checkpoint-format
    twin of :func:`unpack_generations` — keeps the plane-encoding contract
    (plain binary of the state value, LSB plane first) in this module."""
    out = None
    for i in range(planes.shape[0]):
        part = (bitpack.unpack_np(np.asarray(planes[i], dtype=np.uint32))
                << i).astype(np.uint8)
        out = part if out is None else out | part
    return out


def alive_plane(planes: jax.Array) -> jax.Array:
    """(H, W/32) plane that is set exactly where state == 1."""
    higher = reduce(jnp.bitwise_or, [planes[i] for i in range(1, planes.shape[0])],
                    jnp.zeros_like(planes[0]))
    return planes[0] & ~higher


def _mask_plane(bits: List[jax.Array], counts, like: jax.Array) -> jax.Array:
    acc = jnp.zeros_like(like)
    for n in sorted(counts):
        acc = acc | _count_eq(bits, n)
    return acc


def _alive_of(plist):
    higher = reduce(jnp.bitwise_or, plist[1:], jnp.zeros_like(plist[0]))
    return plist[0] & ~higher  # state == 1: low bit set, higher clear


def transition_planes(plist, alive, born_p, keep_p, states: int):
    """Next-generation planes from precomputed birth/keep masks — the
    decay state machine shared by every plane-stack family: the 3x3
    Generations rules (count-equality masks) and multi-state C >= 3 LtL
    (bit-sliced interval-comparator masks, ops/packed_ltl.py).

    ``born_p``/``keep_p`` are raw predicate planes over the window count;
    birth applies only where the state is 0 and keep only where alive —
    the masking happens here so callers can't disagree on it."""
    b = len(plist)
    nonzero = reduce(jnp.bitwise_or, plist)

    kept = alive & keep_p
    one = (~nonzero & born_p) | kept     # cells whose next state is 1
    aging = nonzero & ~kept              # state+1 (mod C) for everyone else alive-ish

    # plane-wise +1: half-adder carry chain
    carry = ~jnp.zeros_like(plist[0])
    inc: List[jax.Array] = []
    for p in plist:
        inc.append(p ^ carry)
        carry = p & carry
    if states != (1 << b):
        # cells that aged to exactly C die (C == 2**b wraps via dropped carry)
        eq_c = reduce(jnp.bitwise_and,
                      [inc[i] if (states >> i) & 1 else ~inc[i]
                       for i in range(b)])
        inc = [p & ~eq_c for p in inc]

    out = [aging & inc[i] for i in range(b)]
    out[0] = out[0] | one
    return tuple(out)


def _transition(plist, alive, bits, rule: GenRule):
    """Next-generation planes from (state planes, alive plane, count bits)."""
    born_p = _mask_plane(bits, rule.born, alive)
    keep_p = _mask_plane(bits, rule.survive, alive)
    return transition_planes(plist, alive, born_p, keep_p, rule.states)


def _step_plane_list(plist, rule: GenRule, topology: Topology):
    """One generation on a tuple of b (H, W/32) planes (no stack copies —
    fori_loop carries the planes as a pytree)."""
    alive = _alive_of(plist)
    bits = count_bits(alive, topology)
    return _transition(plist, alive, bits, rule)


def step_planes_ext(ext_list, rule: GenRule):
    """One generation from b halo-extended (h+2, wp+2) planes -> interior
    (h, wp) plane tuple. Halos come from the caller (sharded ppermute)."""
    alive_ext = _alive_of(ext_list)
    center, bits = count_bits_ext(alive_ext)  # center = interior alive
    interior = tuple(p[1:-1, 1:-1] for p in ext_list)
    return _transition(interior, center, bits, rule)


def step_planes_slab(plist, rule: GenRule, topology: Topology):
    """One generation for the interior rows of b (L, Wp) planes -> b
    (L-2, Wp) planes — the Generations twin of ops/packed.step_packed_slab
    (rows shrink consuming vertical halos; ``topology`` is the horizontal
    closure across the slab's own width). Serves the temporal-blocked
    Pallas kernel's in-VMEM generation loop."""
    from .packed import _row_sum_bits, horizontal_planes

    h = plist[0].shape[0] - 2
    alive = _alive_of(plist)
    w, c, e = horizontal_planes(alive, topology)
    bits = _row_sum_bits(
        w, c, e,
        lambda p: (jax.lax.slice_in_dim(p, 0, h, axis=0),
                   jax.lax.slice_in_dim(p, 2, h + 2, axis=0)),
        lambda p: jax.lax.slice_in_dim(p, 1, h + 1, axis=0))
    interior = tuple(jax.lax.slice_in_dim(p, 1, h + 1, axis=0) for p in plist)
    return _transition(interior,
                       jax.lax.slice_in_dim(alive, 1, h + 1, axis=0),
                       bits, rule)


def step_planes(planes: jax.Array, rule: GenRule, topology: Topology) -> jax.Array:
    """One generation on a (b, H, W/32) bit-plane stack."""
    b = planes.shape[0]
    return jnp.stack(_step_plane_list(
        tuple(planes[i] for i in range(b)), rule, topology))


@optionally_donated("planes")
def multi_step_packed_generations(
    planes: jax.Array,
    n: jax.Array,
    *,
    rule: GenRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations on a (b, H, W/32) stack in one jitted fori_loop."""
    b = planes.shape[0]
    body = lambda _, s: _step_plane_list(s, rule, topology)
    out = jax.lax.fori_loop(0, n, body, tuple(planes[i] for i in range(b)))
    return jnp.stack(out)


def population_packed_generations(planes: jax.Array) -> int:
    """Live-cell count (state == 1 only, matching Engine.population)."""
    return int(bitpack.population(alive_plane(planes)))
