"""Bit-packed SWAR stepping for elementary (Wolfram) 1D CA.

State is a packed uint32 array whose LAST axis is the 32-cells-per-word
row (ops/bitpack.py layout); leading axes, if any, are independent
universes — an (H, Wp) array steps H separate 1D worlds in one fused
pass, so ensembles cost the same program as one row.

One generation = two neighbor shifts + the rule's minterm evaluation:
the Wolfram number's set bits select which of the 8 (l, c, r) patterns
produce a live cell, each pattern a 3-term AND over the left/center/right
planes — at most 8 minterms, fused by XLA into one elementwise pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.elementary import ElementaryRule
from ._jit import optionally_donated
from .packed import horizontal_planes
from .stencil import Topology


@optionally_donated("p")
def step_elementary(
    p: jax.Array, *, rule: ElementaryRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """One generation on a (..., W/32) packed row (or stack of rows)."""
    # the 2D stencil's word-shift machinery works on the last axis, so the
    # 1D family reuses it verbatim (one home for the cross-word carries)
    left, _, right = horizontal_planes(p, topology)
    out = jnp.zeros_like(p)
    for k in range(8):
        if not (rule.number >> k) & 1:
            continue
        l, c, r = (k >> 2) & 1, (k >> 1) & 1, k & 1
        term = left if l else ~left
        term = term & (p if c else ~p)
        term = term & (right if r else ~right)
        out = out | term
    return out


@optionally_donated("p")
def multi_step_elementary(
    p: jax.Array, n: jax.Array, *, rule: ElementaryRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations in one jitted fori_loop."""
    def body(_, s):
        return step_elementary(s, rule=rule, topology=topology)
    return jax.lax.fori_loop(0, n, body, p)


def evolve_spacetime(
    p: jax.Array, steps: int, *, rule: ElementaryRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """The (steps+1, ..., W/32) spacetime diagram (row 0 = initial state)
    — the canonical way to LOOK at a 1D CA; feed it to bitpack.unpack and
    utils/render for the Sierpinski-triangle view of rule 90."""
    def scan_step(s, _):
        nxt = step_elementary(s, rule=rule, topology=topology)
        return nxt, nxt

    _, history = jax.lax.scan(scan_step, p, None, length=int(steps))
    return jnp.concatenate([p[None], history], axis=0)
