"""Dense (one byte per cell) stencil step.

This is the TPU-native replacement for the reference's per-generation mailbox
churn: where GameOfLifeWithActors sends ~9·N·M actor ``Tell`` messages per
generation (8 neighbor-state messages per cell plus the coordinator reply —
SURVEY.md §4b), one generation here is a single fused XLA kernel: a separable
3×3 window sum followed by a branch-free rule-mask lookup. Everything is
static-shaped and jit-friendly; no data-dependent Python control flow.

Two boundary topologies mirror the wrap/dead distinction a grid CA needs:

- ``TORUS``: edges wrap (jnp.pad mode="wrap").
- ``DEAD``: cells outside the grid are permanently dead (zero padding).

The unpacked path is the debuggable reference implementation; the bit-packed
SWAR path in :mod:`..ops.packed` is the performance lever (1 bit/cell instead
of 1 byte/cell → 8× less HBM traffic, plus 32-cell-wide bitwise arithmetic).
Both must agree bit-for-bit — tests enforce it.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from ..models.rules import Rule
from ._jit import BuiltRunner, optionally_donated, register_builder


class Topology(enum.Enum):
    TORUS = "torus"
    DEAD = "dead"


def _pad_mode(topology: Topology) -> dict:
    if topology is Topology.TORUS:
        return dict(mode="wrap")
    return dict(mode="constant", constant_values=0)


def neighbor_counts(state: jax.Array, topology: Topology) -> jax.Array:
    """Count live Moore neighbors (excluding self) for every cell.

    ``state`` is (H, W) uint8 in {0, 1}. Implemented as boundary
    materialisation (pad) + the halo-extended kernel, so the single-device
    and sharded paths share one copy of the stencil math.
    """
    return neighbor_counts_ext(jnp.pad(state, 1, **_pad_mode(topology)))


def neighbor_counts_ext(ext: jax.Array) -> jax.Array:
    """Neighbor counts for the interior of a halo-extended (h+2, w+2) tile.

    No padding/wrap logic: halos were materialised by the caller (jnp.pad
    above, or the sharded engine's ppermute exchange). Uses the separable
    row-sum trick — 3-row sums then 3-column sums (6 adds instead of 8
    shifted adds), which XLA fuses into one pass. Returns (h, w) counts.
    """
    rows = ext[:-2, :] + ext[1:-1, :] + ext[2:, :]
    win = rows[:, :-2] + rows[:, 1:-1] + rows[:, 2:]
    return win - ext[1:-1, 1:-1]


def apply_rule(state: jax.Array, counts: jax.Array, rule: Rule) -> jax.Array:
    """Branch-free rule application via 9-bit mask shift-and-test.

    Selecting ``survive_mask`` vs ``birth_mask`` per cell and testing bit
    ``count`` avoids any gather: it lowers to pure VPU ops.
    """
    mask = jnp.where(
        state.astype(bool),
        jnp.uint16(rule.survive_mask),
        jnp.uint16(rule.birth_mask),
    )
    return ((mask >> counts.astype(jnp.uint16)) & 1).astype(state.dtype)


@optionally_donated("state")
def step(state: jax.Array, *, rule: Rule, topology: Topology = Topology.TORUS) -> jax.Array:
    """One generation on an unpacked (H, W) uint8 grid."""
    return apply_rule(state, neighbor_counts(state, topology), rule)


@optionally_donated("state")
def multi_step(
    state: jax.Array,
    n: jax.Array,
    *,
    rule: Rule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """Run ``n`` generations inside a single jitted loop (no host round-trips).

    ``n`` is a traced scalar so changing the generation count does not
    recompile; the loop body is the fused single-step kernel. Pass
    ``donate=True`` (e.g. from an owner like Engine) for in-place
    double-buffering of the state buffer under XLA.
    """
    body = lambda _, s: apply_rule(s, neighbor_counts(s, topology), rule)
    return jax.lax.fori_loop(0, n, body, state)


# -- contract-gate registration (ops/_jit.py BUILDERS) -----------------------


@register_builder("ops.multi_step", tags=("ops", "dense"))
def _contract_ops_multi_step():
    import numpy as np

    from ..models.rules import CONWAY

    rng = np.random.default_rng(7)
    state = jnp.asarray(rng.integers(0, 2, size=(64, 128), dtype=np.uint8))
    return BuiltRunner(
        lowerable=multi_step.jitted_donating,
        example_args=(state, 3), example_kwargs={"rule": CONWAY},
        donated_argnums=(0,), expected_collective_bytes=0,
        collective_model="single-device: zero collectives")
