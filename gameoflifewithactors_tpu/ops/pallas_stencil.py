"""Temporally-blocked Pallas (Mosaic) stencil kernel — the native fast path.

The reference has no native components to mirror (SURVEY.md §3: it is pure
managed .NET); the framework's native-code budget goes here instead, per
SURVEY.md §8 stage 4. The XLA SWAR path (ops/packed.py) is already
memory-bound at ~2 HBM touches per generation; this kernel beats that bound
with *temporal blocking*: each grid-row block DMAs ``bh + 2g`` packed rows
from HBM into VMEM, advances **g generations entirely on-chip** (the slab
shrinks by 2 rows per generation; the middle ``bh`` rows remain exact), and
writes back once — HBM traffic per generation drops by ~g×.

Layout/contract:
- packed uint32 grid (H, W/32), same bit layout as ops/bitpack.py;
- vertical halos come via 3 contiguous async DMAs (top-wrap, body,
  bottom-wrap — the wrap segments are contiguous because g <= bh and
  H % bh == 0), double-buffered so block i+1's copies overlap block i's
  compute; horizontal wrap is in-VMEM word rolls, so the full row width
  must live in one block (the VMEM-aware block picker shortens blocks for
  wide grids; supported() caps width at ~1.8M cells where even 8-row
  blocks exceed the budget);
- TORUS is handled by the wrapped DMAs; DEAD re-zeroes the exterior rows
  of boundary blocks before every in-slab generation (exterior cells are
  *permanently* dead — they must not evolve with the slab);
- the stencil math itself is imported from ops/packed.py, so Pallas, XLA,
  and sharded paths share one set of plane/CSA/rule code.

TPU tiling wants the lane (last) dimension a multiple of 128 words (4096
cells); ``supported()`` gates that, and callers fall back to the XLA path.
Tests run the kernel in interpret mode on CPU against step_packed.
"""

# EVIDENCE FREEZE (VERDICT r4 #8): this file is a measured path of the
# serving on-chip records — the 2.20e12 cell-updates/s headline
# (results/tpu_best.json auto:default:B3/S23 @93432f1) and the 12/12
# bit-identity record (results/tpu_worklist.json pallas_identity
# @93432f1). Any non-comment edit re-stales them until the watcher
# recaptures on a healthy tunnel window (utils/provenance.py certifies
# comment-only edits via token comparison). Default to landing feature
# work elsewhere while captures are pending; when an edit here is the
# work (e.g. adopting a new autotune optimum), re-run `bench.py` and the
# pallas worklist items in the same window.

from __future__ import annotations

import os
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.rules import Rule
from ._jit import tracked_jit
from .stencil import Topology
from .packed import multi_step_packed, step_packed_slab as step_rows

# Autotuned on v5e-1 (results/tpu_worklist.json pallas_autotune, 16384^2):
# (512, 8) measured 1.78e12 cell-updates/s, ahead of 256/1024-row blocks
# and of g=16 at every block height (the kernel is compute-bound past g=8).
DEFAULT_BLOCK_ROWS = 512
DEFAULT_GENS_PER_CALL = 8


def _zero_edge_rows(slab, block_idx, n_blocks, halo, row_axis: int = 0):
    """Zero the outer ``halo`` rows of the first/last block's slab. Callers
    decide *when*: full-grid DEAD re-zeroes the shrinking exterior every
    generation (permanently-dead cells must not evolve); slab mode zeroes
    the out-of-range DMA payload once (dead closure beyond the exchanged
    halo, corruption absorbed by the crop). ``row_axis`` is 0 for a 2D
    slab, 1 for the Generations (b, L, Wp) stack."""
    if halo <= 0:
        return slab
    L = slab.shape[row_axis]
    rows = jax.lax.broadcasted_iota(jnp.int32, slab.shape, row_axis)
    top_ext = (block_idx == 0) & (rows < halo)
    bot_ext = (block_idx == n_blocks - 1) & (rows >= L - halo)
    return jnp.where(top_ext | bot_ext, jnp.uint32(0), slab)


def _zero_band_exterior(slab, block_idx, bh, halo, shrunk, He, edge_ref,
                        row_axis: int = 0):
    """Per-generation re-zero of the permanently-dead exterior of a
    global-edge row band (slab mode, DEAD vertical closure). The extended
    band's outer ``halo`` rows are exterior on a global-edge device —
    cells born there by the free slab evolution would feed back into the
    interior from the 2nd in-slab generation on (the same failure mode
    full-grid DEAD guards against). Masks by GLOBAL extended-row index:
    after ``shrunk`` rows have been consumed per side (k generations ×
    the rule's radius), slab row ``s`` is extended row
    ``block*bh + s - (halo - shrunk)``; global indexing also keeps any
    block decomposition correct (with bh < 2·halo the exterior spans two
    blocks). Gated at runtime by the device's edge code (bit0 = global
    top band, bit1 = bottom), an SMEM scalar — the compiled program is
    shared by every device in the shard_map, so edge-ness must be data,
    not code.
    """
    code = edge_ref[0, 0]
    rows = jax.lax.broadcasted_iota(jnp.int32, slab.shape, row_axis)
    ext_row = block_idx * bh + rows - (halo - shrunk)
    top = ((code & 1) == 1) & (ext_row < halo)
    bot = ((code & 2) == 2) & (ext_row >= He - halo)
    return jnp.where(top | bot, jnp.uint32(0), slab)


def _dma_pipeline(p_hbm, slab_ref, sems, i, H, bh, halo, n_blocks, stack: bool):
    """The shared double-buffered 3-segment input pipeline: start block
    i+1's copies, wait on block i's (started by the previous grid step or
    the i == 0 prologue), return the revolving buffer index holding block
    i. TPU grid steps run sequentially and scratch/semaphores persist
    across them, which is what makes the hand-off sound; output copies are
    pallas-managed (blocked out_specs) and already pipelined by Mosaic.

    ``halo`` is the vertical halo depth in ROWS (= g for the 3x3 kernels,
    r*g for radius-r LtL — the slab consumes 2·(halo/g) rows per in-slab
    generation either way). The 3 segments (top halo, body, bottom halo)
    are contiguous because halo <= bh. Mosaic must prove the dynamic row
    offsets divisible by the (8, 128) sublane tiling; the jnp.where
    obscures that, so assert it with multiple_of (sound: H, bh, halo are
    all multiples of 8 natively). In slab mode the wrap formula is only an
    arbitrary aligned in-range window — its payload is zeroed after the
    wait. ``stack=True`` copies the Generations (b, rows, Wp) form, whole
    plane axis per segment.
    """
    def copies(j, buf):
        base = j * bh
        top = pl.multiple_of(jnp.where(j == 0, H - halo, base - halo), 8)
        bot = pl.multiple_of(jnp.where(j == n_blocks - 1, 0, base + bh), 8)
        out = []
        for k, (src, n, dst) in enumerate(
                ((top, halo, 0), (base, bh, halo), (bot, halo, halo + bh))):
            if stack:
                out.append(pltpu.make_async_copy(
                    p_hbm.at[:, pl.ds(src, n)],
                    slab_ref.at[buf, :, pl.ds(dst, n)], sems.at[buf, k]))
            else:
                out.append(pltpu.make_async_copy(
                    p_hbm.at[pl.ds(src, n)],
                    slab_ref.at[buf, pl.ds(dst, n)], sems.at[buf, k]))
        return out

    buf = jax.lax.rem(i, 2)

    @pl.when(i == 0)
    def _prologue():
        for c in copies(i, buf):
            c.start()

    @pl.when(i + 1 < n_blocks)
    def _prefetch():
        for c in copies(i + 1, 1 - buf):
            c.start()

    for c in copies(i, buf):
        c.wait()
    return buf


def _make_kernel(rule: Rule, topology: Topology, H: int, Wp: int, bh: int,
                 g: int, slab_mode: bool = False, dead_band: bool = False):
    """The temporal-blocked kernel body, in one of two closure modes.

    Full-grid mode (``slab_mode=False``): the H rows are the whole universe;
    vertical wrap rides the wrapped DMAs, DEAD re-zeroes the exterior rows
    of boundary blocks before *every* in-slab generation (exterior cells are
    permanently dead — they must not evolve with the slab).

    Slab mode (``slab_mode=True``): the H rows are a halo-extended row band
    (``th + 2g``; outer g rows = *exchanged neighbor data*, parallel/
    sharded.py make_multi_step_pallas) spanning the full grid width.
    Vertical out-of-range segments (above row 0 / below row H) are unknown
    beyond the exchanged depth → the wrapped DMA's payload is zeroed ONCE
    before the generation loop (dead closure; the resulting edge corruption
    creeps 1 row/gen and ends inside the g cropped halo rows, so the band
    interior stays exact). No per-generation re-zero: every in-slab row is
    real band or halo data and must evolve freely. ``topology`` is the
    *global horizontal* closure only (TORUS wraps in-VMEM across the full
    width, globally correct for row bands; vertical global wrap rides the
    halo exchange outside).

    ``dead_band`` (slab mode only): global DEAD *vertical* closure for the
    band runners — the kernel takes an extra (1, 1) int32 SMEM edge code
    (bit0 = this device holds the global top band, bit1 = bottom) and
    re-zeroes the permanently-dead exterior rows before every in-slab
    generation on edge devices (_zero_band_exterior). Interior devices
    (code 0) evolve their halos freely, exactly like the TORUS form.
    """
    n_blocks = H // bh
    L = bh + 2 * g

    def kernel(p_hbm, *refs):
        if dead_band:
            edge_ref, out_ref, slab_ref, sems = refs
        else:
            out_ref, slab_ref, sems = refs
        i = pl.program_id(0)
        buf = _dma_pipeline(p_hbm, slab_ref, sems, i, H, bh, g, n_blocks,
                            stack=False)
        slab = slab_ref[buf]
        if slab_mode:
            for k in range(g):
                if k == 0:
                    slab = _zero_edge_rows(slab, i, n_blocks, g)
                if dead_band:
                    slab = _zero_band_exterior(slab, i, bh, g, k, H, edge_ref)
                slab = step_rows(slab, rule, topology)
        else:
            for k in range(g):
                if topology is Topology.DEAD:
                    slab = _zero_edge_rows(slab, i, n_blocks, g - k)
                slab = step_rows(slab, rule, topology)
        out_ref[:] = slab

    return kernel, n_blocks, L


def _make_gen_kernel(rule, topology: Topology, b: int, H: int, Wp: int,
                     bh: int, g: int, slab_mode: bool = False,
                     dead_band: bool = False):
    """Temporal-blocked kernel for the Generations bit-plane stack: the
    (b, H, Wp) planes ride the same 3-segment double-buffered DMA scheme
    (leading plane axis copied whole per segment), the in-VMEM loop steps
    packed_generations.step_planes_slab, and DEAD re-zeroes the exterior
    rows of boundary blocks every generation exactly like the binary form.
    ``slab_mode`` has the same two closure modes as _make_kernel: the H
    rows are a halo-extended row band and out-of-range DMA payloads are
    zeroed once; ``dead_band`` adds the same SMEM edge-code per-generation
    exterior re-zero as the binary slab form (_zero_band_exterior,
    row_axis=1).
    """
    from .packed_generations import step_planes_slab

    n_blocks = H // bh
    L = bh + 2 * g

    def kernel(p_hbm, *refs):
        if dead_band:
            edge_ref, out_ref, slab_ref, sems = refs
        else:
            out_ref, slab_ref, sems = refs
        i = pl.program_id(0)
        buf = _dma_pipeline(p_hbm, slab_ref, sems, i, H, bh, g, n_blocks,
                            stack=True)
        slab = slab_ref[buf]                       # (b, L, Wp)
        for k in range(g):
            if slab_mode:
                if k == 0:
                    slab = _zero_edge_rows(slab, i, n_blocks, g, row_axis=1)
                if dead_band:
                    slab = _zero_band_exterior(slab, i, bh, g, k, H, edge_ref,
                                               row_axis=1)
            elif topology is Topology.DEAD:
                slab = _zero_edge_rows(slab, i, n_blocks, g - k, row_axis=1)
            plist = step_planes_slab(
                tuple(slab[j] for j in range(b)), rule, topology)
            slab = jnp.stack(plist)
        out_ref[:] = slab

    return kernel, n_blocks, L


def _validate_slab(He: int, bh: int, g: int, interpret: bool,
                   Wp: int = 0, planes: int = 1,
                   vmem_bytes=None, budget: int = 0) -> None:
    """Shared kernel shape guards (binary and Generations, full-grid and
    slab forms). ``Wp`` (words per row, per plane) adds the lane-alignment
    and VMEM-budget checks so an explicit block_rows / band request fails
    with a clean ValueError here instead of an opaque Mosaic compile error
    on chip (advisor round-2 finding). ``vmem_bytes``/``budget`` let a
    caller whose kernel has its own footprint model (the bit-sliced LtL
    form budgets against the raised scoped-vmem cap) validate against
    *that*, instead of the binary double-buffer model vs the fixed 14 MiB
    — which held for LtL only by arithmetic coincidence (advisor r5 #1:
    binary_model <= 2/7 * ltl_model, and 2/7 * 48 MiB happens to land
    under 14 MiB; tests/test_pallas.py pins that invariant so the
    coincidence can't silently break for callers still relying on it)."""
    vmem_bytes = vmem_bytes or _vmem_bytes
    budget = budget or _VMEM_BUDGET
    if He % bh:
        raise ValueError(
            f"height {He} not divisible by block rows {bh}")
    if g > bh:
        # the 3-segment DMA scheme needs the g rows above/below a block to
        # be contiguous in the previous/next block: g <= bh. Violations are
        # NOT caught downstream — interior blocks assemble wrong neighbor
        # rows (clamped offsets in interpret mode, out-of-range DMAs native)
        raise ValueError(
            f"slab kernel needs gens ({g}) <= block_rows ({bh}); pick a "
            f"larger block_rows or a shallower exchange depth")
    if not interpret and (bh % 8 or g % 8):
        raise ValueError(
            f"native TPU slab kernel needs block_rows ({bh}) and gens ({g}) "
            f"to be multiples of 8 (sublane tiling)")
    if not interpret and Wp and Wp % 128:
        raise ValueError(
            f"native TPU kernel needs the packed width ({Wp} words = "
            f"{Wp * 32} cells) to be a multiple of 128 words (lane tiling)")
    if not interpret and Wp and vmem_bytes(bh, g, Wp * planes) > budget:
        raise ValueError(
            f"kernel VMEM footprint {vmem_bytes(bh, g, Wp * planes)} bytes "
            f"(block_rows={bh}, gens={g}, width {Wp * 32} cells"
            + (f", {planes} planes" if planes > 1 else "")
            + f") exceeds the {budget >> 20} MiB budget; "
              "use smaller block_rows or a narrower grid")


def _make_ltl_kernel(rule, topology: Topology, H: int, Wp: int, bh: int,
                     g: int, slab_mode: bool = False,
                     dead_band: bool = False):
    """Temporal-blocked kernel for radius-r LtL Moore rules: halo depth
    r*g rows — the slab shrinks 2r rows per in-slab generation through
    packed_ltl.step_ltl_packed_slab (vertical DEAD closure on the slab,
    global horizontal closure in-VMEM).

    Full-grid mode: TORUS rides the wrapped DMAs; DEAD re-zeroes the
    shrinking exterior of boundary blocks before every generation, exactly
    like the 3x3 form but r rows at a time. Slab mode (+``dead_band``):
    same two closure modes as _make_kernel — the H rows are a
    halo-extended row band (outer r*g rows = exchanged data), out-of-range
    DMA payloads are zeroed once, and under a global DEAD vertical closure
    the SMEM edge code drives the per-generation exterior re-zero (the
    shrink argument is r·k, not k)."""
    from .packed_ltl import step_ltl_packed_slab

    r = rule.radius
    hr = r * g
    n_blocks = H // bh
    L = bh + 2 * hr

    def kernel(p_hbm, *refs):
        if dead_band:
            edge_ref, out_ref, slab_ref, sems = refs
        else:
            out_ref, slab_ref, sems = refs
        i = pl.program_id(0)
        buf = _dma_pipeline(p_hbm, slab_ref, sems, i, H, bh, hr, n_blocks,
                            stack=False)
        slab = slab_ref[buf]
        if slab_mode:
            for k in range(g):
                if k == 0:
                    slab = _zero_edge_rows(slab, i, n_blocks, hr)
                if dead_band:
                    slab = _zero_band_exterior(slab, i, bh, hr, r * k, H,
                                               edge_ref)
                slab = step_ltl_packed_slab(slab, rule, topology)
        else:
            for k in range(g):
                if topology is Topology.DEAD:
                    slab = _zero_edge_rows(slab, i, n_blocks, hr - r * k)
                slab = step_ltl_packed_slab(slab, rule, topology)
        out_ref[:] = slab

    return kernel, n_blocks, L


def _ltl_pallas_call(rule, topology: Topology, shape, bh: int, g: int,
                     interpret: bool, slab_mode: bool,
                     dead_band: bool = False):
    H, Wp = shape
    kernel, n_blocks, L = _make_ltl_kernel(rule, topology, H, Wp, bh, g,
                                           slab_mode=slab_mode,
                                           dead_band=dead_band)
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]
    if dead_band:
        in_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0),
                                     memory_space=pltpu.SMEM))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((H, Wp), jnp.uint32),
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bh, Wp), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, L, Wp), jnp.uint32),      # revolving slab buffers
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        # Mosaic's default 16 MiB scoped-vmem cap rejects the bit-sliced
        # window sum's live count planes at bench shapes (measured on
        # chip: 17.74 MB scoped at bosco r=5, g=8, bh=512, Wp=256 —
        # results/tpu_worklist.json ltl_pallas @700b444). v4+ cores have
        # 128 MiB VMEM; raise the cap for this kernel only (and only on
        # such cores) and gate block sizes on _LTL_VMEM_BUDGET below it.
        compiler_params=(pltpu.CompilerParams(vmem_limit_bytes=lim)
                         if not interpret and (lim := _ltl_vmem_limit())
                         else None),
        interpret=interpret,
    )


@lru_cache(maxsize=32)
def _build_ltl_runner(rule, topology: Topology, shape, bh: int, g: int,
                      interpret: bool, donate: bool):
    call = _ltl_pallas_call(rule, topology, shape, bh, g, interpret,
                            slab_mode=False)
    return tracked_jit(
        lambda s, c: jax.lax.fori_loop(0, c, lambda _, t: call(t), s),
        runner="pallas_ltl_loop",
        donate_argnums=(0,) if donate else (),
    )


@lru_cache(maxsize=32)
def make_ltl_pallas_slab_step(
    rule,
    topology: Topology,
    ext_shape,
    *,
    gens: int,
    block_rows: Optional[int] = None,
    interpret: bool = False,
    dead_band: bool = False,
):
    """``ext (He, Wp) -> (He, Wp)`` advancing ``gens`` LtL generations of
    a halo-extended full-width row band (He = band + 2·r·gens); the
    caller crops ``out[r*gens:-r*gens]``. The radius-r twin of
    :func:`make_pallas_slab_step`, same ``dead_band`` SMEM edge-code
    contract; shard_map callers need ``check_vma=False``."""
    He, Wp = ext_shape
    g = int(gens)
    r = rule.radius
    hr = r * g
    vmem_model = _ltl_vmem_model(r)
    budget = _ltl_vmem_budget()
    bh = block_rows or _pick_bh(He, native=not interpret, at_least=hr,
                                g=hr, Wp=Wp, vmem_bytes=vmem_model,
                                budget=budget)
    if hr > bh:
        raise ValueError(
            f"LtL slab kernel needs radius*gens ({hr}) <= block_rows ({bh})")
    # the generic check models the binary kernel; the bit-sliced box
    # sum's count planes budget against the raised LtL scoped-vmem cap
    _validate_slab(He, bh, hr, interpret, Wp=Wp,
                   vmem_bytes=vmem_model, budget=budget)
    return _ltl_pallas_call(rule, topology, (He, Wp), bh, g, interpret,
                            slab_mode=True, dead_band=dead_band)


# Scoped-vmem cap passed to Mosaic for the LtL kernel on cores with
# 128 MiB physical VMEM (v4 and later; v2/v3 cores have 16 MiB and keep
# Mosaic's default cap — see _ltl_vmem_limit); _LTL_VMEM_BUDGET gates
# block picking with headroom under it. The budget assumes a v4+ core —
# the framework's stated target (BASELINE.json: v5e) — so ltl_supported
# on_tpu=True answers for that generation.
_LTL_VMEM_LIMIT = 64 * 1024 * 1024
_LTL_VMEM_BUDGET = 48 * 1024 * 1024


def _ltl_vmem_planes(r: int) -> int:
    """Live slab-sized temporaries of the bit-sliced window sum (count
    planes + sliding partials), BESIDE the two revolving buffers
    _ltl_vmem_bytes adds separately. Calibrated from Mosaic's measured
    scoped allocation at r=5 box (17.74 MiB = 18,601,738 bytes at g=8,
    bh=512, Wp=256 — Mosaic prints binary MiB; its default cap shows as
    "16.00M" — → 26.96 count planes once the 2 revolving L-planes are
    taken out; the prior flat estimate of 8 under-predicted ~3×) and
    extrapolated linearly in the (2r+1) window rows the sliding sum
    holds — a single calibration point, so the scaling is deliberately
    the conservative direction for r>5 (code-review r5: MAX_RADIUS=7
    rules share this model). Floored so small radii never under-reserve
    vs the old estimate."""
    return max(10, -(-27 * (2 * r + 1) // 11))


def _ltl_vmem_bytes(bh: int, hr: int, Wp: int, *, r: int) -> int:
    L = bh + 2 * hr
    return ((2 + _ltl_vmem_planes(r)) * L + 2 * bh) * Wp * 4


def _ltl_vmem_limit() -> int:
    """The scoped-vmem cap to request from Mosaic: raised on v4+ cores
    (128 MiB physical) and on non-TPU hosts, which lower for the v4+
    target the framework builds for (BASELINE.json: v5e) — the CPU test
    rig, the fake-device dryrun, and any AOT cross-lowering must answer
    for that target, not for the host; 0 (= keep Mosaic's default) only
    on pre-v4 / unrecognized TPU cores where 64 MiB exceeds physical
    VMEM. The single decision point: :func:`_ltl_vmem_budget` keys off
    this same value, so block picking can never admit a shape the
    compile-time cap then rejects (code-review r5).

    ``GOLTPU_TPU_GENERATION`` (e.g. ``3``, ``v3``, ``v5e``) overrides
    everything, including the local device kind: it names the *target*
    core generation, so AOT cross-lowering from any host for a pre-v4
    core can opt into the conservative 14/16 MiB budgets that the
    host-platform fallback would otherwise lift (advisor r5 #3)."""
    import re

    target = os.environ.get("GOLTPU_TPU_GENERATION", "").strip()
    if target:
        m = re.search(r"(\d+)", target)
        if not m:
            raise ValueError(
                f"GOLTPU_TPU_GENERATION={target!r} names no TPU "
                "generation; expected e.g. '3', 'v3', 'v5e'")
        return _LTL_VMEM_LIMIT if int(m.group(1)) >= 4 else 0
    d = jax.devices()[0]
    if d.platform != "tpu":
        return _LTL_VMEM_LIMIT
    # 'tpu v5 lite' / 'TPU v4' / bare 'tpu7x'-style kinds all carry the
    # generation digit; only v2/v3 (16 MiB cores) keep the default cap
    m = re.search(r"(?:v|tpu)\s*(\d+)", d.device_kind.lower())
    return _LTL_VMEM_LIMIT if m and int(m.group(1)) >= 4 else 0


def _ltl_vmem_budget() -> int:
    """Block-picking budget with headroom under the cap
    :func:`_ltl_vmem_limit` will request; conservative exactly when the
    cap stays at Mosaic's default."""
    return _LTL_VMEM_BUDGET if _ltl_vmem_limit() else _VMEM_BUDGET


# Pre-v4 model-error margin (ADVICE r5 #2): the count-plane term of
# _ltl_vmem_bytes is calibrated from a SINGLE Mosaic measurement — the
# 18,601,738-byte scoped allocation at r=5 box, g=8, bh=512, Wp=256
# (results/tpu_worklist.json ltl_pallas @700b444, a v5e core) — so away
# from that point it is an extrapolation. On v4+ the 48-vs-64 MiB
# budget-to-cap gap absorbs a 33% model error; on pre-v4 cores the
# budget is 14 MiB against a 16 MiB physical VMEM, absorbing only ~2 MiB
# (~14%), thinner than the extrapolation deserves. Inflate the model by
# 1.25x there so block picking keeps real headroom; v4+ keeps the
# uninflated model (its slack already exceeds the factor).
_LTL_MODEL_SAFETY_PRE_V4 = 1.25


def _ltl_vmem_model(r: int):
    """The LtL VMEM model with the rule's radius bound — the shared
    adapter every ``_pick_bh`` call site passes as ``vmem_bytes``. On
    pre-v4 targets (``_ltl_vmem_limit() == 0``: 16 MiB cores keeping
    Mosaic's default cap) the single-point-calibrated model is inflated
    by ``_LTL_MODEL_SAFETY_PRE_V4`` — see the note above."""
    if _ltl_vmem_limit():
        return lambda bh, hr, Wp: _ltl_vmem_bytes(bh, hr, Wp, r=r)
    return lambda bh, hr, Wp: int(
        _ltl_vmem_bytes(bh, hr, Wp, r=r) * _LTL_MODEL_SAFETY_PRE_V4)


def ltl_supported(shape, rule, *, on_tpu: bool,
                  gens_per_call: Optional[int] = None) -> bool:
    """Whether the LtL kernel can run this packed (H, Wp) shape (both
    neighborhoods — the diamond sum is per-row separable; binary rules
    only, 1 bit/cell): natively lane/sublane alignment; and (both modes)
    a block decomposition with blocks >= the r·g halo within the VMEM
    budget — a grid shorter than the halo has no decomposition even in
    interpret mode, and the engine's fallback must know that up front."""
    if rule.states != 2:
        return False
    H, Wp = shape
    g = gens_per_call or DEFAULT_GENS_PER_CALL
    r = rule.radius
    hr = r * g
    if on_tpu and (Wp % 128 or H % 8 or hr % 8):
        return False
    try:
        _pick_bh(H, native=on_tpu, at_least=hr, g=hr, Wp=Wp,
                 vmem_bytes=_ltl_vmem_model(r), budget=_ltl_vmem_budget())
    except ValueError:
        return False
    return True


def make_ltl_pallas_step(
    rule,
    topology: Topology,
    shape,
    *,
    block_rows: Optional[int] = None,
    gens_per_call: Optional[int] = None,
    interpret: bool = False,
    donate: bool = False,
):
    """The cached (loop, g) pair advancing g LtL generations per kernel
    call — the radius-r twin of :func:`make_pallas_step`. Temporal
    blocking pays 2·r·g redundant halo rows per block per call, so the
    HBM-traffic win per generation is the same ~g× as the 3x3 kernel
    while the compute per cell is the rule's bit-sliced window network
    (box or diamond)."""
    H, Wp = shape
    g = gens_per_call or DEFAULT_GENS_PER_CALL
    r = rule.radius
    hr = r * g
    bh = block_rows or _pick_bh(H, native=not interpret, at_least=hr,
                                g=hr, Wp=Wp, vmem_bytes=_ltl_vmem_model(r),
                                budget=_ltl_vmem_budget())
    if g < 1 or hr > bh:
        raise ValueError(
            f"LtL kernel needs radius*gens ({hr}) <= block_rows ({bh})")
    if H % bh:
        raise ValueError(f"grid height {H} not divisible by block rows {bh}")
    if not interpret and (bh % 8 or hr % 8):
        raise ValueError(
            f"native LtL kernel needs block_rows ({bh}) and radius*gens "
            f"({hr}) to be multiples of 8 (sublane tiling)")
    if not interpret and Wp % 128:
        raise ValueError(
            f"native TPU kernel needs the packed width ({Wp} words) to be "
            "a multiple of 128 words (lane tiling)")
    fp, budget = _ltl_vmem_model(r)(bh, hr, Wp), _ltl_vmem_budget()
    if not interpret and fp > budget:
        # explicit block_rows bypasses _pick_bh — guard here too, so an
        # oversized block raises this ValueError instead of the opaque
        # Mosaic scoped-vmem error (the slab twin has the same check)
        raise ValueError(
            f"LtL kernel VMEM footprint {fp} bytes (block_rows={bh}, "
            f"radius*gens={hr}, width {Wp * 32} cells) exceeds the "
            f"{budget >> 20} MiB budget; use smaller block_rows or a "
            "shallower exchange")
    return _build_ltl_runner(rule, topology, (H, Wp), bh, g, interpret,
                             donate), g


def multi_step_ltl_pallas(
    p: jax.Array,
    n: int,
    *,
    rule,
    topology: Topology = Topology.TORUS,
    block_rows: Optional[int] = None,
    gens_per_call: Optional[int] = None,
    interpret: bool = False,
    donate: bool = False,
) -> jax.Array:
    """``n`` LtL generations via the temporal-blocked kernel, with the
    n % g remainder on the XLA bit-sliced path. ``n`` is a Python int."""
    from .packed_ltl import multi_step_ltl_packed

    loop, g = make_ltl_pallas_step(
        rule, topology, p.shape, block_rows=block_rows,
        gens_per_call=gens_per_call, interpret=interpret, donate=donate)
    chunks, rem = divmod(int(n), g)
    if chunks:
        p = loop(p, chunks)
    if rem:
        p = multi_step_ltl_packed(p, rem, rule=rule, topology=topology,
                                  donate=donate or chunks > 0)
    return p


def _gen_pallas_call(rule, topology: Topology, shape, bh: int, g: int,
                     interpret: bool, slab_mode: bool,
                     dead_band: bool = False):
    b, H, Wp = shape
    kernel, n_blocks, L = _make_gen_kernel(rule, topology, b, H, Wp, bh, g,
                                           slab_mode=slab_mode,
                                           dead_band=dead_band)
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]
    if dead_band:
        in_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0),
                                     memory_space=pltpu.SMEM))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, H, Wp), jnp.uint32),
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, bh, Wp), lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, b, L, Wp), jnp.uint32),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )


@lru_cache(maxsize=64)
def _build_gen_runner(rule, topology: Topology, shape, bh: int, g: int,
                      interpret: bool, donate: bool):
    call = _gen_pallas_call(rule, topology, shape, bh, g, interpret,
                            slab_mode=False)
    return tracked_jit(
        lambda s, c: jax.lax.fori_loop(0, c, lambda _, t: call(t), s),
        runner="pallas_generations_loop",
        donate_argnums=(0,) if donate else (),
    )


@lru_cache(maxsize=64)
def make_pallas_gen_slab_step(
    rule,
    topology: Topology,
    ext_shape,
    *,
    gens: int,
    block_rows: Optional[int] = None,
    interpret: bool = False,
    dead_band: bool = False,
):
    """``ext (b, He, Wp) -> (b, He, Wp)`` advancing ``gens`` generations of
    a halo-extended full-width Generations row band (He = band + 2*gens);
    the caller crops ``out[:, gens:-gens]``. Same contract as
    :func:`make_pallas_slab_step`, plane-stack form (incl. ``dead_band``'s
    extra (1, 1) edge-code operand); shard_map callers need
    ``check_vma=False``."""
    b, He, Wp = ext_shape
    g = int(gens)
    bh = block_rows or _pick_bh(He, native=not interpret, at_least=g, g=g,
                                Wp=Wp * b)
    _validate_slab(He, bh, g, interpret, Wp=Wp, planes=b)
    return _gen_pallas_call(rule, topology, (b, He, Wp), bh, g, interpret,
                            slab_mode=True, dead_band=dead_band)


def multi_step_pallas_generations(
    planes: jax.Array,
    n: int,
    *,
    rule,
    topology: Topology = Topology.TORUS,
    block_rows: Optional[int] = None,
    gens_per_call: Optional[int] = None,
    interpret: bool = False,
    donate: bool = False,
) -> jax.Array:
    """``n`` generations of a Generations rule on a (b, H, W/32) bit-plane
    stack via the temporal-blocked kernel; the n % g remainder takes the
    XLA bit-plane path. ``n`` is a Python int."""
    from .packed_generations import multi_step_packed_generations

    b, H, Wp = planes.shape
    g_req = gens_per_call or DEFAULT_GENS_PER_CALL
    bh = block_rows or _pick_bh(H, native=not interpret, g=g_req,
                                Wp=Wp * b)  # b planes share the budget
    g = min(g_req, bh)
    _validate_slab(H, bh, g, interpret, Wp=Wp, planes=b)
    loop = _build_gen_runner(rule, topology, (b, H, Wp), bh, g, interpret,
                             donate)
    chunks, rem = divmod(int(n), g)
    if chunks:
        planes = loop(planes, chunks)
    if rem:
        planes = multi_step_packed_generations(
            planes, rem, rule=rule, topology=topology,
            donate=donate or chunks > 0)
    return planes


@lru_cache(maxsize=64)
def _build_slab_runner(rule: Rule, topology: Topology, ext_shape, bh: int,
                       g: int, interpret: bool, dead_band: bool = False):
    He, Wp = ext_shape
    kernel, n_blocks, L = _make_kernel(rule, topology, He, Wp, bh, g,
                                       slab_mode=True, dead_band=dead_band)
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]
    if dead_band:
        in_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0),
                                     memory_space=pltpu.SMEM))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((He, Wp), jnp.uint32),
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bh, Wp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, L, Wp), jnp.uint32),      # revolving slab buffers
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )


def make_pallas_slab_step(
    rule: Rule,
    topology: Topology,
    ext_shape,
    *,
    gens: int,
    block_rows: Optional[int] = None,
    interpret: bool = False,
    dead_band: bool = False,
):
    """``ext (He, Wp) -> (He, Wp)`` advancing ``gens`` generations of a
    halo-extended full-width row band (He = band rows + 2*gens); the caller
    crops ``out[gens:-gens]`` for the exact band interior. ``topology`` is
    the global horizontal closure (see _make_kernel slab mode).
    ``dead_band=True`` adds a second (1, 1) int32 operand — the device's
    global-edge code (bit0 top, bit1 bottom) — and realizes the permanently
    dead exterior on edge bands under a global DEAD vertical closure.
    Note: a caller wrapping this in shard_map must pass ``check_vma=False``
    — the vma checker cannot type the kernel's scratch-DMA primitives."""
    He, Wp = ext_shape
    g = int(gens)
    bh = block_rows or _pick_bh(He, native=not interpret, at_least=g,
                                g=g, Wp=Wp)
    _validate_slab(He, bh, g, interpret, Wp=Wp)
    return _build_slab_runner(rule, topology, (He, Wp), bh, g, interpret,
                              dead_band=dead_band)


def band_supported(band_rows: int, g: int, *, native: bool,
                   wp: int = 0) -> bool:
    """Whether the slab kernel can run a ``band_rows``-row band with a
    depth-``g`` exchange: alignment (band % 8, g % 8 native), exchange depth
    within the band, and a block decomposition of the extended height with
    blocks >= g rows must exist (within the VMEM budget when ``wp`` is
    given). Engine's auto resolution gates on this so 'auto' never selects
    a configuration the kernel would reject."""
    if g < 1 or g > band_rows:
        return False
    if native and (band_rows % 8 or g % 8):
        return False
    if native and wp and wp % 128:
        # lane tiling: same constraint supported() enforces on the
        # single-device path — an unaligned width must fall back cleanly
        # instead of surfacing as a Mosaic compile error on chip
        return False
    try:
        # raises when no divisor of the extended height is >= g (the DMA
        # contiguity floor) — a returned bh always satisfies g <= bh
        _pick_bh(band_rows + 2 * g, native=native, at_least=g, g=g, Wp=wp)
    except ValueError:
        return False
    return True


def supported(shape, *, on_tpu: bool, planes: int = 1) -> bool:
    """Whether the kernel can run this packed (H, Wp) shape natively.

    The TPU lane (last) dimension must be a multiple of 128 words (= 4096
    cells of width), the height a multiple of 8 (sublane tiling, so a
    block decomposition with 8-aligned DMA offsets exists), and even the
    shortest legal block (8 rows) must fit the double-buffered VMEM budget
    — widths up to ~1.8M cells; interpret mode (CPU) has no constraint.
    ``planes`` scales the VMEM budget for the Generations bit-plane stack
    (b planes share one slab buffer); alignment is per plane.
    """
    H, Wp = shape
    return not on_tpu or (
        Wp % 128 == 0 and H % 8 == 0
        and _vmem_bytes(8, DEFAULT_GENS_PER_CALL, Wp * planes) <= _VMEM_BUDGET)


def default_interpret() -> bool:
    """Native Mosaic only exists on TPU; everywhere else use interpret."""
    return jax.devices()[0].platform != "tpu"


_VMEM_BUDGET = 14 * 1024 * 1024  # headroom under the ~16 MiB/core VMEM


def _vmem_bytes(bh: int, g: int, Wp: int) -> int:
    """Kernel VMEM footprint: two revolving (bh+2g, Wp) slab buffers plus
    the Mosaic-double-buffered (bh, Wp) output block, uint32 words."""
    return (2 * (bh + 2 * g) + 2 * bh) * Wp * 4


def _pick_bh(H: int, native: bool = False, at_least: int = 1,
             g: int = DEFAULT_GENS_PER_CALL, Wp: int = 0,
             vmem_bytes=None, budget: int = 0) -> int:
    """Largest block height <= max(DEFAULT_BLOCK_ROWS, at_least) dividing H
    (8-aligned when targeting real Mosaic, see the multiple_of hints in the
    kernel), >= ``at_least`` (the slab path's DMA scheme needs blocks at
    least as tall as the exchange depth), and — when ``Wp`` is given —
    fitting the VMEM budget under ``vmem_bytes(bh, g, Wp)`` (the
    double-buffered model by default, the bit-sliced LtL model via
    _ltl_vmem_bytes; wide grids get shorter blocks instead of a Mosaic
    allocation failure). ``budget`` overrides the 14 MiB default —
    the LtL kernel budgets against its raised scoped-vmem cap."""
    vmem_bytes = vmem_bytes or _vmem_bytes
    budget = budget or _VMEM_BUDGET
    bh = min(max(DEFAULT_BLOCK_ROWS, at_least), H)
    step = 1
    if native:
        bh -= bh % 8
        step = 8
    floor = max(at_least, 1)
    while bh >= floor and (
            H % bh or (Wp and vmem_bytes(bh, g, Wp) > budget)):
        bh -= step
    if bh < floor:
        raise ValueError(
            f"no usable block height for grid height {H}"
            + (f" with blocks >= {at_least} rows" if at_least > 1 else "")
            + (f" within the {budget >> 20} MiB VMEM budget at "
               f"width {Wp * 32} cells" if Wp else ""))
    return bh


@lru_cache(maxsize=64)
def _build_runner(rule: Rule, topology: Topology, shape, bh: int, g: int,
                  interpret: bool, donate: bool):
    """Compile-once cache: (kernel pallas_call, jitted chunk loop).

    Keyed on everything that shapes the lowered kernel, so Engine.step /
    bench repetitions reuse one executable instead of re-tracing per call.
    """
    H, Wp = shape
    kernel, n_blocks, L = _make_kernel(rule, topology, H, Wp, bh, g)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((H, Wp), jnp.uint32),
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((bh, Wp), lambda i: (i, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, L, Wp), jnp.uint32),      # revolving slab buffers
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=interpret,
    )
    loop = tracked_jit(
        lambda s, c: jax.lax.fori_loop(0, c, lambda _, t: call(t), s),
        runner="pallas_binary_loop",
        donate_argnums=(0,) if donate else (),
    )
    return loop


def make_pallas_step(
    rule: Rule,
    topology: Topology,
    shape,
    *,
    block_rows: Optional[int] = None,
    gens_per_call: Optional[int] = None,
    interpret: bool = False,
    donate: bool = False,
):
    """The cached (loop, g) pair advancing g generations per kernel call.

    ``gens_per_call`` is the temporal-blocking depth g: bigger g = less HBM
    traffic per generation but more redundant edge recompute (2g extra rows
    per block per call). g is clamped to bh so wrap DMAs stay contiguous.
    ``donate=True`` hands the caller's buffer to the loop (owners only).
    """
    H, Wp = shape
    bh = block_rows or _pick_bh(
        H, native=not interpret,
        g=gens_per_call or DEFAULT_GENS_PER_CALL, Wp=Wp)
    g = min(gens_per_call or DEFAULT_GENS_PER_CALL, bh)
    # the multiple_of(…, 8) DMA-offset hints in the kernel are only
    # sound when every slab boundary lands on a sublane-tile boundary
    _validate_slab(H, bh, g, interpret, Wp=Wp)
    return _build_runner(rule, topology, (H, Wp), bh, g, interpret, donate), g


def multi_step_pallas(
    p: jax.Array,
    n: int,
    *,
    rule: Rule,
    topology: Topology = Topology.TORUS,
    block_rows: Optional[int] = None,
    gens_per_call: Optional[int] = None,
    interpret: bool = False,
    donate: bool = False,
) -> jax.Array:
    """Advance ``n`` generations via the temporal-blocked kernel, with the
    n % g remainder handled by the XLA SWAR path. ``n`` is a Python int."""
    loop, g = make_pallas_step(
        rule, topology, p.shape,
        block_rows=block_rows, gens_per_call=gens_per_call, interpret=interpret,
        donate=donate,
    )
    chunks, rem = divmod(int(n), g)
    if chunks:
        p = loop(p, chunks)
    if rem:
        # after the loop ran, p is an internal intermediate we own
        p = multi_step_packed(p, rem, rule=rule, topology=topology,
                              donate=donate or chunks > 0)
    return p
