"""Larger-than-Life stepper: separable box-sum convolutions on the MXU.

The 3×3 families ride the VPU (bitwise SWAR / byte selects); a radius-r
box count is 2·(2r+1) MACs per cell, which is convolution work — so this
path feeds the MXU. The (2r+1)² box is separable: a (2r+1)×1 column conv
then a 1×(2r+1) row conv. Inputs are cast to bf16 on TPU (f32 elsewhere)
with f32 accumulation; counts are integers < 256 for r <= 7, so the
arithmetic is exact (models/ltl.py caps the radius accordingly).

Same halo-extension contract as every other stepper in ops/: the `_ext`
variant consumes a (h+2r, w+2r) tile with halos already materialised —
by jnp.pad here, or by depth-r ppermute exchange in parallel/sharded.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..models.ltl import LtLRule
from .stencil import Topology, _pad_mode


def _compute_dtype() -> jnp.dtype:
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


def box_sums_ext(ext: jax.Array, radius: int) -> jax.Array:
    """(h+2r, w+2r) {0,1} tile -> (h, w) f32 window sums (center included).

    Two 1-D VALID convolutions; XLA maps them onto the MXU on TPU.
    """
    r = radius
    k = 2 * r + 1
    x = ext.astype(_compute_dtype())[None, None, :, :]          # NCHW
    col = jnp.ones((1, 1, k, 1), x.dtype)
    row = jnp.ones((1, 1, 1, k), x.dtype)
    dn = ("NCHW", "OIHW", "NCHW")
    y = lax.conv_general_dilated(
        x, col, (1, 1), "VALID", dimension_numbers=dn,
        preferred_element_type=jnp.float32)
    y = lax.conv_general_dilated(
        y.astype(x.dtype), row, (1, 1), "VALID", dimension_numbers=dn,
        preferred_element_type=jnp.float32)
    return y[0, 0]


def step_ltl_ext(ext: jax.Array, rule: LtLRule) -> jax.Array:
    """One generation from a halo-extended (h+2r, w+2r) uint8 tile."""
    r = rule.radius
    state = ext[r:-r, r:-r]
    sums = box_sums_ext(ext, r)
    count = sums - (0.0 if rule.middle else state.astype(jnp.float32))
    alive = state.astype(bool)
    (b1, b2), (s1, s2) = rule.born, rule.survive
    born = (~alive) & (count >= b1) & (count <= b2)
    keep = alive & (count >= s1) & (count <= s2)
    return (born | keep).astype(jnp.uint8)


@partial(jax.jit, static_argnames=("rule", "topology"), donate_argnames=("state",))
def step_ltl(state: jax.Array, *, rule: LtLRule,
             topology: Topology = Topology.TORUS) -> jax.Array:
    """One generation on an unpacked (H, W) uint8 binary grid."""
    return step_ltl_ext(jnp.pad(state, rule.radius, **_pad_mode(topology)), rule)


@partial(jax.jit, static_argnames=("rule", "topology"), donate_argnames=("state",))
def multi_step_ltl(
    state: jax.Array,
    n: jax.Array,
    *,
    rule: LtLRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations in one jitted fori_loop."""
    body = lambda _, s: step_ltl_ext(
        jnp.pad(s, rule.radius, **_pad_mode(topology)), rule
    )
    return jax.lax.fori_loop(0, n, body, state)
