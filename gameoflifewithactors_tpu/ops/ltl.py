"""Larger-than-Life stepper: log-tree sliding-window sums on the VPU.

A radius-r box count is a separable (2r+1)-wide window sum per axis. The
first design here expressed that as two 1-D convolutions aimed at the MXU;
measured on a real v5e it ran at 1.2e8 cell-updates/s — ~50x slower than
the byte-stencil Generations path on the same chip, because XLA's TPU conv
lowering mangles the degenerate 1-channel layout. A (2r+1)-tap conv is not
MXU-shaped work (the systolic array wants 128x128 contractions), so this
module uses the idiomatic vector answer instead: a doubling tree of shifted
partial sums. Window sums of width k cost ~2·log2(k) full-array integer
adds per axis, all static slices that XLA fuses into a few VPU passes —
exact in int32, HBM-bound, and nearly independent of the radius.

Same halo-extension contract as every other stepper in ops/: the `_ext`
variant consumes a (h+2r, w+2r) tile with halos already materialised —
by jnp.pad here, or by depth-r ppermute exchange in parallel/sharded.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..models.ltl import LtLRule
from ._jit import optionally_donated
from .stencil import Topology, _pad_mode


def sliding_sum(x: jax.Array, k: int, axis: int) -> jax.Array:
    """Width-``k`` sliding window sum along ``axis`` (VALID: output length
    ``x.shape[axis] - k + 1``) via a doubling tree of shifted adds.

    Builds power-of-two window sums s_{2m}[i] = s_m[i] + s_m[i+m], then
    composes k from its binary expansion — ~2·log2(k) adds total instead
    of k-1, every operand a static slice of the same array.
    """
    n = x.shape[axis]
    if not 1 <= k <= n:
        raise ValueError(f"window {k} outside [1, {n}]")
    pows = {1: x}
    m = 1
    while 2 * m <= k:
        s = pows[m]
        length = s.shape[axis] - m
        pows[2 * m] = (
            lax.slice_in_dim(s, 0, length, axis=axis)
            + lax.slice_in_dim(s, m, m + length, axis=axis)
        )
        m *= 2
    out_len = n - k + 1
    acc = None
    offset = 0
    for p in sorted(pows, reverse=True):  # greedy binary decomposition of k
        while k - offset >= p:
            piece = lax.slice_in_dim(pows[p], offset, offset + out_len, axis=axis)
            acc = piece if acc is None else acc + piece
            offset += p
    return acc


def box_sums_ext(ext: jax.Array, radius: int) -> jax.Array:
    """(h+2r, w+2r) {0,1} tile -> (h, w) int32 window sums (center included).

    Two separable log-tree passes; counts <= (2r+1)^2 are exact in int32.
    """
    k = 2 * radius + 1
    x = ext.astype(jnp.int32)
    return sliding_sum(sliding_sum(x, k, axis=0), k, axis=1)


def diamond_sums_ext(ext: jax.Array, radius: int) -> jax.Array:
    """(h+2r, w+2r) {0,1} tile -> (h, w) int32 von Neumann (|dx|+|dy| <= r)
    window sums, center included.

    The diamond is not separable, but per-row it is still an interval whose
    half-width a = r - |dv| varies with the row offset — so one prefix-sum
    pass along the row axis turns every row's contribution into a
    two-slice difference, and the vertical assembly is 2r+1 adds. All
    static slices; exact in int32.
    """
    r = radius
    h, w = ext.shape[0] - 2 * r, ext.shape[1] - 2 * r
    pref = jnp.pad(jnp.cumsum(ext.astype(jnp.int32), axis=1), ((0, 0), (1, 0)))
    total = None
    for dv in range(-r, r + 1):
        a = r - abs(dv)
        rows = lax.slice_in_dim(pref, r + dv, r + dv + h, axis=0)
        # interior column j maps to ext column j+r; the width-(2a+1)
        # interval [j+r-a, j+r+a] is pref[j+r+a+1] - pref[j+r-a]
        s = (lax.slice_in_dim(rows, r + a + 1, r + a + 1 + w, axis=1)
             - lax.slice_in_dim(rows, r - a, r - a + w, axis=1))
        total = s if total is None else total + s
    return total


def step_ltl_ext(ext: jax.Array, rule: LtLRule) -> jax.Array:
    """One generation from a halo-extended (h+2r, w+2r) uint8 tile.

    ``states == 2``: the classic binary family, window sums straight over
    the 0/1 grid. ``states >= 3`` (Golly's C parameter): only state 1
    excites, births land on dead (0) cells only, and an alive cell
    failing its survival interval decays through 2..states-1 before dying
    — the Generations select applied to LtL window counts."""
    r = rule.radius
    state = ext[r:-r, r:-r]
    multistate = rule.states > 2
    src = (ext == 1).astype(jnp.uint8) if multistate else ext
    sums = (box_sums_ext(src, r) if rule.neighborhood == "M"
            else diamond_sums_ext(src, r))
    is_alive = state == 1
    count = sums - (0 if rule.middle else is_alive.astype(jnp.int32))

    def in_any(intervals):
        hit = None
        for lo, hi in intervals:
            t = (count >= lo) & (count <= hi)
            hit = t if hit is None else (hit | t)
        # an empty interval list (Golly allows e.g. empty survival) = never
        return jnp.zeros_like(state, dtype=bool) if hit is None else hit

    born = (state == 0) & in_any(rule.born_intervals)
    keep = is_alive & in_any(rule.survive_intervals)
    if not multistate:
        return (born | keep).astype(jnp.uint8)
    from .generations import decay_select

    return decay_select(state, born, keep, rule.states)


@optionally_donated("state")
def step_ltl(state: jax.Array, *, rule: LtLRule,
             topology: Topology = Topology.TORUS) -> jax.Array:
    """One generation on an unpacked (H, W) uint8 binary grid."""
    return step_ltl_ext(jnp.pad(state, rule.radius, **_pad_mode(topology)), rule)


@optionally_donated("state")
def multi_step_ltl(
    state: jax.Array,
    n: jax.Array,
    *,
    rule: LtLRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations in one jitted fori_loop."""
    body = lambda _, s: step_ltl_ext(
        jnp.pad(s, rule.radius, **_pad_mode(topology)), rule
    )
    return jax.lax.fori_loop(0, n, body, state)
