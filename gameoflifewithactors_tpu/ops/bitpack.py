"""Bit-packing between unpacked (H, W) uint8 grids and (H, W/32) uint32 words.

Layout contract (shared by the SWAR step, halo exchange, and the Pallas
kernel): bit ``i`` (LSB = bit 0) of word ``j`` in row ``r`` holds the cell at
``(r, 32*j + i)``. Packing to 1 bit/cell cuts HBM traffic 8× vs. the
1-byte/cell unpacked path and lets one bitwise op process 32 cells — the
lever BASELINE.md identifies for the ≥1e9 cell-updates/s/chip target
(uint32, not uint64, because JAX runs with x64 disabled by default and TPU
VPU lanes are 32-bit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

WORD = 32  # cells per packed word

_BIT_WEIGHTS = (np.uint32(1) << np.arange(WORD, dtype=np.uint32)).astype(np.uint32)


def packed_width(width: int) -> int:
    if width % WORD != 0:
        raise ValueError(f"grid width {width} must be a multiple of {WORD}")
    return width // WORD


def pack(state: jax.Array) -> jax.Array:
    """(H, W) uint8 in {0,1} -> (H, W/32) uint32."""
    h, w = state.shape
    wp = packed_width(w)
    bits = state.reshape(h, wp, WORD).astype(jnp.uint32)
    return jnp.sum(bits * _BIT_WEIGHTS, axis=-1, dtype=jnp.uint32)


def pack_np(state: np.ndarray) -> np.ndarray:
    """Host-side (H, W) uint8 -> (H, W/32) uint32 pack, same layout as
    :func:`pack`.

    Packing on the host before `device_put` ships 1 bit/cell instead of
    1 byte/cell — on a tunneled TPU the 8× smaller transfer matters more
    than the pack cost itself.
    """
    h, w = state.shape
    wp = packed_width(w)
    by = np.packbits(np.ascontiguousarray(state, dtype=np.uint8),
                     axis=-1, bitorder="little")
    # bytes k..k+3 of a row are bits 0..31 of word k/4 -> little-endian u32
    return by.reshape(h, wp, 4).view(np.dtype("<u4")).reshape(h, wp)


def unpack_np(packed: np.ndarray) -> np.ndarray:
    """Host-side (H, W/32) uint32 -> (H, W) uint8, inverse of :func:`pack_np`.

    Lets checkpoint/IO paths stay in the 1-bit/cell layout end to end —
    at 65536² the packed words are 512 MB where the dense grid is 4.3 GB.
    """
    h, wp = packed.shape
    by = np.ascontiguousarray(packed, dtype="<u4").view(np.uint8).reshape(h, wp * 4)
    return np.unpackbits(by, axis=-1, bitorder="little")


def unpack(packed: jax.Array) -> jax.Array:
    """(H, W/32) uint32 -> (H, W) uint8 in {0,1}."""
    h, wp = packed.shape
    bits = (packed[:, :, None] >> jnp.arange(WORD, dtype=jnp.uint32)) & 1
    return bits.astype(jnp.uint8).reshape(h, wp * WORD)


def row_population(packed: jax.Array) -> jax.Array:
    """Per-row live-cell counts, (H,) uint32.

    Row partials stay exact in uint32 (a row of 65536 cells ≤ 2^16); the
    grand total is summed on the host in Python ints so 65536² grids
    (4.3e9 cells, overflowing uint32) stay exact — see :func:`population`.
    """
    return jnp.sum(jax.lax.population_count(packed), axis=-1, dtype=jnp.uint32)


def population(packed: jax.Array) -> int:
    """Exact total live-cell count (host-side Python int)."""
    return int(np.asarray(row_population(packed)).sum(dtype=np.uint64))
