"""ops subpackage."""
