"""Opt-in buffer donation for functional op entry points.

Round-1 hardware runs exposed a footgun: the ``step_*``/``multi_step_*``
functions were jitted with their state argument *always* donated. Donation
is a no-op on the CPU backend (so the test suite never noticed), but on TPU
the caller's array is really consumed — any caller that touched its input
again (compare-against-oracle harnesses, autotune sweeps re-seeding from
one array) died with ``INVALID_ARGUMENT: TPU backend error`` at the next
fetch. Functional APIs must not destroy their arguments by default.

This module keeps donation available — the Engine owns its state buffer
and wants in-place double-buffering (at 65536² packed that is the
difference between 512 MB and 1 GB of HBM) — but as an explicit
``donate=True`` opt-in. Two jitted instances are built per function
(jax.jit donation is a trace-time property); the wrapper picks one.

Being the choke point every ``step_*``/``multi_step_*`` call flows
through also makes this the natural place to *see* compiles: each call
routes through :func:`obs.compile.tracked_call`, which records a
CompileEvent (runner name, shape/dtype signature, wall seconds) whenever
the call grew the jit cache — the data that lets StepMetrics stop
reporting first-tick compile time as step time.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence, Tuple

import jax

from ..obs import compile as _obs_compile


def tracked_jit(fun: Callable = None, *, runner: str = None,
                **jit_kwargs) -> Callable:
    """``jax.jit`` that stays inside the compile-accounting choke point.

    The runner builders (parallel/sharded.py, parallel/batched.py,
    ops/sparse.py, the pallas loop builders) historically returned bare
    ``jax.jit`` objects — their compiles never became CompileEvents, so
    a sharded engine's first tick hid seconds of XLA time inside
    StepMetrics and the retrace sanitizer was blind to the whole SPMD
    family. This wrapper is the fix and the lint rule GOL006's
    prescription: same signature surface as ``jax.jit`` (kwargs pass
    through), but every call routes through
    :func:`obs.compile.tracked_call`.

    Usable directly or as a decorator factory::

        run = tracked_jit(_run, runner="sharded.multi_step_packed",
                          donate_argnums=(0,) if donate else ())

        @tracked_jit(runner="sparse_many", donate_argnums=(0, 1))
        def sparse_many(padded, active, n): ...

    ``.jitted`` exposes the underlying jit and ``.lower`` forwards to it,
    so introspection sites (utils/profiling.measured_halo_bytes_per_gen,
    AOT export) keep working on wrapped runners.
    """
    if fun is None:
        return lambda f: tracked_jit(f, runner=runner, **jit_kwargs)
    jitted = jax.jit(fun, **jit_kwargs)
    name = runner or getattr(fun, "__name__", None) or "jit"
    donated = bool(jit_kwargs.get("donate_argnums")
                   or jit_kwargs.get("donate_argnames"))

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        return _obs_compile.tracked_call(jitted, name, args, kwargs,
                                         donated=donated)

    wrapper.__name__ = name
    wrapper.jitted = jitted
    wrapper.lower = jitted.lower  # introspection passthrough
    return wrapper


def optionally_donated(
    donate_arg: str, static: Tuple[str, ...] = ("rule", "topology")
) -> Callable:
    """Decorator: jit ``fun`` with ``donate=False`` (default, safe) or
    ``donate=True`` (caller hands over ``donate_arg``'s buffer)."""

    def deco(fun: Callable) -> Callable:
        plain = jax.jit(fun, static_argnames=static)
        donating = jax.jit(fun, static_argnames=static, donate_argnames=(donate_arg,))
        name = fun.__name__

        @functools.wraps(fun)
        def wrapper(*args, donate: bool = False, **kwargs):
            return _obs_compile.tracked_call(
                donating if donate else plain, name, args, kwargs,
                donated=donate)

        # the jit objects themselves, for .lower()/.trace() introspection
        wrapper.jitted = plain
        wrapper.jitted_donating = donating
        return wrapper

    return deco
