"""Opt-in buffer donation for functional op entry points.

Round-1 hardware runs exposed a footgun: the ``step_*``/``multi_step_*``
functions were jitted with their state argument *always* donated. Donation
is a no-op on the CPU backend (so the test suite never noticed), but on TPU
the caller's array is really consumed — any caller that touched its input
again (compare-against-oracle harnesses, autotune sweeps re-seeding from
one array) died with ``INVALID_ARGUMENT: TPU backend error`` at the next
fetch. Functional APIs must not destroy their arguments by default.

This module keeps donation available — the Engine owns its state buffer
and wants in-place double-buffering (at 65536² packed that is the
difference between 512 MB and 1 GB of HBM) — but as an explicit
``donate=True`` opt-in. Two jitted instances are built per function
(jax.jit donation is a trace-time property); the wrapper picks one.

Being the choke point every ``step_*``/``multi_step_*`` call flows
through also makes this the natural place to *see* compiles: each call
routes through :func:`obs.compile.tracked_call`, which records a
CompileEvent (runner name, shape/dtype signature, wall seconds) whenever
the call grew the jit cache — the data that lets StepMetrics stop
reporting first-tick compile time as step time.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax

from ..obs import compile as _obs_compile


def tracked_jit(fun: Callable = None, *, runner: str = None,
                **jit_kwargs) -> Callable:
    """``jax.jit`` that stays inside the compile-accounting choke point.

    The runner builders (parallel/sharded.py, parallel/batched.py,
    ops/sparse.py, the pallas loop builders) historically returned bare
    ``jax.jit`` objects — their compiles never became CompileEvents, so
    a sharded engine's first tick hid seconds of XLA time inside
    StepMetrics and the retrace sanitizer was blind to the whole SPMD
    family. This wrapper is the fix and the lint rule GOL006's
    prescription: same signature surface as ``jax.jit`` (kwargs pass
    through), but every call routes through
    :func:`obs.compile.tracked_call`.

    Usable directly or as a decorator factory::

        run = tracked_jit(_run, runner="sharded.multi_step_packed",
                          donate_argnums=(0,) if donate else ())

        @tracked_jit(runner="sparse_many", donate_argnums=(0, 1))
        def sparse_many(padded, active, n): ...

    ``.jitted`` exposes the underlying jit and ``.lower`` forwards to it,
    so introspection sites (utils/profiling.measured_halo_bytes_per_gen,
    AOT export) keep working on wrapped runners.
    """
    if fun is None:
        return lambda f: tracked_jit(f, runner=runner, **jit_kwargs)
    jitted = jax.jit(fun, **jit_kwargs)
    name = runner or getattr(fun, "__name__", None) or "jit"
    donated = bool(jit_kwargs.get("donate_argnums")
                   or jit_kwargs.get("donate_argnames"))

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        return _obs_compile.tracked_call(jitted, name, args, kwargs,
                                         donated=donated)

    wrapper.__name__ = name
    wrapper.jitted = jitted
    wrapper.lower = jitted.lower  # introspection passthrough
    return wrapper


def optionally_donated(
    donate_arg: str, static: Tuple[str, ...] = ("rule", "topology")
) -> Callable:
    """Decorator: jit ``fun`` with ``donate=False`` (default, safe) or
    ``donate=True`` (caller hands over ``donate_arg``'s buffer)."""

    def deco(fun: Callable) -> Callable:
        plain = jax.jit(fun, static_argnames=static)
        donating = jax.jit(fun, static_argnames=static, donate_argnames=(donate_arg,))
        name = fun.__name__

        @functools.wraps(fun)
        def wrapper(*args, donate: bool = False, **kwargs):
            return _obs_compile.tracked_call(
                donating if donate else plain, name, args, kwargs,
                donated=donate)

        # the jit objects themselves, for .lower()/.trace() introspection
        wrapper.jitted = plain
        wrapper.jitted_donating = donating
        return wrapper

    return deco


# -- runner-builder registry (the HLO contract gate's enumeration) -----------
#
# Being the jit choke point makes this module the one place every runner
# family already imports, so the registry of *contract factories* lives
# here too: each builder module (parallel/sharded.py, parallel/batched.py,
# ops/packed.py, ops/stencil.py) registers zero-arg factories that the
# contract gate (analysis/contracts.py, scripts/contract_check.py) calls
# to obtain a lowerable runner plus the invariants to prove about it —
# donation really applied, zero host transfers, collective traffic equal
# to the closed-form halo model. Registration must stay import-cheap:
# factories build meshes and example grids only when the gate runs them.


@dataclasses.dataclass(frozen=True)
class BuiltRunner:
    """A contract factory's product.

    ``lowerable`` must expose ``.lower(*example_args, **example_kwargs)``
    — tracked_jit wrappers, optionally_donated ``.jitted_donating``
    instances, and raw ``jax.jit`` objects all do.

    ``expected_collective_bytes`` is the closed-form interconnect model
    (ghost_exchange_bytes / deep_exchange_bytes) the compiled HLO's
    collective-permute byte total must equal *exactly* — byte accounting
    is invariant under XLA's collective-combining passes, so this is a
    hard contract. Instruction *counts* are not invariant (see
    utils/profiling.collective_permute_count), so they gate as pinned
    manifest measurements with jax-version staleness instead. ``None``
    means no byte model applies (single-device runners: the contract is
    then zero collectives).

    ``mesh``/``out_spec`` let the gate's fault-injection seam wrap the
    runner with one extra ppermute (GOLTPU_CONTRACT_INJECT) to prove the
    gate actually fails closed; single-device runners leave them None.

    ``require_gather`` makes the gate insist the compiled HLO resolves
    neighbors by gather (≥1 gather/dynamic-gather op). The paged pool
    runner sets it: its whole point is that halos come from page-table
    *indexing*, not per-slot copies, and a refactor that silently turned
    the gather into unrolled copies would retrace on every allocation.
    """
    lowerable: Callable
    example_args: tuple
    example_kwargs: dict = dataclasses.field(default_factory=dict)
    donated_argnums: Tuple[int, ...] = ()
    expected_collective_bytes: Optional[int] = None
    collective_model: str = ""
    mesh: Optional[object] = None
    out_spec: Optional[object] = None
    require_gather: bool = False


@dataclasses.dataclass(frozen=True)
class BuilderSpec:
    name: str
    factory: Callable[[], BuiltRunner]
    tags: Tuple[str, ...] = ()


BUILDERS: Dict[str, BuilderSpec] = {}


def register_builder(name: str, factory: Callable = None, *,
                     tags: Sequence[str] = ()):
    """Register a zero-arg contract factory under ``name`` (usable as a
    decorator factory or called directly). Duplicate names are refused:
    the manifest keys on them, so a silent overwrite would let one
    runner's contracts mask another's."""
    if factory is None:
        return lambda f: register_builder(name, f, tags=tags)
    if name in BUILDERS:
        raise ValueError(f"duplicate builder registration: {name!r}")
    BUILDERS[name] = BuilderSpec(name=name, factory=factory, tags=tuple(tags))
    return factory
