"""Dense stepper for the Generations (multi-state) rule family.

Same fused-stencil shape as ops/stencil.py — separable window sum over the
*alive plane* (state == 1; dying cells do not excite neighbors), then a
branch-free next-state select. One byte per cell; states up to 256. All
`jnp.where` chains lower to VPU selects, no gathers. The halo-extended
variant feeds the sharded runner (parallel/sharded.py) exactly like the
binary paths, so multi-state universes shard over a mesh with the same
two-phase ppermute halo exchange.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models.generations import GenRule
from ._jit import optionally_donated
from .stencil import Topology, _pad_mode, neighbor_counts_ext


def decay_select(state: jax.Array, born: jax.Array, keep: jax.Array,
                 states: int) -> jax.Array:
    """Branch-free multi-state transition shared by the Generations and
    C>=3 LtL families: dead -> 1 iff born, alive -> 1 iff keep, everything
    else counts up and states-1 wraps to 0 (dying cells decay; an alive
    cell failing survival starts decaying at 2). The increment runs in
    int32 so ``states == 256`` (the uint8 ceiling) cannot overflow the
    Python-scalar-vs-uint8 cast."""
    aged = ((state.astype(jnp.int32) + 1) % states).astype(jnp.uint8)
    return jnp.where(
        state == 0,
        jnp.where(born, jnp.uint8(1), jnp.uint8(0)),
        jnp.where((state == 1) & keep, jnp.uint8(1), aged),
    ).astype(jnp.uint8)


def step_generations_ext(ext: jax.Array, rule: GenRule) -> jax.Array:
    """One generation from a halo-extended (h+2, w+2) uint8 tile."""
    state = ext[1:-1, 1:-1]
    # only state 1 excites: count over the alive plane with the shared stencil
    counts = neighbor_counts_ext((ext == 1).astype(jnp.uint8)).astype(jnp.uint16)
    born = ((jnp.uint16(rule.birth_mask) >> counts) & 1).astype(bool)
    keep = ((jnp.uint16(rule.survive_mask) >> counts) & 1).astype(bool)
    return decay_select(state, born, keep, rule.states)


@optionally_donated("state")
def step_generations(
    state: jax.Array, *, rule: GenRule, topology: Topology = Topology.TORUS
) -> jax.Array:
    """One generation on an unpacked (H, W) uint8 multi-state grid."""
    return step_generations_ext(jnp.pad(state, 1, **_pad_mode(topology)), rule)


@optionally_donated("state")
def multi_step_generations(
    state: jax.Array,
    n: jax.Array,
    *,
    rule: GenRule,
    topology: Topology = Topology.TORUS,
) -> jax.Array:
    """``n`` generations in one jitted fori_loop (no host round-trips)."""
    body = lambda _, s: step_generations_ext(
        jnp.pad(s, 1, **_pad_mode(topology)), rule
    )
    return jax.lax.fori_loop(0, n, body, state)
