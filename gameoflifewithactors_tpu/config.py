"""Simulation configuration + CLI parsing (SURVEY.md §6 'Config/flag system').

The reference hardcodes grid size and seed in ``Program`` [RECON]; here
every knob the framework has is a dataclass field with a CLI flag, and the
rule string parser is a first-class feature (any "B…/S…" rule, plus named
rules). ``SimulationConfig.build()`` assembles the whole stack —
coordinator, mesh, renderer, metrics — so the CLI and library users share
one construction path.
"""

from __future__ import annotations

import argparse
import dataclasses
import re
import sys
from typing import Optional, Tuple

from .ops.stencil import Topology


@dataclasses.dataclass
class SimulationConfig:
    height: int = 64
    width: int = 64
    rule: str = "B3/S23"
    topology: str = "torus"                 # torus | dead
    seed: Optional[str] = "glider"          # pattern name, @file.rle, or None
    random_fill: Optional[float] = None     # Bernoulli p (overrides seed)
    seed_origin: Optional[Tuple[int, int]] = None
    rng_seed: int = 0
    backend: str = "auto"                   # auto | packed | dense | pallas | sparse
    gens_per_exchange: int = 1              # sharded packed: halo depth G, exchange every G gens
    sparse_tile: Optional[Tuple[int, int]] = None   # (rows, cols), cols % 32 == 0
    sparse_capacity: Optional[int] = None   # max active tiles before dense fallback
    mesh: Optional[str] = None              # None | "auto" | "bands" | "2x4"
    steps: int = 100
    render_every: int = 1
    view_height: int = 40
    view_width: int = 80
    rate_hz: Optional[float] = None
    metrics: Optional[str] = None           # "jsonl" | "csv:PATH" | None
    track_population: bool = False
    checkpoint: Optional[str] = None        # save path (written at end)
    resume: Optional[str] = None            # checkpoint to resume from
    supervise: bool = False                 # restart-with-rollback loop (resilience/)
    checkpoint_every: int = 100             # supervised: auto-checkpoint cadence
    max_restarts: int = 5                   # supervised: circuit-breaker threshold
    ppm: Optional[str] = None               # final-frame / spacetime PPM path
    ppm_every: int = 0                      # full-res frame sequence cadence
    save_rle: Optional[str] = None          # final state as RLE (binary rules)
    telemetry_out: Optional[str] = None     # RunReport JSON path (obs/)
    stall_deadline: Optional[float] = None  # watchdog deadline seconds
    serve_metrics: Optional[int] = None     # Prometheus /metrics port (obs/)
    flight_dump: Optional[str] = None       # flight-recorder dump path;
    #                                         default <telemetry_out>.flight.jsonl
    device_poll: Optional[float] = None     # device-sampler interval seconds
    profile_sample: Optional[float] = None  # sampling-profiler period seconds
    cache_dir: Optional[str] = None         # warm-start cache root (aot/);
    #                                         None = GOLTPU_CACHE_DIR env or
    #                                         ~/.cache/gameoflifewithactors_tpu

    # -- assembly ------------------------------------------------------------

    def build_mesh(self):
        from .parallel import mesh as mesh_lib

        if self.mesh is None:
            return None
        if self.mesh == "auto":
            return mesh_lib.make_mesh()
        if self.mesh == "bands":
            # (n, 1) row bands: the layout the native pallas runners need
            # (full-width bands; backend 'auto' then picks the kernel on
            # TPU for eligible rules/shapes)
            import jax

            return mesh_lib.make_mesh((len(jax.devices()), 1))
        try:
            shape = _parse_geometry(self.mesh)
        except argparse.ArgumentTypeError:
            raise ValueError(
                f"--mesh must be 'auto', 'bands', or like '2x4', "
                f"got {self.mesh!r}"
            ) from None
        return mesh_lib.make_mesh(shape)

    def build_metrics(self):
        from .utils import metrics as metrics_lib

        if self.metrics is None:
            return None
        if self.metrics == "jsonl":
            return metrics_lib.MetricsLogger(metrics_lib.JsonlSink(sys.stderr))
        if self.metrics.startswith("csv:"):
            f = open(self.metrics[4:], "w", newline="")
            return metrics_lib.MetricsLogger(metrics_lib.CsvSink(f))
        raise ValueError(f"--metrics must be 'jsonl' or 'csv:PATH', got {self.metrics!r}")

    def build_sparse_opts(self) -> Optional[dict]:
        from .ops import bitpack

        opts = {}
        if self.sparse_tile is not None:
            rows, cols = self.sparse_tile
            if cols % bitpack.WORD:
                raise ValueError(
                    f"--sparse-tile columns must be a multiple of {bitpack.WORD}, got {cols}"
                )
            opts["tile_rows"] = rows
            opts["tile_words"] = cols // bitpack.WORD
        if self.sparse_capacity is not None:
            opts["capacity"] = self.sparse_capacity
        return opts or None

    def build(self):
        """Construct the full (coordinator, scheduler) stack."""
        from .aot import cache as aot_cache
        from .coordinator import GridCoordinator
        from .models import seeds as seeds_lib
        from .scheduler import TickScheduler
        from .utils import checkpoint as ckpt_lib

        # before any engine exists, so an explicit --cache-dir governs
        # every compile of the run (Engine re-ensures idempotently)
        aot_cache.ensure_persistent_cache(self.cache_dir)
        topology = Topology(self.topology)
        mesh = self.build_mesh()
        if self.resume:
            engine = ckpt_lib.load_engine(self.resume, mesh=mesh, backend=self.backend)
            coordinator = GridCoordinator.from_engine(
                engine,
                track_population=self.track_population,
                metrics=self.build_metrics(),
                view_shape=(self.view_height, self.view_width),
            )
        else:
            # random_fill overrides the default seed (only an *explicitly*
            # conflicting combination should error, and GridCoordinator
            # can't tell a default 'glider' from a requested one)
            seed = None if self.random_fill is not None else self.seed
            if isinstance(seed, str) and seed.startswith("@"):
                seed = seeds_lib.from_rle(open(seed[1:]).read())
            coordinator = GridCoordinator(
                (self.height, self.width),
                self.rule,
                seed=seed,
                seed_origin=self.seed_origin,
                random_fill=self.random_fill,
                rng_seed=self.rng_seed,
                topology=topology,
                mesh=mesh,
                backend=self.backend,
                sparse_opts=self.build_sparse_opts(),
                gens_per_exchange=self.gens_per_exchange,
                track_population=self.track_population,
                metrics=self.build_metrics(),
                view_shape=(self.view_height, self.view_width),
            )
        scheduler = TickScheduler(
            coordinator,
            rate_hz=self.rate_hz,
            generations_per_tick=max(1, self.render_every),
        )
        return coordinator, scheduler


def _parse_geometry(text: str) -> Tuple[int, int]:
    m = re.fullmatch(r"(\d+)x(\d+)", text)
    if not m:
        raise argparse.ArgumentTypeError(f"expected HxW like '1024x1024', got {text!r}")
    return int(m.group(1)), int(m.group(2))


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gameoflifewithactors_tpu",
        description="TPU-native Game of Life (capabilities of rikace/GameOfLifeWithActors)",
    )
    p.add_argument("--grid", type=_parse_geometry, default=(64, 64), metavar="HxW",
                   help="grid size, e.g. 1024x1024 (default 64x64, the reference's size)")
    p.add_argument("--rule", default="B3/S23",
                   help="B/S rule string or name (conway, highlife, 'day & night', ...)")
    p.add_argument("--topology", choices=[t.value for t in Topology], default="torus")
    p.add_argument("--seed", default="glider",
                   help="pattern name, @file.rle, 'random', or 'empty'")
    p.add_argument("--random-p", type=float, default=0.5, help="fill prob for --seed random")
    p.add_argument("--seed-at", type=_parse_geometry, default=None, metavar="RxC",
                   help="pattern top-left placement (default: centered)")
    p.add_argument("--rng-seed", type=int, default=0)
    p.add_argument("--backend", choices=["auto", "packed", "dense", "pallas", "sparse"],
                   default="auto")
    p.add_argument("--gens-per-exchange", type=int, default=1, metavar="G",
                   help="sharded packed backend: exchange a depth-G halo every "
                        "G generations instead of 1-deep every generation "
                        "(communication-avoiding; bit-exact for G <= 32)")
    p.add_argument("--sparse-tile", type=_parse_geometry, default=None, metavar="RxC",
                   help="sparse backend tile size in cells; C %% 32 == 0 "
                        "(default: auto-scaled so the activity map stays small; "
                        "32x128 for small grids)")
    p.add_argument("--sparse-capacity", type=int, default=None, metavar="N",
                   help="sparse backend: max active tiles per step before dense fallback")
    p.add_argument("--mesh", default=None,
                   help="'auto' (all devices, 2D tiles), 'bands' (all "
                        "devices as (N, 1) full-width row bands — the "
                        "layout the native pallas runners use), or "
                        "'NXxNY'; default single-device")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--render", choices=["off", "live", "final"], default="off")
    p.add_argument("--render-every", type=int, default=1, metavar="N",
                   help="draw every N generations in live mode")
    p.add_argument("--view", type=_parse_geometry, default=(40, 80), metavar="HxW",
                   help="max console view size (grid is downsampled to fit)")
    p.add_argument("--rate", type=float, default=None, metavar="HZ",
                   help="tick rate limit; default unthrottled")
    p.add_argument("--metrics", default=None, help="'jsonl' (stderr) or 'csv:PATH'")
    p.add_argument("--population", action="store_true", help="track live-cell count")
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="write final state here")
    p.add_argument("--supervise", action="store_true",
                   help="run under a restart supervisor (resilience/): "
                        "auto-checkpoint to --checkpoint every "
                        "--checkpoint-every generations and, on a "
                        "coordinator exception or watchdog stall, restore "
                        "the last checkpoint and replay with capped "
                        "exponential backoff (see README 'Resilience & "
                        "soak'). Requires --checkpoint PATH")
    p.add_argument("--checkpoint-every", type=int, default=100, metavar="N",
                   help="with --supervise: checkpoint cadence in "
                        "generations (default 100)")
    p.add_argument("--max-restarts", type=int, default=5, metavar="N",
                   help="with --supervise: consecutive failed chunks "
                        "before the circuit breaker gives up (default 5)")
    p.add_argument("--ppm", default=None, metavar="PATH",
                   help="write the final grid (2D rules) or the full "
                        "spacetime diagram (1D W-rules) as a PPM image")
    p.add_argument("--ppm-every", type=int, default=0, metavar="N",
                   help="with --ppm PATH: write a FULL-resolution frame "
                        "every N generations as PATH-stem_NNNNNN.ppm "
                        "(ffmpeg-ready sequence; the final single --ppm "
                        "write is skipped — the last frame is in the "
                        "sequence). Under --render live/--rate/--metrics "
                        "the sequence follows the tick cadence "
                        "(--render-every) instead")
    p.add_argument("--save-rle", default=None, metavar="PATH",
                   help="write the final state as standard RLE (Golly-"
                        "compatible; binary rules only — round-trips with "
                        "--seed @file.rle)")
    p.add_argument("--telemetry-out", default=None, metavar="PATH",
                   help="write a RunReport JSON here at end of run: host "
                        "spans (dispatch/sync/readback), jit compile "
                        "events, StepMetrics, halo-byte figures, stalls "
                        "(see README 'Observability'; inspect with the "
                        "'report' subcommand)")
    p.add_argument("--cache-dir", default=None, metavar="PATH",
                   help="warm-start cache root (persistent XLA compile "
                        "cache + AOT registry; README 'Warm start'). "
                        "Default: $GOLTPU_CACHE_DIR, else "
                        "~/.cache/gameoflifewithactors_tpu; pass '' to "
                        "disable caching for this run")
    p.add_argument("--serve-metrics", type=int, default=None, metavar="PORT",
                   help="serve Prometheus text-format metrics (registry "
                        "counters + live HBM gauges) at "
                        "http://0.0.0.0:PORT/metrics while the run steps; "
                        "0 picks an ephemeral port (printed to stderr). "
                        "Also honored via $GOLTPU_METRICS_PORT")
    p.add_argument("--flight-dump", default=None, metavar="PATH",
                   help="flight-recorder crash-report path (JSONL): the "
                        "last N StepMetrics/spans/compile events + a "
                        "registry snapshot, written on watchdog stall, "
                        "coordinator exception, or SIGTERM/SIGINT. "
                        "Default with --telemetry-out: "
                        "<telemetry-out>.flight.jsonl")
    p.add_argument("--device-poll", type=float, default=None, metavar="S",
                   help="device memory sampler interval in seconds "
                        "(default 1.0, or $GOLTPU_DEVICE_POLL_S); feeds "
                        "the hbm_bytes_* gauges --serve-metrics exposes")
    p.add_argument("--profile-sample", type=float, default=None, metavar="S",
                   help="arm the always-on sampling profiler: one 200 ms "
                        "jax.profiler window every S seconds, op-class "
                        "attribution into the RunReport profile section + "
                        "profile_* gauges (off by default; also honored "
                        "via $GOLTPU_PROFILE_SAMPLE_S; the window is "
                        "capped at 10%% of S)")
    p.add_argument("--stall-deadline", type=float, default=None, metavar="S",
                   help="with --telemetry-out: flag any tick exceeding S "
                        "seconds, naming the last-completed span "
                        "(default 60; the wedged-TPU diagnostic)")
    p.add_argument("--resume", default=None, metavar="PATH",
                   help="resume from a checkpoint (the checkpoint's grid/rule/"
                        "seed/topology win; --grid/--rule/--seed/--topology are ignored)")
    p.add_argument("--list", action="store_true",
                   help="print the registered seed patterns and named rules "
                        "of every family, then exit")
    return p


def from_args(argv=None) -> "tuple[SimulationConfig, argparse.Namespace]":
    args = make_parser().parse_args(argv)
    (h, w) = args.grid
    cfg = SimulationConfig(
        height=h,
        width=w,
        rule=args.rule,
        topology=args.topology,
        seed=None if args.seed in ("random", "empty") else args.seed,
        random_fill=args.random_p if args.seed == "random" else None,
        seed_origin=args.seed_at,
        rng_seed=args.rng_seed,
        backend=args.backend,
        gens_per_exchange=args.gens_per_exchange,
        sparse_tile=args.sparse_tile,
        sparse_capacity=args.sparse_capacity,
        mesh=args.mesh,
        steps=args.steps,
        render_every=args.render_every,
        view_height=args.view[0],
        view_width=args.view[1],
        rate_hz=args.rate,
        metrics=args.metrics,
        track_population=args.population,
        checkpoint=args.checkpoint,
        resume=args.resume,
        supervise=args.supervise,
        checkpoint_every=args.checkpoint_every,
        max_restarts=args.max_restarts,
        ppm=args.ppm,
        ppm_every=args.ppm_every,
        save_rle=args.save_rle,
        telemetry_out=args.telemetry_out,
        stall_deadline=args.stall_deadline,
        serve_metrics=args.serve_metrics,
        flight_dump=args.flight_dump,
        device_poll=args.device_poll,
        profile_sample=args.profile_sample,
        cache_dir=args.cache_dir,
    )
    return cfg, args
