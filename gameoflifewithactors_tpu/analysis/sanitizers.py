"""Runtime sanitizers: the dynamic half of goltpu-lint.

The static rules (rules.py) catch what the AST can prove; these catch
the rest at run time, opt-in via ``GOLTPU_SANITIZE=1`` so production
runs pay nothing:

- **Transfer guard** — :func:`no_implicit_host_transfers` wraps the
  engine's step dispatch in ``jax.transfer_guard_device_to_host
  ("disallow")``: any *implicit* device→host readback inside the hot
  loop raises instead of silently serializing the pipeline. Paths that
  legitimately pull to host (snapshot/population readback, the sparse
  backend's per-step scalar, render/report plumbing) declare themselves
  with :func:`allow_host_transfers(reason)` — the allow-scope IS the
  documentation of every sanctioned sync point. Note the guard only
  fires where a real transfer happens (TPU/GPU); on CPU the arrays are
  host-resident and jax performs no guarded transfer, so the wiring is
  exercised by tier-1 but the teeth only bite on hardware.
- **Retrace budget** — a warmed engine (AOT-loaded or persistent-cache
  served) must never pay a real XLA compile again; PR 2 made that
  *observable* (``CompileEvent.kind == "cache_miss"``), this makes it
  *enforced*. :class:`RetraceSentinel` taps the process
  :data:`~..obs.compile.COMPILE_LOG`; :meth:`RetraceSentinel.check`
  raises :class:`RetraceError` naming the runner and shape signature
  that recompiled. ``Engine.step`` checks automatically on warmed
  engines when sanitizing; tests use :func:`retrace_budget` directly.

jax is imported lazily inside the guard scopes: this module must import
(and the lint half of the package must run) with no jax installed.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Iterator, List, Optional

from ..obs import compile as obs_compile

ENV_SANITIZE = "GOLTPU_SANITIZE"
_ENABLED_VALUES = ("1", "true", "on", "yes")


def enabled() -> bool:
    """Is the opt-in sanitizer wiring live (``GOLTPU_SANITIZE=1``)?
    Read per call, so a test can flip it with monkeypatch.setenv."""
    return os.environ.get(ENV_SANITIZE, "").strip().lower() \
        in _ENABLED_VALUES


@contextlib.contextmanager
def no_implicit_host_transfers() -> Iterator[None]:
    """Disallow implicit device→host transfers inside the scope (no-op
    unless sanitizing). Explicit fetches (``jax.device_get``) stay
    allowed — the point is catching the *silent* syncs."""
    if not enabled():
        yield
        return
    import jax

    with jax.transfer_guard_device_to_host("disallow"):
        yield


@contextlib.contextmanager
def allow_host_transfers(reason: str) -> Iterator[None]:
    """Declare a sanctioned device→host readback (snapshot, population,
    the sparse step scalar, render/report paths). ``reason`` is
    mandatory and unused at runtime — it exists so every allow-scope in
    the tree reads as its own justification."""
    if not reason:
        raise ValueError("allow_host_transfers requires a reason string")
    if not enabled():
        yield
        return
    import jax

    with jax.transfer_guard_device_to_host("allow"):
        yield


class RetraceError(AssertionError):
    """A warmed engine paid a real XLA compile (retrace budget blown)."""


class RetraceSentinel:
    """Tap the compile log; fail fast when cache_miss events exceed the
    budget. ``arm()``/``disarm()`` bracket the watched window;
    ``check()`` raises; ``misses()`` inspects."""

    def __init__(self, budget: int = 0, *, context: str = "",
                 log: Optional[obs_compile.CompileEventLog] = None):
        if budget < 0:
            raise ValueError(f"budget must be >= 0, got {budget}")
        self.budget = budget
        self.context = context
        self._log = log if log is not None else obs_compile.COMPILE_LOG
        self._events: List[obs_compile.CompileEvent] = []
        self._lock = threading.Lock()
        self._armed = False

    def _on_event(self, ev) -> None:
        # listener exceptions are swallowed by CompileEventLog.record,
        # so never raise here — tape the miss, let check() do the failing
        if getattr(ev, "cache_miss", False):
            with self._lock:
                self._events.append(ev)

    def arm(self) -> "RetraceSentinel":
        if not self._armed:
            self._armed = True
            self._log.add_listener(self._on_event)
        return self

    def disarm(self) -> None:
        if self._armed:
            self._armed = False
            self._log.remove_listener(self._on_event)

    def misses(self) -> List[obs_compile.CompileEvent]:
        with self._lock:
            return list(self._events)

    def reset(self) -> None:
        """Forget taped misses without disarming — supervised recovery
        attributes a *planned* retrace fault to its injection and re-arms
        the budget for the remainder of the run."""
        with self._lock:
            self._events.clear()

    def check(self) -> None:
        misses = self.misses()
        if len(misses) > self.budget:
            detail = "; ".join(
                f"{e.runner}({e.signature}) {e.wall_seconds:.2f}s"
                for e in misses[:4])
            more = f" (+{len(misses) - 4} more)" if len(misses) > 4 else ""
            raise RetraceError(
                f"retrace budget blown{' for ' + self.context if self.context else ''}: "
                f"{len(misses)} real XLA compile(s) after warm "
                f"(budget {self.budget}): {detail}{more} — a warmed "
                "engine recompiling means the AOT/persistent-cache key "
                "or a shape/dtype signature drifted")

    def __enter__(self) -> "RetraceSentinel":
        return self.arm()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.disarm()
        if exc_type is None:
            self.check()


@contextlib.contextmanager
def retrace_budget(budget: int = 0, *, context: str = "",
                   log: Optional[obs_compile.CompileEventLog] = None,
                   ) -> Iterator[RetraceSentinel]:
    """``with retrace_budget(): engine.step(n)`` — raises RetraceError on
    exit if more than ``budget`` real compiles landed inside the scope.
    Always live (not gated on GOLTPU_SANITIZE): the caller opting into
    the context *is* the opt-in."""
    sentinel = RetraceSentinel(budget, context=context, log=log)
    with sentinel:
        yield sentinel
