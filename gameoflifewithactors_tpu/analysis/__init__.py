"""goltpu-lint: TPU-invariant static analysis + opt-in runtime sanitizers.

Two halves with one job — *prevent* the failure classes obs/ can only
report: silent device→host transfers in hot paths, accidental retraces
of warmed runners, jit boundaries that escape compile accounting, and
lock slips in the telemetry recorders.

- :mod:`.lint` / :mod:`.rules` — the jax-free AST engine and the GOL00x
  rule set (``scripts/lint.py`` is the CLI; README "Static analysis &
  sanitizers" has the rule table and pragma syntax). Importing these
  must work on a box with no jax at all: the CI lint job runs before
  any dependency install.
- :mod:`.sanitizers` — ``GOLTPU_SANITIZE=1`` runtime checks: the
  device→host transfer guard around the engine step loop (with
  declared allow-scopes at every sanctioned readback) and the
  retrace-budget assertion over PR 2's compile-event attribution.
  jax is imported lazily inside the scopes that need it.
"""

from .lint import (  # noqa: F401
    Finding,
    LintResult,
    RULES,
    lint_paths,
    lint_source,
)
from .sanitizers import (  # noqa: F401
    ENV_SANITIZE,
    RetraceError,
    RetraceSentinel,
    allow_host_transfers,
    no_implicit_host_transfers,
    retrace_budget,
)

__all__ = [
    "Finding", "LintResult", "RULES", "lint_paths", "lint_source",
    "ENV_SANITIZE", "RetraceError", "RetraceSentinel",
    "allow_host_transfers", "no_implicit_host_transfers",
    "retrace_budget",
]
