"""Flow-sensitive AST analyses feeding rules GOL008–GOL010 — stdlib only.

The GOL001–007 rules are line-local pattern matches; the two worst bugs
in this repo's history were *flow* bugs they could not see. The PR 11
donated-buffer use-after-free was a value (``jnp.asarray(caller_numpy)``)
travelling three hops — ``__init__`` store, ``self.state`` load, donated
call — before the aliasing mattered; deadlocks live in the *order* two
locks are taken across classes, not in any single ``with``. This module
holds the def-use / graph machinery those rules need, kept separate from
rules.py so the analyses stay testable on their own and reusable (the
rules are thin adapters that turn analysis results into Findings).

Three analyses:

- :func:`donation_alias_findings` — per-module def-use tracking of
  caller-owned buffers through aliasing producers (``jnp.asarray``,
  ``jnp.array(copy=False)``, view-forwarding helpers, ``self`` attribute
  stores) into donated argument positions, plus re-reads of a name after
  it was donated. ``jnp.array(x, copy=True)`` breaks the chain — that is
  the shipped PR 11 fix and the negative fixture.
- :class:`LockGraph` — project-wide lock-acquisition graph over the
  classes of ``obs/``, ``serve/`` and ``resilience/``: nodes are
  ``Class.lock_attr``, edges are "acquired while holding" (nested
  ``with``, self-method calls under a lock, cross-object calls through
  constructor-typed attributes). Cycles and cross-class
  acquire-while-holding are the GOL009 findings.
- :func:`collect_metric_decls` / :func:`per_chip_gauge_names` — the
  constant-string metric declarations (``*.counter/gauge/histogram``)
  and the ``PER_CHIP_GAUGES`` set parsed out of ``obs/aggregate.py``,
  for GOL010's naming/membership/kind-collision checks.

Like every rule helper: heuristic on purpose, tuned for zero false
positives on this tree. When a chain cannot be proven, it is dropped —
not guessed at.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

# -- tiny shared helpers (rules.py imports these) -----------------------------


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.fori_loop' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return None


def const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


def param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])]
    names += [p.arg for p in a.args]
    names += [p.arg for p in a.kwonlyargs]
    return names


def lock_attr_types(cls: ast.ClassDef) -> Dict[str, str]:
    """``self`` attributes assigned a threading.Lock()/RLock() anywhere
    in the class (typically __init__), mapped to which kind — the
    distinction matters: re-acquiring a plain Lock self-deadlocks."""
    locks: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            d = dotted(node.value.func) or ""
            kind = d.split(".")[-1]
            if kind in ("Lock", "RLock"):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name) and t.value.id == "self":
                        locks[t.attr] = kind
    return locks


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' when ``node`` is exactly ``self.x``."""
    if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _walk_scope(root: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does NOT descend into nested function/lambda bodies:
    their parameters shadow the enclosing scope (two sibling lambdas both
    taking ``s`` share nothing), so flow facts must not leak across."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and child is not root:
                continue
            stack.append(child)


# =============================================================================
# donation aliasing (GOL008)
# =============================================================================

# what the donated-position heuristic assumes: in this codebase every
# ``donate=True`` opt-in (ops/_jit.optionally_donated, the make_* runner
# factories) donates the FIRST positional argument of the eventual call
_DONATED_POS_DEFAULT = (0,)


@dataclasses.dataclass(frozen=True)
class Alias:
    """A value proven to share the caller's buffer."""

    root: str       # the caller-owned name it aliases ("np_grid", a param)
    producer: str   # "jnp.asarray", "jnp.array(copy=False)", "helper()"
    line: int       # where the alias was made (for the message)


def _is_aliasing_call(call: ast.Call) -> Optional[str]:
    """'jnp.asarray'-style producers that may return a view of their
    first argument rather than a copy. ``jnp.array`` copies by default —
    only an explicit ``copy=False`` aliases. Returns a producer label,
    or None for copying/unknown calls."""
    d = dotted(call.func) or ""
    tail = d.split(".")[-1]
    if not call.args:
        return None
    if tail == "asarray":
        return d
    if tail == "array":
        for kw in call.keywords:
            if kw.arg == "copy" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return f"{d}(copy=False)"
        return None
    return None


def _owned_base(expr: ast.AST) -> Optional[ast.AST]:
    """Unwrap view-preserving syntax (subscripts like ``x[None]`` — numpy
    slices are views) down to the Name/self-attr whose buffer is shared."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Name) or _self_attr(expr) is not None:
        return expr
    return None


class _FnAnalysis:
    """One function's linear pass: environment of proven aliases plus
    donation events, statements visited in source order."""

    def __init__(self, owner: "_DonationAnalysis", fn: ast.AST,
                 owned: Set[str], attr_aliases: Dict[str, Alias],
                 fn_label: str):
        self.owner = owner
        self.fn = fn
        self.owned = owned              # caller-owned names (parameters)
        self.attr_aliases = attr_aliases  # class-wide: attr -> Alias
        self.fn_label = fn_label
        self.env: Dict[str, Alias] = {}
        self.donated_at: Dict[str, Tuple[int, str]] = {}  # name -> (line, callee)
        self.findings: List[Tuple[ast.AST, str]] = []

    # - alias environment ----------------------------------------------------

    def _alias_of(self, expr: ast.AST) -> Optional[Alias]:
        """The Alias a value expression carries, if provable."""
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id)
        attr = _self_attr(expr)
        if attr is not None:
            return self.attr_aliases.get(attr)
        if isinstance(expr, ast.Call):
            producer = _is_aliasing_call(expr)
            if producer is not None:
                root = self._caller_owned_root(expr.args[0])
                if root is not None:
                    return Alias(root=root, producer=producer,
                                 line=expr.lineno)
                inner = self._alias_of(expr.args[0])
                if inner is not None:  # asarray of an alias stays an alias
                    return Alias(root=inner.root, producer=inner.producer,
                                 line=inner.line)
            # one level of helper forwarding: y = prep(buf) where
            # ``def prep(x): return jnp.asarray(x)``
            fname = dotted(expr.func)
            if fname is not None:
                idx = self.owner.forwarders.get(fname.split(".")[-1])
                if idx is not None and idx < len(expr.args):
                    root = self._caller_owned_root(expr.args[idx])
                    if root is not None:
                        return Alias(
                            root=root, line=expr.lineno,
                            producer=f"{fname}() (returns an alias of "
                                     f"its argument)")
        return None

    def _caller_owned_root(self, expr: ast.AST) -> Optional[str]:
        """Name of the caller-owned buffer ``expr`` shares, if any."""
        base = _owned_base(expr)
        if base is None:
            return None
        if isinstance(base, ast.Name):
            if base.id in self.owned:
                return base.id
            inner = self.env.get(base.id)
            return inner.root if inner else None
        return None

    # - donation sites -------------------------------------------------------

    def _donated_positions(self, call: ast.Call) -> Tuple[int, ...]:
        """Which positional args of this call hand their buffer to XLA."""
        fname = dotted(call.func)
        tail = (fname or "").split(".")[-1]
        # explicit per-call opt-in: f(state, n, donate=True). On a
        # ``make_*`` factory (or a local alias of one) the flag
        # configures the *returned* runner (the assignment pass tracks
        # that), not this call's args.
        if not tail.startswith("make_") \
                and tail not in self.owner.factory_aliases:
            for kw in call.keywords:
                if kw.arg == "donate" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    return _DONATED_POS_DEFAULT
        # a callable known to donate (factory built with donate=True, or
        # jit with constant donate_argnums)
        if fname is not None:
            pos = self.owner.donating_callables.get(fname)
            if pos:
                return pos
        return ()

    # - the walk -------------------------------------------------------------

    def run(self) -> None:
        body = self.fn.body if not isinstance(self.fn, ast.Lambda) else []
        for stmt in body:
            self._stmt(stmt)
        self._check_reads_after_donation()

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not self.fn:
            return  # nested scope: analyzed on its own
        if isinstance(node, ast.Assign):
            self._visit_calls(node.value)
            alias = self._alias_of(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if alias is not None:
                        self.env[t.id] = alias
                    else:
                        self.env.pop(t.id, None)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                self._visit_calls(node.value)
            return
        if isinstance(node, ast.Call):
            self._check_call(node)
        for child in ast.iter_child_nodes(node):
            self._stmt(child)

    def _visit_calls(self, node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            return  # its own scope: param names shadow ours
        for sub in _walk_scope(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub)

    def _check_call(self, call: ast.Call) -> None:
        positions = self._donated_positions(call)
        if not positions:
            return
        callee = dotted(call.func) or "<call>"
        for pos in positions:
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            alias = self._alias_of(arg)
            if alias is not None:
                self.findings.append((call, (
                    f"donated argument of `{callee}` aliases caller-owned "
                    f"buffer '{alias.root}' (via {alias.producer} at line "
                    f"{alias.line}): donation invalidates the caller's "
                    f"array in place — the PR 11 use-after-free; copy "
                    f"first with jnp.array(x, copy=True)")))
            # remember what was donated for the re-read check; the call's
            # end line is the threshold so a multi-line call site does
            # not flag its own argument
            name = None
            if isinstance(arg, ast.Name):
                name = arg.id
            elif _self_attr(arg) is not None:
                name = f"self.{_self_attr(arg)}"
            if name is not None and name not in self.donated_at:
                self.donated_at[name] = (
                    call.lineno, getattr(call, "end_lineno", None)
                    or call.lineno, callee)

    def _check_reads_after_donation(self) -> None:
        """A Load of a donated name on a later line — with no intervening
        re-assignment — reads a buffer XLA now owns."""
        if not self.donated_at:
            return
        stores: Dict[str, List[int]] = {}
        loads: Dict[str, List[ast.AST]] = {}
        for node in _walk_scope(self.fn):
            if isinstance(node, ast.Name):
                key = node.id
            else:
                attr = _self_attr(node)
                if attr is None:
                    continue
                key = f"self.{attr}"
            if key not in self.donated_at:
                continue
            if isinstance(node.ctx, ast.Store):
                stores.setdefault(key, []).append(node.lineno)
            elif isinstance(node.ctx, ast.Load):
                loads.setdefault(key, []).append(node)
        for key, (dline, dend, callee) in self.donated_at.items():
            for node in loads.get(key, []):
                if node.lineno <= dend:
                    continue
                if any(dline <= s <= node.lineno
                       for s in stores.get(key, [])):
                    continue  # rebound before the read: the usual
                    # ``state = run(state, n)`` swap
                self.findings.append((node, (
                    f"`{key}` read after being donated to `{callee}` at "
                    f"line {dline}: the buffer belongs to XLA once the "
                    f"call dispatches — keep a copy, or re-read the "
                    f"call's result instead")))
                break  # one finding per donated name is enough


class _DonationAnalysis:
    """Module-level orchestration: donating callables, forwarding
    helpers, per-class attribute aliases, then every function body."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        # dotted callee name -> donated positional indices
        self.donating_callables: Dict[str, Tuple[int, ...]] = {}
        # helper name -> index of the param its return value aliases
        self.forwarders: Dict[str, int] = {}
        # local names bound to a make_* runner factory (``make = sharded.
        # make_multi_step_packed``): calling one with donate=True
        # configures the runner it RETURNS, it donates nothing itself
        self.factory_aliases: Set[str] = set()
        self.findings: List[Tuple[ast.AST, str]] = []
        self._collect_module_facts()

    # - module pass ----------------------------------------------------------

    @staticmethod
    def _jit_donated_positions(call: ast.Call,
                               params: List[str]) -> Tuple[int, ...]:
        """Constant donate_argnums/argnames of a jit-like call."""
        out: List[int] = []
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                out.extend(const_int_tuple(kw.value) or ())
            elif kw.arg == "donate_argnames":
                for nm in const_str_tuple(kw.value) or ():
                    if nm in params:
                        out.append(params.index(nm))
        return tuple(sorted(set(out)))

    @staticmethod
    def _call_has_donate_true(call: ast.Call) -> bool:
        return any(kw.arg == "donate" and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True for kw in call.keywords)

    @classmethod
    def _lambda_donated_positions(cls, lam: ast.Lambda) -> Tuple[int, ...]:
        """``lambda s, n: f(s, n, donate=True)`` donates whichever of ITS
        params land in the wrapped call's donated slots — the Engine's
        backend-closure idiom."""
        if not isinstance(lam.body, ast.Call):
            return ()
        call = lam.body
        tail = (dotted(call.func) or "").split(".")[-1]
        if tail.startswith("make_") or not cls._call_has_donate_true(call):
            return ()
        params = param_names(lam)
        out = []
        for pos in _DONATED_POS_DEFAULT:
            if pos < len(call.args) and isinstance(call.args[pos],
                                                   ast.Name) \
                    and call.args[pos].id in params:
                out.append(params.index(call.args[pos].id))
        return tuple(out)

    def _collect_module_facts(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = param_names(node)
                # decorated defs that donate on every call
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        tail = (dotted(dec.func) or "").split(".")[-1]
                        if tail in ("tracked_jit", "jit"):
                            pos = self._jit_donated_positions(dec, params)
                            if pos:
                                self.donating_callables[node.name] = pos
                        elif tail == "partial" and dec.args:
                            inner = (dotted(dec.args[0]) or "").split(".")[-1]
                            if inner in ("tracked_jit", "jit"):
                                pos = self._jit_donated_positions(
                                    dec, params)
                                if pos:
                                    self.donating_callables[node.name] = pos
                # forwarding helpers: a single-return body whose value
                # aliases a parameter
                if len(node.body) >= 1:
                    ret = node.body[-1]
                    if isinstance(ret, ast.Return) and ret.value is not None:
                        idx = self._forwarded_param(ret.value, params)
                        if idx is not None:
                            self.forwarders[node.name] = idx
            elif isinstance(node, ast.Assign):
                pos: Tuple[int, ...] = ()
                if isinstance(node.value, ast.Call):
                    call = node.value
                    tail = (dotted(call.func) or "").split(".")[-1]
                    if tail in ("tracked_jit", "jit"):
                        pos = self._jit_donated_positions(call, [])
                    elif self._call_has_donate_true(call):
                        # run = make_multi_step_*(mesh, rule, donate=True):
                        # the returned runner consumes its first argument
                        pos = _DONATED_POS_DEFAULT
                elif isinstance(node.value, ast.Lambda):
                    pos = self._lambda_donated_positions(node.value)
                else:
                    # bare factory references: make = sharded.make_* (or
                    # an IfExp choosing between factories)
                    tails = {(dotted(sub) or "").split(".")[-1]
                             for sub in ast.walk(node.value)
                             if isinstance(sub, (ast.Name, ast.Attribute))}
                    if any(t.startswith("make_") for t in tails):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.factory_aliases.add(t.id)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.donating_callables[t.id] = pos
                        attr = _self_attr(t)
                        if attr is not None:
                            self.donating_callables[f"self.{attr}"] = pos

    @staticmethod
    def _forwarded_param(expr: ast.AST, params: List[str]) -> Optional[int]:
        if isinstance(expr, ast.Call) and _is_aliasing_call(expr):
            base = _owned_base(expr.args[0])
            if isinstance(base, ast.Name) and base.id in params:
                return params.index(base.id)
        return None

    # - function passes ------------------------------------------------------

    def run(self) -> List[Tuple[ast.AST, str]]:
        # classes first: attribute aliases cross method boundaries
        class_fns: Set[int] = set()
        for cls in [n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)]:
            attr_aliases = self._class_attr_aliases(cls)
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_fns.add(id(fn))
                    owned = set(param_names(fn)) - {"self", "cls"}
                    fa = _FnAnalysis(self, fn, owned, attr_aliases,
                                     f"{cls.name}.{fn.name}")
                    fa.run()
                    self.findings.extend(fa.findings)
        for fn in ast.walk(self.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(fn) not in class_fns:
                fa = _FnAnalysis(self, fn, set(param_names(fn)), {},
                                 fn.name)
                fa.run()
                self.findings.extend(fa.findings)
        return self.findings

    def _class_attr_aliases(self, cls: ast.ClassDef) -> Dict[str, Alias]:
        """self attributes that alias a caller-owned buffer in ANY method
        (an aliased store is sticky: one clean re-assignment elsewhere
        does not un-alias the caller's copy)."""
        out: Dict[str, Alias] = {}
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            owned = set(param_names(fn)) - {"self", "cls"}
            fa = _FnAnalysis(self, fn, owned, {}, fn.name)
            for stmt in (fn.body if not isinstance(fn, ast.Lambda) else []):
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Assign):
                        continue
                    alias = fa._alias_of(node.value)
                    # track locals so chained stores resolve
                    for t in node.targets:
                        if isinstance(t, ast.Name) and alias is not None:
                            fa.env[t.id] = alias
                        attr = _self_attr(t)
                        if attr is not None and alias is not None \
                                and attr not in out:
                            out[attr] = alias
        return out


def donation_alias_findings(tree: ast.Module) -> List[Tuple[ast.AST, str]]:
    """GOL008's engine: (node, message) pairs for caller-buffer aliases
    reaching donated call positions and reads-after-donation."""
    return _DonationAnalysis(tree).run()


# =============================================================================
# lock-order graph (GOL009)
# =============================================================================


@dataclasses.dataclass
class _Acquisition:
    """One 'acquired B while holding A' event inside a method."""

    held: str                      # lock attr currently held (same class)
    target: Tuple                  # ("lock", attr) | ("self", meth)
    #                              | ("attr", obj_attr, meth)
    node: ast.AST


@dataclasses.dataclass
class ClassLockSummary:
    """Everything the project pass needs to know about one class."""

    path: str
    name: str
    locks: Dict[str, str]                    # lock attr -> Lock | RLock
    attr_types: Dict[str, str]               # self._x = Cls(...) -> Cls
    entry_acquires: Dict[str, List[Tuple[str, ast.AST]]]  # method ->
    #                                        locks taken while holding none
    held_events: Dict[str, List[_Acquisition]]            # method -> events


def summarize_class_locks(cls: ast.ClassDef, path: str) -> ClassLockSummary:
    locks = lock_attr_types(cls)
    attr_types: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            ctor = dotted(node.value.func)
            if ctor and ctor.split(".")[-1][:1].isupper():
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        attr_types[attr] = ctor.split(".")[-1]
    entry: Dict[str, List[Tuple[str, ast.AST]]] = {}
    events: Dict[str, List[_Acquisition]] = {}

    for fn in cls.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        e_list: List[Tuple[str, ast.AST]] = []
        ev_list: List[_Acquisition] = []

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in locks:
                        acquired.append((attr, item.context_expr))
                for attr, site in acquired:
                    if held:
                        ev_list.append(_Acquisition(
                            held=held[-1], target=("lock", attr),
                            node=site))
                    else:
                        e_list.append((attr, site))
                new_held = held + tuple(a for a, _ in acquired)
                for child in node.body:
                    walk(child, new_held)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, ast.Call) and held:
                f = node.func
                if isinstance(f, ast.Attribute):
                    owner = _self_attr(f.value)
                    if isinstance(f.value, ast.Name) \
                            and f.value.id == "self":
                        ev_list.append(_Acquisition(
                            held=held[-1], target=("self", f.attr),
                            node=node))
                    elif owner is not None:
                        ev_list.append(_Acquisition(
                            held=held[-1],
                            target=("attr", owner, f.attr), node=node))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for child in fn.body:
            walk(child, ())
        if e_list:
            entry[fn.name] = e_list
        if ev_list:
            events[fn.name] = ev_list

    return ClassLockSummary(path=path, name=cls.name, locks=locks,
                            attr_types=attr_types, entry_acquires=entry,
                            held_events=events)


@dataclasses.dataclass
class LockEdge:
    """src lock-node acquires dst lock-node while held."""

    src: str                       # "Class.attr"
    dst: str
    path: str                      # module emitting the edge
    node: ast.AST
    how: str                       # human phrasing for the finding
    cross_class: bool


class LockGraph:
    """The project-wide acquired-while-holding graph."""

    def __init__(self, summaries: Iterable[ClassLockSummary]):
        self.classes: Dict[str, ClassLockSummary] = {}
        for s in summaries:
            if s.locks:
                self.classes[s.name] = s
        self.edges: List[LockEdge] = []
        # (summary, method, ast node, description) — re-entry of a plain
        # threading.Lock, the guaranteed-deadlock case. Self-loop edges
        # never enter the graph: RLock re-entry is legal and a plain-Lock
        # re-entry is reported here, not as a "cycle".
        self.self_deadlocks: List[Tuple[ClassLockSummary, str,
                                        ast.AST, str]] = []
        self._build()

    def _node(self, cls: str, attr: str) -> str:
        return f"{cls}.{attr}"

    def _build(self) -> None:
        for s in self.classes.values():
            for meth, events in s.held_events.items():
                for ev in events:
                    src = self._node(s.name, ev.held)
                    kind = ev.target[0]
                    if kind == "lock":
                        attr = ev.target[1]
                        if attr == ev.held:
                            if s.locks.get(attr) == "Lock":
                                self.self_deadlocks.append((
                                    s, meth, ev.node,
                                    f"{s.name}.{meth} nests `with "
                                    f"self.{attr}` inside `with "
                                    f"self.{attr}`"))
                            continue
                        self.edges.append(LockEdge(
                            src=src,
                            dst=self._node(s.name, attr),
                            path=s.path, node=ev.node,
                            how=f"{s.name}.{meth} nests "
                                f"`with self.{attr}` inside "
                                f"`with self.{ev.held}`",
                            cross_class=False))
                    elif kind == "self":
                        callee = ev.target[1]
                        for attr, _ in s.entry_acquires.get(callee, []):
                            if attr == ev.held:
                                if s.locks.get(attr) == "Lock":
                                    self.self_deadlocks.append((
                                        s, meth, ev.node,
                                        f"{s.name}.{meth} calls "
                                        f"self.{callee}() while holding "
                                        f"self.{ev.held}, and {callee} "
                                        f"re-acquires self.{ev.held}"))
                                continue
                            self.edges.append(LockEdge(
                                src=src, dst=self._node(s.name, attr),
                                path=s.path, node=ev.node,
                                how=f"{s.name}.{meth} calls "
                                    f"self.{callee}() (which takes "
                                    f"self.{attr}) while holding "
                                    f"self.{ev.held}",
                                cross_class=False))
                    else:
                        _, obj_attr, callee = ev.target
                        tcls = self.classes.get(
                            s.attr_types.get(obj_attr, ""))
                        if tcls is None:
                            continue
                        for attr, _ in tcls.entry_acquires.get(callee, []):
                            self.edges.append(LockEdge(
                                src=src,
                                dst=self._node(tcls.name, attr),
                                path=s.path, node=ev.node,
                                how=f"{s.name}.{meth} calls "
                                    f"self.{obj_attr}.{callee}() (which "
                                    f"takes {tcls.name}.{attr}) while "
                                    f"holding self.{ev.held}",
                                cross_class=True))

    def cycles(self) -> List[List[LockEdge]]:
        """Elementary cycles in the acquisition graph, each reported once
        (deduped on the canonical node set)."""
        adj: Dict[str, List[LockEdge]] = {}
        for e in self.edges:
            adj.setdefault(e.src, []).append(e)
        seen_sets: Set[frozenset] = set()
        out: List[List[LockEdge]] = []

        def dfs(node: str, path_edges: List[LockEdge],
                on_path: Dict[str, int]) -> None:
            for e in adj.get(node, []):
                if e.dst in on_path:
                    cyc = path_edges[on_path[e.dst]:] + [e]
                    key = frozenset(x.src for x in cyc)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(cyc)
                    continue
                on_path[e.dst] = len(path_edges) + 1
                dfs(e.dst, path_edges + [e], on_path)
                del on_path[e.dst]

        for start in sorted(adj):
            dfs(start, [], {start: 0})
        return out


# =============================================================================
# metric discipline (GOL010)
# =============================================================================

_METRIC_KINDS = ("counter", "gauge", "histogram")


@dataclasses.dataclass(frozen=True)
class MetricDecl:
    name: str
    kind: str        # counter | gauge | histogram
    path: str
    node: ast.AST = dataclasses.field(compare=False, hash=False)


def collect_metric_decls(tree: ast.Module, path: str) -> List[MetricDecl]:
    """Constant-string ``*.counter/gauge/histogram("name", ...)`` calls.
    Dynamic names are invisible to the registry-discipline checks on
    purpose — guessing would produce noise, and the runtime registry
    still enforces kind conflicts for those."""
    out: List[MetricDecl] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_KINDS):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        out.append(MetricDecl(name=node.args[0].value,
                              kind=node.func.attr, path=path, node=node))
    return out


def per_chip_gauge_names(tree: ast.Module) -> Optional[Set[str]]:
    """The literal ``PER_CHIP_GAUGES`` set out of obs/aggregate.py's AST,
    or None when no such constant assignment exists."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "PER_CHIP_GAUGES"
                   for t in node.targets):
            continue
        names: Set[str] = set()
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                names.add(sub.value)
        return names
    return None
