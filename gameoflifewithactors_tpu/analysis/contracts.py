"""HLO contract gate: prove runner invariants from what XLA actually emits.

The lint half of this package reasons about *source*; this module
reasons about *compiled artifacts*. Every runner family registers a
zero-arg contract factory in ``ops/_jit.py``'s ``BUILDERS`` registry;
the gate lowers each built runner to HLO on the 8-virtual-CPU platform
and asserts, per runner:

- **donation applied** — every position in ``donated_argnums`` carries
  a donation marker in the lowered MLIR (``tf.aliasing_output`` for
  plain jit donation, ``jax.buffer_donor`` for shard_map runners, where
  aliasing is resolved at compile) and the compiled module actually
  aliases (``input_output_alias``). This is the PR 11 bug class proved
  end-to-end: ``donate=True`` that silently fell off a runner would
  pass every numeric test on CPU and double HBM on hardware.
- **zero host transfers** — no infeed/outfeed/host-callback ops in the
  compiled HLO: a generation loop that round-trips to the host would
  also pass CPU tests while destroying TPU throughput.
- **collective accounting** — collective-permute byte totals equal the
  closed-form halo models (``ghost_exchange_bytes`` /
  ``deep_exchange_bytes``) *exactly* for the comm-avoiding runners;
  byte totals are invariant under XLA's collective-combining passes, so
  this is a hard contract. Instruction *counts* are not invariant (see
  utils/profiling.collective_permute_count), so counts — and byte
  totals of runners without a model — gate as measurements pinned in
  ``results/hlo_contracts.json``, with perf_gate's staleness semantics:
  a manifest pinned under a different jax version gates as
  **"skipped (stale)"**, never "ok", while the invariants above stay
  enforced regardless.

Failures name the runner — "a collective appeared somewhere" is not
actionable; "sharded.multi_step_packed_ghost moved 1792 bytes where
ghost_exchange_bytes(k=4) predicts 1536" is.

jax is imported lazily inside the functions that need it: this module
lives next to the jax-free lint engine and must not poison its imports.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence

MANIFEST_RELPATH = os.path.join("results", "hlo_contracts.json")

# fault-injection seam: name a registered runner here and the gate wraps
# it with one extra ppermute before lowering — the committed test that
# the gate fails *closed* (tests/test_contracts.py)
ENV_INJECT = "GOLTPU_CONTRACT_INJECT"

# ops in compiled HLO whose presence means a host round-trip; matched as
# word fragments against instruction lines (XLA spells these several
# ways across versions — infeed/outfeed instructions, host send/recv,
# and the python-callback custom-calls io_callback lowers to)
_HOST_TRANSFER_RE = re.compile(
    r"\b(infeed|outfeed|send-to-host|recv-from-host|SendToHost|"
    r"RecvFromHost|xla_python_cpu_callback|xla_ffi_python_cpu_callback|"
    r"host_callback)\b")

_MAIN_SIG_RE = re.compile(r"func\.func public @main\((?P<sig>.*?)\)\s*->",
                          re.DOTALL)
_ARG_SPLIT_RE = re.compile(r"%arg(\d+):")

# gather instructions in compiled HLO (inside fusions too — compiled
# text includes fusion bodies); the lookbehind keeps `all-gather`
# collectives from counting as neighbor-resolution gathers
_GATHER_RE = re.compile(r"(?<![\w-])gather\(")


@dataclasses.dataclass
class RunnerContracts:
    """One runner's measured facts plus its invariant violations."""
    name: str
    tags: tuple
    donated_argnums: tuple
    donation_applied: bool
    host_transfer_sites: List[str]
    collective_permute_count: int
    collective_permute_bytes: int
    expected_collective_bytes: Optional[int]
    collective_model: str
    gather_count: int = 0
    require_gather: bool = False
    errors: List[str] = dataclasses.field(default_factory=list)

    def to_manifest_entry(self) -> dict:
        return {
            "tags": list(self.tags),
            "donated_argnums": list(self.donated_argnums),
            "donation_applied": self.donation_applied,
            "host_transfer_sites": len(self.host_transfer_sites),
            "collective_permute_count": self.collective_permute_count,
            "collective_permute_bytes": self.collective_permute_bytes,
            "expected_collective_bytes": self.expected_collective_bytes,
            "collective_model": self.collective_model,
            "gather_count": self.gather_count,
            "require_gather": self.require_gather,
        }


def load_registry() -> Dict[str, object]:
    """Import every builder module so ``BUILDERS`` is fully populated,
    and return it. Importing is the whole registration protocol — the
    factories themselves stay unbuilt until the gate calls them."""
    from ..memory import pool  # noqa: F401  (register on import)
    from ..ops import packed, stencil  # noqa: F401
    from ..parallel import batched, sharded  # noqa: F401
    from ..ops._jit import BUILDERS

    return BUILDERS


def donor_marked_args(mlir_text: str) -> List[int]:
    """Argument positions of ``@main`` carrying a donation marker
    (``tf.aliasing_output`` or ``jax.buffer_donor``) in lowered MLIR."""
    m = _MAIN_SIG_RE.search(mlir_text)
    if m is None:
        return []
    sig = m.group("sig")
    # split the signature into per-%argN chunks; each chunk's attribute
    # dict (if any) trails its tensor type
    marks: List[int] = []
    parts = _ARG_SPLIT_RE.split(sig)
    # parts = [prefix, idx0, chunk0, idx1, chunk1, ...]
    for idx, chunk in zip(parts[1::2], parts[2::2]):
        if "tf.aliasing_output" in chunk or "jax.buffer_donor" in chunk:
            marks.append(int(idx))
    return marks


def host_transfer_sites(hlo_text: str) -> List[str]:
    """Distinct host-transfer markers present in compiled HLO."""
    return sorted({m.group(1) for m in _HOST_TRANSFER_RE.finditer(hlo_text)})


def _with_injected_permute(built):
    """Wrap a built runner so its program carries one extra one-tile
    ppermute over the first >1-sized mesh axis — the seam the
    fails-closed test uses. Requires ``mesh``/``out_spec`` on the
    BuiltRunner (sharded runners set them)."""
    import functools

    import jax

    from ..parallel._compat import shard_map

    if built.mesh is None or built.out_spec is None:
        raise ValueError(
            "contract injection needs mesh/out_spec on the BuiltRunner; "
            "this runner registered without an injection seam")
    mesh, spec = built.mesh, built.out_spec
    axis = next(a for a in mesh.axis_names if mesh.shape[a] > 1)
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]
    inner = getattr(built.lowerable, "jitted", built.lowerable)

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                       out_specs=spec)
    def _shift(tile):
        return jax.lax.ppermute(tile, axis, perm)

    def fn(*args, **kwargs):
        return _shift(inner(*args, **kwargs))

    # keep the original donation so the injected build fails on exactly
    # one contract — the collective accounting — not as collateral
    # goltpu: ignore[GOL006] -- deliberately-broken build for the fails-closed test; must NOT enter compile accounting
    return jax.jit(fn, donate_argnums=built.donated_argnums,
                   static_argnames=tuple(built.example_kwargs))


def check_runner(spec, *, inject: bool = False) -> RunnerContracts:
    """Build, lower, and compile one registered runner; return its
    measured contract facts with every invariant violation spelled out
    (each error string leads with the runner name)."""
    from ..utils import profiling

    built = spec.factory()
    lowerable = (_with_injected_permute(built) if inject
                 else built.lowerable)
    lowered = lowerable.lower(*built.example_args, **built.example_kwargs)
    mlir = lowered.as_text()
    hlo = lowered.compile().as_text()

    errors: List[str] = []
    donation_applied = True
    if built.donated_argnums:
        marked = donor_marked_args(mlir)
        missing = [i for i in built.donated_argnums if i not in marked]
        aliased = ("input_output_alias" in hlo
                   or "tf.aliasing_output" in mlir)
        donation_applied = not missing and aliased
        if missing:
            errors.append(
                f"{spec.name}: donation NOT applied to arg position(s) "
                f"{missing} — the lowered program carries no donation "
                "marker there (the PR 11 bug class: donate=True fell off "
                "the runner)")
        elif not aliased:
            errors.append(
                f"{spec.name}: buffer donor marked but the compiled "
                "module shows no input_output_alias — XLA dropped the "
                "aliasing, so donation buys no memory on this build")

    host = host_transfer_sites(hlo)
    if host:
        errors.append(
            f"{spec.name}: host transfer(s) in compiled HLO: "
            f"{', '.join(host)} — generation loops must stay on-device")

    cp_count = profiling.collective_permute_count(hlo)
    cp_bytes = profiling.collective_permute_bytes(hlo)
    if (built.expected_collective_bytes is not None
            and cp_bytes != built.expected_collective_bytes):
        errors.append(
            f"{spec.name}: collective-permute bytes {cp_bytes} != "
            f"closed-form {built.expected_collective_bytes} "
            f"({built.collective_model or 'model'})")

    gather_count = len(_GATHER_RE.findall(hlo))
    require_gather = bool(getattr(built, "require_gather", False))
    if require_gather and gather_count == 0:
        errors.append(
            f"{spec.name}: no gather ops in compiled HLO — the paged "
            "runner's contract is page-table GATHER neighbor resolution "
            "(slot indirection compiled away means halos stopped being "
            "data-dependent, i.e. the page table is no longer consulted)")

    return RunnerContracts(
        name=spec.name, tags=tuple(spec.tags),
        donated_argnums=tuple(built.donated_argnums),
        donation_applied=donation_applied,
        host_transfer_sites=host,
        collective_permute_count=cp_count,
        collective_permute_bytes=cp_bytes,
        expected_collective_bytes=built.expected_collective_bytes,
        collective_model=built.collective_model,
        gather_count=gather_count,
        require_gather=require_gather,
        errors=errors)


def check_all(only: Optional[Sequence[str]] = None,
              inject: Optional[str] = None) -> List[RunnerContracts]:
    """Check every registered runner (or the ``only`` subset), in name
    order so output and manifests are diffable. ``inject`` names one
    runner to run through the fault-injection seam."""
    registry = load_registry()
    names = sorted(registry)
    if only:
        unknown = [n for n in only if n not in registry]
        if unknown:
            raise KeyError(
                f"unknown runner(s) {unknown}; registered: {names}")
        names = sorted(only)
    return [check_runner(registry[n], inject=(n == inject)) for n in names]


# -- the frozen manifest ------------------------------------------------------


def jax_version() -> str:
    import jax

    return jax.__version__


def build_manifest(results: Sequence[RunnerContracts]) -> dict:
    return {
        "jax": jax_version(),
        "platform": "cpu",
        "generated_by": "scripts/contract_check.py --write",
        "runners": {r.name: r.to_manifest_entry() for r in results},
    }


def load_manifest(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_manifest(manifest: dict, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")


def gate(results: Sequence[RunnerContracts], frozen: Optional[dict],
         *, strict: bool = False, complete: bool = True) -> List[str]:
    """Per-runner status lines: ``ok NAME ...``, ``skipped (stale)
    NAME ...``, or ``FAIL NAME: reason``. Invariants (donation, zero
    host transfers, closed-form bytes) fail regardless of manifest
    state; pinned count/byte comparisons need a fresh manifest — same
    jax version as the run — and gate as skipped-stale otherwise,
    never silently ok (scripts/perf_gate.py semantics). ``strict``
    additionally fails runners the manifest does not pin (CI mode: an
    unpinned runner is an unreviewed contract)."""
    lines: List[str] = []
    pinned = (frozen or {}).get("runners", {})
    fresh = frozen is not None and frozen.get("jax") == jax_version()
    for r in results:
        for e in r.errors:
            lines.append(f"FAIL {e}")
        if r.errors:
            continue
        entry = pinned.get(r.name)
        if entry is None:
            if strict:
                lines.append(
                    f"FAIL {r.name}: not pinned in the manifest — "
                    "regenerate with scripts/contract_check.py --write "
                    "and review the diff")
            else:
                lines.append(f"unpinned {r.name} (count="
                             f"{r.collective_permute_count} bytes="
                             f"{r.collective_permute_bytes})")
            continue
        if not fresh:
            pinned_jax = (frozen or {}).get("jax", "<unknown>")
            lines.append(
                f"skipped (stale) {r.name}: manifest pinned under jax "
                f"{pinned_jax}, running {jax_version()} — invariants "
                "enforced, pinned counts not comparable; regenerate "
                "with --write")
            continue
        tol = int(entry.get("count_tolerance", 0))
        want_count = entry.get("collective_permute_count")
        want_bytes = entry.get("collective_permute_bytes")
        if (want_count is not None
                and abs(r.collective_permute_count - want_count) > tol):
            lines.append(
                f"FAIL {r.name}: collective-permute count "
                f"{r.collective_permute_count} != pinned {want_count} "
                f"(tolerance {tol}) — an extra (or missing) collective "
                "changed this runner's program")
            continue
        if (want_bytes is not None
                and r.collective_permute_bytes != want_bytes):
            lines.append(
                f"FAIL {r.name}: collective-permute bytes "
                f"{r.collective_permute_bytes} != pinned {want_bytes}")
            continue
        lines.append(
            f"ok {r.name} (count={r.collective_permute_count} "
            f"bytes={r.collective_permute_bytes}"
            + (f" model={r.collective_model}" if r.collective_model
               else "") + ")")
    # a runner the manifest pins but the registry lost is a contract
    # silently un-proved — fail loud, someone deleted a registration
    # (``complete=False`` for --only runs, which check a subset)
    have = {r.name for r in results}
    for name in sorted(set(pinned) - have) if complete else ():
        lines.append(
            f"FAIL {name}: pinned in the manifest but no longer "
            "registered — if the runner was removed on purpose, "
            "regenerate the manifest with --write")
    return lines
