"""The built-in goltpu-lint rules (GOL001…GOL010).

Each rule encodes one invariant this codebase actually depends on — the
failure classes the telemetry layer (obs/) can only report after the
fact. They are deliberately *heuristic*: AST-level, no type inference,
tuned to zero false positives on this tree (tests/test_lint.py pins a
positive and a negative fixture per rule). When a rule cannot decide, it
stays quiet — a linter that cries wolf gets pragma'd into silence, which
is worse than a narrow one.

GOL001–007 are line-local; GOL008 is flow-sensitive within a module and
GOL009/GOL010 are *project* rules (analysis/dataflow.py holds their
def-use and graph machinery; lint.register_project runs them once over
every scanned module).

| code   | invariant                                                    |
| ------ | ------------------------------------------------------------ |
| GOL001 | no host-sync calls (.item()/float()/np.asarray/print) on     |
|        | traced values inside jit/shard_map/lax bodies                |
| GOL002 | no Python ``if``/``while`` on traced (non-static) arguments  |
|        | inside traced bodies                                         |
| GOL003 | no unconditional buffer donation at a jit boundary —         |
|        | donation is a caller opt-in (ops/_jit.py)                    |
| GOL004 | obs/ classes that own a ``_lock`` mutate their shared        |
|        | ``self._*`` state only under it                              |
| GOL005 | no raw ``time.time()`` — intervals use ``perf_counter``,     |
|        | phases use obs.spans; wall-clock stamps carry a pragma       |
| GOL006 | no bare ``jax.jit`` outside the ops/_jit.py choke point —    |
|        | untracked jits silently escape compile-event accounting      |
| GOL007 | obs/ classes that own a ``_lock`` READ their ``self._cache`` |
|        | scrape-cache state only under it (GOL004 covers writes; a    |
|        | torn read of a (stamp, payload) tuple is just as racy)       |
| GOL008 | no alias of a caller-owned buffer (jnp.asarray/              |
|        | array(copy=False) of a parameter) may reach a donated call   |
|        | position, and no name is re-read after being donated —       |
|        | the PR 11 use-after-free class                               |
| GOL009 | the obs/serve/resilience lock-acquisition graph is acyclic   |
|        | and never re-enters a plain Lock; cross-class                |
|        | acquire-while-holding must be pragma-justified               |
| GOL010 | registry counters end ``_total``; per-chip-shaped gauges are |
|        | listed in obs/aggregate.py PER_CHIP_GAUGES; no metric name   |
|        | is declared under two different kinds                        |
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import dataflow
from .lint import Finding, ModuleContext, ProjectContext, register, \
    register_project

# ``x.shape``/``x.dtype``-style reads are trace-time constants even on a
# traced array: branching on them is fine, syncing on them impossible
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "weak_type"}

# the repo's own jit entry-point decorator and its default statics
# (ops/_jit.py optionally_donated)
_OPTIONALLY_DONATED_DEFAULT_STATIC = ("rule", "topology")

# list/dict/set/deque mutators for the lock-discipline rule
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "setdefault", "add", "discard"}


# shared AST helpers live in dataflow.py since GOL008+ (the analyses
# need them too); the underscored aliases keep this module's idiom
_dotted = dataflow.dotted


def _is_jax_jit(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("jax.jit", "jit")


def _is_tracked_jit(node: ast.AST) -> bool:
    d = _dotted(node)
    return d is not None and d.split(".")[-1] == "tracked_jit"


def _is_shard_map(node: ast.AST) -> bool:
    d = _dotted(node)
    return d is not None and d.split(".")[-1] == "shard_map"


def _is_partial(node: ast.AST) -> bool:
    return _dotted(node) in ("partial", "functools.partial")


_const_str_tuple = dataflow.const_str_tuple
_const_int_tuple = dataflow.const_int_tuple
_param_names = dataflow.param_names


def _static_names_from_jit_kwargs(keywords, params: List[str]) -> Set[str]:
    static: Set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnames":
            static |= set(_const_str_tuple(kw.value) or ())
        elif kw.arg == "static_argnums":
            for i in _const_int_tuple(kw.value) or ():
                if 0 <= i < len(params):
                    static.add(params[i])
    return static


class _TracedFn:
    """A function/lambda whose body runs under trace (jit / shard_map /
    lax control flow), with the param names that are NOT static."""

    def __init__(self, fn: ast.AST, static: Set[str], why: str):
        self.fn = fn
        self.params = _param_names(fn)
        self.traced_params = [p for p in self.params if p not in static]
        self.why = why  # "jit" / "shard_map" / "lax.scan" ... (messages)


def _collect_traced(tree: ast.Module) -> List[_TracedFn]:
    """Find every function the heuristic can PROVE is traced: decorated
    with jit/shard_map (directly or via partial/optionally_donated), or
    passed by name/inline into jax.jit()/shard_map()/lax control flow."""
    defs_by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # last definition wins; good enough for lint purposes
            defs_by_name[node.name] = node

    traced: Dict[int, _TracedFn] = {}

    def add(fn: Optional[ast.AST], static: Set[str], why: str) -> None:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)) and id(fn) not in traced:
            traced[id(fn)] = _TracedFn(fn, static, why)

    def resolve(node: ast.AST) -> Optional[ast.AST]:
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            return defs_by_name.get(node.id)
        return None

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = _param_names(node)
            for dec in node.decorator_list:
                if _is_jax_jit(dec) or _is_shard_map(dec) \
                        or _is_tracked_jit(dec):
                    add(node, set(), "shard_map" if _is_shard_map(dec)
                        else "jit")
                elif isinstance(dec, ast.Call):
                    f = dec.func
                    if _is_jax_jit(f) or _is_tracked_jit(f):
                        add(node, _static_names_from_jit_kwargs(
                            dec.keywords, params), "jit")
                    elif _is_shard_map(f):
                        add(node, set(), "shard_map")
                    elif _is_partial(f) and dec.args and (
                            _is_jax_jit(dec.args[0])
                            or _is_tracked_jit(dec.args[0])
                            or _is_shard_map(dec.args[0])):
                        static = (set() if _is_shard_map(dec.args[0])
                                  else _static_names_from_jit_kwargs(
                                      dec.keywords, params))
                        add(node, static,
                            "shard_map" if _is_shard_map(dec.args[0])
                            else "jit")
                    elif _dotted(f) is not None and \
                            _dotted(f).split(".")[-1] == "optionally_donated":
                        static = set(_OPTIONALLY_DONATED_DEFAULT_STATIC)
                        for kw in dec.keywords:
                            if kw.arg == "static":
                                static = set(_const_str_tuple(kw.value)
                                             or static)
                        add(node, static, "jit")
        elif isinstance(node, ast.Call):
            f = node.func
            fname = _dotted(f)
            if (_is_jax_jit(f) or _is_tracked_jit(f)) and node.args:
                fn = resolve(node.args[0])
                if fn is not None:
                    add(fn, _static_names_from_jit_kwargs(
                        node.keywords, _param_names(fn)), "jit")
            elif _is_shard_map(f) and node.args:
                fn = resolve(node.args[0])
                add(fn, set(), "shard_map")
            elif fname is not None:
                tail = fname.split(".")[-1]
                # positions of the traced callee(s) per lax primitive
                callee_slots = {"scan": (0,), "fori_loop": (2,),
                                "while_loop": (0, 1), "cond": (1, 2),
                                "map": (0,), "associative_scan": (0,),
                                "checkpoint": (0,)}.get(tail)
                if callee_slots and ("lax" in fname.split(".")
                                     or tail == "checkpoint"):
                    for slot in callee_slots:
                        if slot < len(node.args):
                            add(resolve(node.args[slot]), set(),
                                f"lax.{tail}")
                elif tail == "switch" and "lax" in fname.split(".") \
                        and len(node.args) > 1 and isinstance(
                            node.args[1], (ast.List, ast.Tuple)):
                    for e in node.args[1].elts:
                        add(resolve(e), set(), "lax.switch")
    return list(traced.values())


def _names_in(node: ast.AST, targets: Set[str],
              skip_static_attr_roots: bool = True) -> List[ast.Name]:
    """Name nodes in ``node`` matching ``targets`` — excluding names that
    only appear as the root of a static-attribute read (``x.shape``), a
    ``isinstance(x, ...)`` probe, or an ``is``/``is not`` comparison
    (all trace-time constants)."""
    skip: Set[int] = set()

    class _Marker(ast.NodeVisitor):
        def visit_Attribute(self, n: ast.Attribute) -> None:
            if skip_static_attr_roots and n.attr in _STATIC_ATTRS \
                    and isinstance(n.value, ast.Name):
                skip.add(id(n.value))
            self.generic_visit(n)

        def visit_Call(self, n: ast.Call) -> None:
            if isinstance(n.func, ast.Name) and \
                    n.func.id in ("isinstance", "len", "type", "getattr",
                                  "hasattr"):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Name):
                        skip.add(id(sub))
            self.generic_visit(n)

        def visit_Compare(self, n: ast.Compare) -> None:
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Name):
                        skip.add(id(sub))
            self.generic_visit(n)

    _Marker().visit(node)
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Name) and n.id in targets
            and id(n) not in skip]


# -- GOL001: host sync inside traced code -------------------------------------


@register("GOL001", "host-sync-in-jit",
          "no device→host sync calls inside jit/shard_map/lax bodies")
def _host_sync_in_jit(ctx: ModuleContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for tf in _collect_traced(ctx.tree):
        traced = set(tf.traced_params)
        body = tf.fn.body if isinstance(tf.fn, ast.Lambda) else tf.fn
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item":
                out.append(ctx.finding(
                    "GOL001", node,
                    f".item() inside a traced ({tf.why}) body forces a "
                    "device→host sync per trace; fetch after the "
                    "dispatch, outside the jit boundary"))
            elif isinstance(f, ast.Attribute) and f.attr in (
                    "asarray", "array") and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy"):
                out.append(ctx.finding(
                    "GOL001", node,
                    f"np.{f.attr}() inside a traced ({tf.why}) body pulls "
                    "the traced value to host (ConcretizationTypeError at "
                    "best, a silent transfer at worst); use jnp, or move "
                    "the readback outside the jit"))
            elif isinstance(f, ast.Name) and f.id == "print":
                out.append(ctx.finding(
                    "GOL001", node,
                    f"print() inside a traced ({tf.why}) body runs at "
                    "trace time (or syncs on the traced value); use "
                    "jax.debug.print for runtime values"))
            elif isinstance(f, ast.Name) and f.id in ("float", "int",
                                                      "bool") \
                    and node.args and _names_in(node.args[0], traced):
                out.append(ctx.finding(
                    "GOL001", node,
                    f"{f.id}() on traced argument inside a traced "
                    f"({tf.why}) body is a concretizing device→host "
                    "sync; keep the value on device or make the "
                    "argument static"))
    return out


# -- GOL002: Python branching on traced values --------------------------------


@register("GOL002", "traced-branch",
          "no Python if/while on traced (non-static) args in traced bodies")
def _traced_branch(ctx: ModuleContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for tf in _collect_traced(ctx.tree):
        traced = set(tf.traced_params)
        if not traced:
            continue
        body = tf.fn.body if isinstance(tf.fn, ast.Lambda) else tf.fn
        for node in ast.walk(body):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            hits = _names_in(node.test, traced)
            if hits:
                kind = "if" if isinstance(node, ast.If) else "while"
                out.append(ctx.finding(
                    "GOL002", node,
                    f"Python `{kind}` on traced argument "
                    f"'{hits[0].id}' inside a traced ({tf.why}) body — "
                    "this concretizes (TracerBoolConversionError) or "
                    "bakes one branch into the trace; use lax.cond/"
                    "lax.select, or mark the argument static"))
    return out


# -- GOL003: unconditional buffer donation ------------------------------------


@register("GOL003", "unconditional-donation",
          "donation at a jit boundary must be a caller opt-in")
def _unconditional_donation(ctx: ModuleContext) -> Iterable[Finding]:
    if ctx.is_jit_choke_point:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_jitlike = _is_jax_jit(f) or (
            _dotted(f) is not None
            and _dotted(f).split(".")[-1] == "tracked_jit") or (
            _is_partial(f) and node.args and (
                _is_jax_jit(node.args[0])
                or (_dotted(node.args[0]) or "").split(".")[-1]
                == "tracked_jit"))
        if not is_jitlike:
            continue
        for kw in node.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames"):
                continue
            donated = _const_int_tuple(kw.value)
            if donated is None:
                donated = _const_str_tuple(kw.value)
            if donated:  # a non-empty compile-time constant: always on
                out.append(ctx.finding(
                    "GOL003", kw.value,
                    f"unconditional {kw.arg}={donated!r}: donation "
                    "consumes the caller's buffer on TPU (a no-op on "
                    "CPU, so tests won't catch it) — make it an opt-in "
                    "like ops/_jit.optionally_donated, e.g. "
                    "`donate_argnums=(0,) if donate else ()`"))
    return out


# -- GOL004: obs/ lock discipline ---------------------------------------------


def _lock_attr_names(cls: ast.ClassDef) -> Set[str]:
    """Attributes assigned a threading.Lock()/RLock() anywhere in the
    class (typically __init__)."""
    return set(dataflow.lock_attr_types(cls))


@register("GOL004", "lock-discipline",
          "obs/ shared state mutations must hold the owning class's lock")
def _lock_discipline(ctx: ModuleContext) -> Iterable[Finding]:
    if not ctx.in_obs:
        return []
    out: List[Finding] = []
    for cls in [n for n in ast.walk(ctx.tree)
                if isinstance(n, ast.ClassDef)]:
        locks = _lock_attr_names(cls)
        if not locks:
            continue

        def check_fn(fn: ast.FunctionDef) -> None:
            def walk(node: ast.AST, in_lock: bool) -> None:
                if isinstance(node, ast.With):
                    holds = any(
                        isinstance(item.context_expr, ast.Attribute)
                        and isinstance(item.context_expr.value, ast.Name)
                        and item.context_expr.value.id == "self"
                        and item.context_expr.attr in locks
                        for item in node.items)
                    for child in node.body:
                        walk(child, in_lock or holds)
                    return
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)) \
                        and node is not fn:
                    return  # nested scope: judged on its own if reached
                self_attr = None
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        base = t.value if isinstance(t, ast.Subscript) \
                            else t
                        if isinstance(base, ast.Attribute) and isinstance(
                                base.value, ast.Name) \
                                and base.value.id == "self" \
                                and base.attr.startswith("_") \
                                and base.attr not in locks:
                            self_attr = base.attr
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in _MUTATORS:
                    v = node.func.value
                    if isinstance(v, ast.Attribute) and isinstance(
                            v.value, ast.Name) and v.value.id == "self" \
                            and v.attr.startswith("_") \
                            and v.attr not in locks:
                        self_attr = v.attr
                if self_attr is not None and not in_lock:
                    out.append(ctx.finding(
                        "GOL004", node,
                        f"`self.{self_attr}` mutated outside "
                        f"`with self.{sorted(locks)[0]}:` in "
                        f"{cls.name}.{fn.name} — obs/ recorders are "
                        "read from monitor/exporter threads; hold the "
                        "lock or pragma why this access is safe"))
                for child in ast.iter_child_nodes(node):
                    walk(child, in_lock)

            for child in fn.body:
                walk(child, False)

        for fn in cls.body:
            if isinstance(fn, ast.FunctionDef) and fn.name != "__init__":
                check_fn(fn)
    return out


# -- GOL007: obs/ scrape-cache read discipline --------------------------------


@register("GOL007", "cache-read-discipline",
          "obs/ scrape caches are read only under the owning class's lock")
def _cache_read_discipline(ctx: ModuleContext) -> Iterable[Finding]:
    """GOL004's mirror for *reads*: a TTL scrape cache like
    ``FleetAggregator._cache`` holds a (stamp, payload) tuple replaced
    wholesale under the lock — reading it lock-free can observe the
    swap mid-publication on a free-threaded build, and the pattern
    invites "just peek at it" drift. Narrow on purpose: only ``self``
    attributes whose name contains ``cache``, only in obs/ classes that
    own a lock, and never inside ``__init__`` (publication happens
    before the object escapes)."""
    if not ctx.in_obs:
        return []
    out: List[Finding] = []
    for cls in [n for n in ast.walk(ctx.tree)
                if isinstance(n, ast.ClassDef)]:
        locks = _lock_attr_names(cls)
        if not locks:
            continue

        def check_fn(fn: ast.FunctionDef) -> None:
            def walk(node: ast.AST, in_lock: bool) -> None:
                if isinstance(node, ast.With):
                    holds = any(
                        isinstance(item.context_expr, ast.Attribute)
                        and isinstance(item.context_expr.value, ast.Name)
                        and item.context_expr.value.id == "self"
                        and item.context_expr.attr in locks
                        for item in node.items)
                    for child in node.body:
                        walk(child, in_lock or holds)
                    return
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)) \
                        and node is not fn:
                    return
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr.startswith("_") \
                        and "cache" in node.attr \
                        and node.attr not in locks and not in_lock:
                    out.append(ctx.finding(
                        "GOL007", node,
                        f"`self.{node.attr}` read outside "
                        f"`with self.{sorted(locks)[0]}:` in "
                        f"{cls.name}.{fn.name} — the scrape cache is "
                        "republished wholesale under the lock; snapshot "
                        "it under the lock and work on the local"))
                for child in ast.iter_child_nodes(node):
                    walk(child, in_lock)

            for child in fn.body:
                walk(child, False)

        for fn in cls.body:
            if isinstance(fn, ast.FunctionDef) and fn.name != "__init__":
                check_fn(fn)
    return out


# -- GOL005: raw wall-clock timing --------------------------------------------


@register("GOL005", "wall-clock-timing",
          "time.time() is neither monotonic nor span-attributed")
def _wall_clock(ctx: ModuleContext) -> Iterable[Finding]:
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _dotted(node.func) == "time.time":
            out.append(ctx.finding(
                "GOL005", node,
                "raw time.time(): intervals want time.perf_counter() "
                "(monotonic), instrumented phases want obs.spans.span() "
                "so the RunReport sees them; a genuine wall-clock stamp "
                "needs a pragma saying so"))
    return out


# -- GOL006: jit outside the choke point --------------------------------------


@register("GOL008", "donation-aliasing",
          "no caller-buffer alias may reach a donated call position")
def _donation_aliasing(ctx: ModuleContext) -> Iterable[Finding]:
    """The PR 11 bug class, caught before the soak: a value made by
    ``jnp.asarray(param)`` / ``jnp.array(param, copy=False)`` shares the
    caller's buffer — donating it (directly, via a ``self`` attribute
    stored in one method and donated in another, or through a
    view-forwarding helper) invalidates memory the caller still holds.
    The shipped fix, ``jnp.array(x, copy=True)``, breaks the alias chain
    and stays clean; so does the rebind-after-donate idiom
    ``state = run(state, n)``. Re-reading a name after it was donated
    (without a rebind) is flagged for the same reason."""
    return [ctx.finding("GOL008", node, msg)
            for node, msg in dataflow.donation_alias_findings(ctx.tree)]


# -- GOL009: lock-order across obs/serve/resilience ---------------------------

_LOCK_ORDER_DIRS = ("obs/", "serve/", "resilience/")


def _in_lock_order_scope(path: str) -> bool:
    return any(f"/{d}" in path or path.startswith(d)
               for d in _LOCK_ORDER_DIRS)


@register_project("GOL009", "lock-order",
                  "the cross-class lock-acquisition graph must be acyclic")
def _lock_order(pctx: ProjectContext) -> Iterable[Finding]:
    """GOL004/007 prove each access holds *a* lock; this rule proves the
    locks compose. It builds the acquired-while-holding graph across the
    threaded subsystems (obs/, serve/, resilience/) — nested ``with``,
    self-method calls under a lock, cross-object calls through
    constructor-typed attributes — and flags (a) re-entering a plain
    ``threading.Lock`` (guaranteed self-deadlock), (b) cycles (deadlock
    under the right interleaving), and (c) cross-class
    acquire-while-holding, which is where future cycles come from and
    must carry a pragma explaining why the callee can never call back."""
    by_path = {}
    summaries = []
    for m in pctx.modules:
        if not _in_lock_order_scope(m.path) or m.in_tests:
            continue
        for cls in [n for n in ast.walk(m.tree)
                    if isinstance(n, ast.ClassDef)]:
            s = dataflow.summarize_class_locks(cls, m.path)
            if s.locks:
                summaries.append(s)
        by_path[m.path] = m
    if not summaries:
        return []
    graph = dataflow.LockGraph(summaries)
    out: List[Finding] = []

    def emit(path: str, node: ast.AST, msg: str) -> None:
        m = by_path.get(path)
        if m is not None:
            out.append(m.finding("GOL009", node, msg))

    for s, meth, node, desc in graph.self_deadlocks:
        emit(s.path, node,
             f"self-deadlock: {desc} — threading.Lock is not reentrant; "
             "inline the locked body or switch to an unlocked _locked() "
             "helper")
    for cyc in graph.cycles():
        chain = " -> ".join([e.src for e in cyc] + [cyc[0].src])
        e = cyc[-1]
        emit(e.path, e.node,
             f"lock-order cycle {chain}: {e.how} closes the cycle — two "
             "threads entering from different ends deadlock; impose one "
             "global acquisition order or drop the lock before the call")
    # a call into a lock-LEAF class (one that never calls out while
    # holding its own lock — e.g. a pure store) cannot deadlock today;
    # flag only callees that themselves acquire-and-call, which is where
    # the next cycle comes from
    outgoing = {e.src.split(".")[0] for e in graph.edges}
    for e in graph.edges:
        if e.cross_class and e.dst.split(".")[0] in outgoing:
            emit(e.path, e.node,
                 f"cross-class acquire-while-holding: {e.how} — if "
                 f"{e.dst.split('.')[0]} ever calls back under its lock "
                 "this deadlocks; move the call outside the lock or "
                 "pragma why the callee cannot re-enter")
    return out


# -- GOL010: metric-name discipline -------------------------------------------

_PER_CHIP_SUFFIXES = ("_per_sec", "_ratio", "_fraction", "_duty_cycle")


@register_project("GOL010", "metric-discipline",
                  "metric names follow the registry/aggregation contract")
def _metric_discipline(pctx: ProjectContext) -> Iterable[Finding]:
    """Today these contracts only fail in production: a counter without
    ``_total`` breaks the PromQL conventions the dashboards assume, a
    per-chip gauge missing from PER_CHIP_GAUGES gets silently summed
    across the fleet (the exact bug PerChipSumError exists to refuse),
    and a name declared as both gauge and histogram raises at import
    time on whichever process loads both modules. All three are visible
    in the AST. Tests are exempt (throwaway metric names are the point
    there); the per-chip membership check only runs when
    obs/aggregate.py is part of the scanned tree."""
    decls: List[dataflow.MetricDecl] = []
    by_path = {m.path: m for m in pctx.modules}
    for m in pctx.modules:
        if m.in_tests:
            continue
        decls.extend(dataflow.collect_metric_decls(m.tree, m.path))
    out: List[Finding] = []

    def emit(d: dataflow.MetricDecl, msg: str) -> None:
        m = by_path.get(d.path)
        if m is not None:
            out.append(m.finding("GOL010", d.node, msg))

    per_chip: Optional[Set[str]] = None
    agg = pctx.module("obs/aggregate.py")
    if agg is not None:
        per_chip = dataflow.per_chip_gauge_names(agg.tree)

    for d in decls:
        if d.kind == "counter" and not d.name.endswith("_total"):
            emit(d, f"counter '{d.name}' does not end in '_total': the "
                    "fleet plane and dashboards key on the Prometheus "
                    "counter convention — rename, or pragma why this "
                    "series name is frozen")
        if d.kind == "gauge" and per_chip is not None \
                and d.name not in per_chip \
                and (d.name.startswith("hbm_")
                     or d.name.endswith(_PER_CHIP_SUFFIXES)):
            emit(d, f"per-chip-shaped gauge '{d.name}' is not listed in "
                    "obs/aggregate.py PER_CHIP_GAUGES: fleet aggregation "
                    "would sum it across chips into a meaningless number "
                    "— add it to the set (or pragma why summing is "
                    "correct here)")

    kinds: Dict[str, dataflow.MetricDecl] = {}
    flagged: Set[Tuple[str, str]] = set()
    for d in decls:
        first = kinds.setdefault(d.name, d)
        if first.kind != d.kind and (d.name, d.path) not in flagged:
            flagged.add((d.name, d.path))
            emit(d, f"metric '{d.name}' declared as {d.kind} here but as "
                    f"{first.kind} in {first.path}: "
                    "MetricsRegistry raises on the kind conflict at "
                    "runtime — rename one of them")
    return out


# -- GOL006: jit outside the choke point --------------------------------------


@register("GOL006", "untracked-jit",
          "bare jax.jit bypasses the ops/_jit compile-accounting choke point")
def _untracked_jit(ctx: ModuleContext) -> Iterable[Finding]:
    if ctx.is_jit_choke_point:
        return []
    out: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Attribute) and node.attr == "jit" \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "jax":
            out.append(ctx.finding(
                "GOL006", node,
                "bare jax.jit bypasses the ops/_jit choke point: its "
                "compiles never become CompileEvents, so StepMetrics "
                "mis-attributes the stall and the retrace sanitizer "
                "cannot see it — use ops._jit.tracked_jit (or "
                "optionally_donated for step entry points)"))
    return out
