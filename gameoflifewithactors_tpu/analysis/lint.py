"""goltpu-lint engine: AST rules, pragmas, baseline — stdlib only.

The telemetry stack (obs/, PR 1-3) *reports* the failure classes that
kill TPU throughput — silent device→host syncs, accidental retraces,
lock slips in the recorders — after they happen. This module is the
preventive half: a static-analysis engine over the package's own source
that machine-checks the invariants the hot path depends on, cheap enough
to run on every commit with **no jax installed** (the CI lint job runs
before the dependency install; importing this module must never touch
jax, numpy, or the device).

Three layers:

- **Rule registry** — rules register under a stable ``GOLxxx`` code via
  :func:`register`; each is a callable ``(ModuleContext) -> [Finding]``.
  *Project* rules (:func:`register_project`) see every parsed module at
  once — ``(ProjectContext) -> [Finding]`` — for cross-file invariants
  like lock ordering (GOL009) and metric-name discipline (GOL010).
  The codes are API: pragmas and baselines reference them, so a rule may
  be retired but its code never reused.
- **Pragmas** — ``# goltpu: ignore[GOL006] -- reason`` suppresses
  matching findings on its own line or the line directly below a
  standalone pragma comment. The reason is mandatory: a suppression
  without a written justification is itself a finding (GOL000), because
  an unexplained ignore is where the next silent transfer hides.
- **Baseline** — a committed JSON file of grandfathered findings
  (matched by ``(code, path, message)`` so line drift does not
  invalidate it). New code must lint clean; the baseline exists so the
  tool could have been adopted mid-stream — this repo ships with it
  empty and intends to keep it that way.

``scripts/lint.py`` is the CLI face (exit 1 on unsuppressed findings,
0 clean, 2 bad input); tests/test_lint.py pins every rule's positive and
negative fixtures plus the whole-tree "repo is clean" smoke.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Tuple

BASELINE_VERSION = 1

# the engine's own code: pragma/baseline bookkeeping problems. Not a
# registered rule — it cannot be pragma-suppressed (fix the pragma).
PRAGMA_ERROR_CODE = "GOL000"

_PRAGMA_RE = re.compile(
    r"#\s*goltpu:\s*ignore\[(?P<codes>[^\]]*)\]"
    r"(?:\s*--\s*(?P<reason>.*\S))?")
_CODE_RE = re.compile(r"^GOL\d{3}$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    code: str
    path: str          # as handed to the engine (relative paths keep the
                       # baseline portable across checkouts)
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift with unrelated edits, so
        grandfathering matches on (code, path, message)."""
        return (self.code, self.path, self.message)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: " \
               f"{self.code} {self.message}"


@dataclasses.dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable[["ModuleContext"], Iterable[Finding]]


RULES: Dict[str, Rule] = {}

# cross-file rules: ``check`` takes a ProjectContext (every parsed module
# in the run) instead of one ModuleContext. Same code space as RULES —
# pragmas and baselines cannot tell the layers apart, by design.
PROJECT_RULES: Dict[str, Rule] = {}


def register(code: str, name: str, summary: str):
    """Decorator: file a rule under ``code`` (stable, never reused)."""
    if not _CODE_RE.match(code):
        raise ValueError(f"rule code must match GOLnnn, got {code!r}")

    def deco(fn):
        if code in RULES or code in PROJECT_RULES:
            raise ValueError(f"duplicate rule code {code}")
        RULES[code] = Rule(code=code, name=name, summary=summary, check=fn)
        return fn

    return deco


def register_project(code: str, name: str, summary: str):
    """Decorator: file a *project-level* rule — its check runs once per
    lint run over a :class:`ProjectContext` and may emit findings against
    any scanned file (per-file pragmas still suppress them)."""
    if not _CODE_RE.match(code):
        raise ValueError(f"rule code must match GOLnnn, got {code!r}")

    def deco(fn):
        if code in RULES or code in PROJECT_RULES:
            raise ValueError(f"duplicate rule code {code}")
        PROJECT_RULES[code] = Rule(code=code, name=name, summary=summary,
                                   check=fn)
        return fn

    return deco


@dataclasses.dataclass
class ModuleContext:
    """Everything a rule may look at for one source file."""

    path: str                 # reporting path (normalized, '/'-separated)
    source: str
    tree: ast.Module
    in_obs: bool              # under the obs/ subpackage (lock rules)
    is_jit_choke_point: bool  # ops/_jit.py itself (exempt from GOL006)
    in_tests: bool

    @classmethod
    def from_source(cls, source: str, path: str) -> "ModuleContext":
        norm = path.replace(os.sep, "/")
        return cls(
            path=norm,
            source=source,
            tree=ast.parse(source, filename=path),
            in_obs="/obs/" in norm or norm.startswith("obs/"),
            is_jit_choke_point=norm.endswith("ops/_jit.py"),
            in_tests="/tests/" in norm or norm.startswith("tests/"),
        )

    def finding(self, code: str, node: ast.AST, message: str) -> Finding:
        return Finding(code=code, path=self.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message)


@dataclasses.dataclass
class ProjectContext:
    """What a project-level rule may look at: every module that parsed,
    in scan order. Findings are emitted via the owning module's
    :meth:`ModuleContext.finding` so pragma suppression keeps working."""

    modules: List[ModuleContext]

    def module(self, path_suffix: str) -> Optional[ModuleContext]:
        """First scanned module whose path ends with ``path_suffix``
        (e.g. ``"obs/aggregate.py"``), or None if it was not scanned —
        rules use this to gate sub-checks that need a specific anchor
        file rather than guessing from a partial tree."""
        for m in self.modules:
            if m.path.endswith(path_suffix):
                return m
        return None


# -- pragmas ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Pragma:
    line: int                 # 1-based line the comment sits on
    codes: Tuple[str, ...]
    reason: Optional[str]
    standalone: bool          # comment-only line: applies to the next line


def parse_pragmas(source: str) -> List[Pragma]:
    """Pragmas live in COMMENT tokens only — a regex over raw lines would
    also match the pragma syntax quoted inside string literals (this
    module's own docstrings being exhibit A)."""
    import io
    import tokenize

    out = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return out  # the ast parse decides whether the file is bad input
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        codes = tuple(c.strip() for c in m.group("codes").split(",")
                      if c.strip())
        out.append(Pragma(
            line=tok.start[0], codes=codes, reason=m.group("reason"),
            standalone=tok.line[:tok.start[1]].strip() == ""))
    return out


def _pragma_errors(pragmas: List[Pragma], path: str) -> List[Finding]:
    errs = []
    for p in pragmas:
        bad = [c for c in p.codes if not _CODE_RE.match(c)]
        if bad or not p.codes:
            errs.append(Finding(
                code=PRAGMA_ERROR_CODE, path=path, line=p.line, col=0,
                message="malformed pragma: expected "
                        "'# goltpu: ignore[GOLnnn] -- reason'"
                        + (f" (bad code(s): {', '.join(bad)})" if bad
                           else " (no codes)")))
        if p.reason is None:
            errs.append(Finding(
                code=PRAGMA_ERROR_CODE, path=path, line=p.line, col=0,
                message="pragma without a reason: every suppression must "
                        "say why ('-- <reason>')"))
    return errs


def _suppressed_by(finding: Finding, by_line: Dict[int, List[Pragma]]) -> bool:
    """A well-formed pragma suppresses findings on its own line, and — when
    it is a standalone comment line — on the line directly below."""
    candidates = list(by_line.get(finding.line, []))
    candidates += [p for p in by_line.get(finding.line - 1, [])
                   if p.standalone]
    return any(finding.code in p.codes and p.reason is not None
               and all(_CODE_RE.match(c) for c in p.codes)
               for p in candidates)


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str) -> List[dict]:
    """Parse a baseline file; raises BaselineError on malformed input
    (the CLI maps that to exit 2 — a broken baseline silently
    grandfathering nothing, or everything, is worse than failing)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise BaselineError(
            f"{path}: expected {{'version': {BASELINE_VERSION}, "
            "'findings': [...]}")
    entries = data.get("findings")
    if not isinstance(entries, list) or not all(
            isinstance(e, dict) and {"code", "path", "message"} <= set(e)
            for e in entries):
        raise BaselineError(
            f"{path}: each finding needs code/path/message keys")
    return entries


def baseline_payload(findings: Iterable[Finding]) -> dict:
    """What ``scripts/lint.py --write-baseline`` writes: current findings
    as grandfathered entries (sorted, line recorded for humans only)."""
    return {
        "version": BASELINE_VERSION,
        "findings": [f.to_dict() for f in sorted(
            findings, key=lambda f: (f.path, f.line, f.code))],
    }


class BaselineError(ValueError):
    """Unusable baseline file (CLI exit 2)."""


# -- the engine ---------------------------------------------------------------


@dataclasses.dataclass
class FileReport:
    path: str
    findings: List[Finding]            # unsuppressed (pre-baseline)
    suppressed: List[Finding]          # pragma'd out
    error: Optional[str] = None        # unreadable / unparseable


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]            # after pragmas AND baseline
    suppressed: List[Finding]          # by pragma
    baselined: List[Finding]           # grandfathered
    unused_baseline: List[dict]        # stale grandfather entries
    files: List[FileReport]
    errors: List[str]                  # bad-input problems (CLI exit 2)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "baselined": [f.to_dict() for f in self.baselined],
            "unused_baseline": list(self.unused_baseline),
            "errors": list(self.errors),
            "files_scanned": len([r for r in self.files if r.error is None]),
        }


def _lint_file(source: str, path: str, rules: Dict[str, Rule]):
    """Per-file pass. Returns (FileReport, ModuleContext | None, by_line
    pragma map) — the context and pragma map feed the project-rule pass,
    which must route its findings through the same suppression."""
    try:
        ctx = ModuleContext.from_source(source, path)
    except SyntaxError as exc:
        return (FileReport(path=path, findings=[], suppressed=[],
                           error=f"{path}: not parseable as Python: {exc}"),
                None, {})
    pragmas = parse_pragmas(source)
    by_line: Dict[int, List[Pragma]] = {}
    for p in pragmas:
        by_line.setdefault(p.line, []).append(p)
    raw: List[Finding] = list(_pragma_errors(pragmas, ctx.path))
    for rule in rules.values():
        raw.extend(rule.check(ctx))
    findings, suppressed = [], []
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.code)):
        if f.code != PRAGMA_ERROR_CODE and _suppressed_by(f, by_line):
            suppressed.append(f)
        else:
            findings.append(f)
    return (FileReport(path=ctx.path, findings=findings,
                       suppressed=suppressed), ctx, by_line)


def lint_source(source: str, path: str,
                rules: Optional[Dict[str, Rule]] = None) -> FileReport:
    """Lint one file's source with the per-file rules. SyntaxError
    surfaces as FileReport.error (bad input), never as an exception —
    the CLI keeps scanning. Project rules need the whole run's modules
    and so only fire from lint_paths/lint_sources."""
    return _lint_file(source, path, RULES if rules is None else rules)[0]


def _apply_project_rules(reports_by_path, ctxs, by_lines,
                         project_rules: Optional[Dict[str, Rule]]) -> None:
    """Run the cross-file rules and fold their findings into the owning
    FileReports, honoring that file's pragmas."""
    prules = PROJECT_RULES if project_rules is None else project_rules
    if not ctxs or not prules:
        return
    pctx = ProjectContext(modules=list(ctxs))
    for rule in prules.values():
        for f in rule.check(pctx):
            rep = reports_by_path.get(f.path)
            if rep is None or rep.error is not None:
                continue  # rules only emit against scanned modules
            if _suppressed_by(f, by_lines.get(f.path, {})):
                rep.suppressed.append(f)
            else:
                rep.findings.append(f)
    for rep in reports_by_path.values():
        rep.findings.sort(key=lambda f: (f.line, f.col, f.code))
        rep.suppressed.sort(key=lambda f: (f.line, f.col, f.code))


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for name in sorted(names):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def _aggregate(reports: List[FileReport], errors: List[str],
               baseline: Optional[List[dict]]) -> LintResult:
    baseline_keys = {(e["code"], e["path"], e["message"])
                     for e in (baseline or [])}
    matched_keys = set()
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    for rep in reports:
        if rep.error:
            errors.append(rep.error)
            continue
        suppressed.extend(rep.suppressed)
        for f in rep.findings:
            if f.key() in baseline_keys:
                matched_keys.add(f.key())
                baselined.append(f)
            else:
                findings.append(f)
    unused = [e for e in (baseline or [])
              if (e["code"], e["path"], e["message"]) not in matched_keys]
    return LintResult(findings=findings, suppressed=suppressed,
                      baselined=baselined, unused_baseline=unused,
                      files=reports, errors=errors)


def _run_lint(files, pre_errors: List[str],
              baseline: Optional[List[dict]],
              rules: Optional[Dict[str, Rule]],
              project_rules: Optional[Dict[str, Rule]]) -> LintResult:
    """Shared core: ``files`` is [(path, source | None, error | None)] —
    per-file rules, then project rules, then the baseline."""
    per_file = RULES if rules is None else rules
    reports: List[FileReport] = []
    reports_by_path: Dict[str, FileReport] = {}
    ctxs: List[ModuleContext] = []
    by_lines: Dict[str, Dict[int, List[Pragma]]] = {}
    for path, source, error in files:
        if error is not None:
            rep = FileReport(path=path, findings=[], suppressed=[],
                             error=error)
        else:
            rep, ctx, by_line = _lint_file(source, path, per_file)
            if ctx is not None:
                ctxs.append(ctx)
                by_lines[ctx.path] = by_line
        reports.append(rep)
        reports_by_path[rep.path] = rep
    _apply_project_rules(reports_by_path, ctxs, by_lines, project_rules)
    return _aggregate(reports, list(pre_errors), baseline)


def lint_sources(sources: Dict[str, str], *,
                 baseline: Optional[List[dict]] = None,
                 rules: Optional[Dict[str, Rule]] = None,
                 project_rules: Optional[Dict[str, Rule]] = None
                 ) -> LintResult:
    """Lint an in-memory {path: source} set as one run — the project
    rules see all of them together. This is how cross-file rule fixtures
    are pinned without touching disk."""
    return _run_lint([(p, s, None) for p, s in sources.items()],
                     [], baseline, rules, project_rules)


def lint_paths(paths: Iterable[str], *,
               baseline: Optional[List[dict]] = None,
               rules: Optional[Dict[str, Rule]] = None,
               project_rules: Optional[Dict[str, Rule]] = None
               ) -> LintResult:
    """Lint files/trees; run per-file then project rules; apply the
    baseline; aggregate."""
    files = []
    errors: List[str] = []
    seen = set()
    any_path = False
    for path in paths:
        any_path = True
        if not os.path.exists(path):
            errors.append(f"{path}: no such file or directory")
            continue
        for fp in iter_python_files([path]):
            if fp in seen:
                continue
            seen.add(fp)
            try:
                with open(fp, encoding="utf-8") as f:
                    src = f.read()
            except OSError as exc:
                files.append((fp, None, f"{fp}: {exc}"))
                continue
            files.append((fp, src, None))
    if not any_path:
        errors.append("no paths given")
    return _run_lint(files, errors, baseline, rules, project_rules)


# registering the built-in rules populates RULES as a side effect; the
# import sits at the bottom so rules.py can import the registry above
from . import rules as _rules  # noqa: E402,F401  (registration import)
