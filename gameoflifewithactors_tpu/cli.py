"""Command-line entry point — the reference's ``Program`` role.

``python -m gameoflifewithactors_tpu --grid 1024x1024 --seed random --steps
1000 --metrics jsonl`` runs the full stack: config → coordinator → tick
scheduler → renderer/metrics → optional checkpoint, mirroring the
reference's Program.main → ActorSystem → GridCoordinator startup
(SURVEY.md §4a) as one construction path.

Subcommands ride in front of the flags: ``report`` (RunReport summary /
diff), ``warmup`` (precompile pipeline), ``serve`` (multi-tenant session
service — README "Serving").
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from .config import from_args
from .utils.render import ConsoleRenderer


def _run_elementary(cfg, args, rule) -> int:
    """The 1D (Wolfram W0..255) route: evolve the full spacetime diagram on
    device in one lax.scan dispatch (ops/elementary.evolve_spacetime), then
    render rows-as-time. VERDICT round-2 item #7 — the one rule family the
    2D Engine cannot drive gets its own CLI surface instead: ``--grid HxW``
    contributes the row width W (H is ignored — time is the vertical axis),
    ``--steps`` the generation count, ``--seed`` center (default) / random /
    empty, ``--render final`` an ASCII diagram, ``--ppm`` the image."""
    import numpy as np

    from .ops import bitpack
    from .ops.elementary import evolve_spacetime
    from .ops.stencil import Topology

    import jax.numpy as jnp

    # flags this route cannot honor must fail loudly, not exit 0 without
    # the requested side effect (a later --resume on the missing file
    # would fail far from the cause)
    for flag, value in (("--checkpoint", cfg.checkpoint),
                        ("--supervise", cfg.supervise or None),
                        ("--metrics", cfg.metrics), ("--mesh", cfg.mesh),
                        ("--ppm-every", cfg.ppm_every or None),
                        ("--save-rle", cfg.save_rle),
                        ("--telemetry-out", cfg.telemetry_out),
                        ("--serve-metrics", cfg.serve_metrics),
                        ("--flight-dump", cfg.flight_dump),
                        ("--device-poll", cfg.device_poll),
                        ("--profile-sample", cfg.profile_sample)):
        if value is not None:
            raise SystemExit(
                f"{flag} is not supported for 1D W-rules (the spacetime "
                "route has no engine state to checkpoint/shard; use --ppm "
                "for the artifact)")

    width = cfg.width
    if width % bitpack.WORD:
        raise SystemExit(
            f"elementary rules run bit-packed: width {width} must be a "
            f"multiple of {bitpack.WORD} (use --grid 1x{width + bitpack.WORD - width % bitpack.WORD})")
    row = np.zeros(width, dtype=np.uint8)
    if cfg.random_fill is not None:                 # --seed random
        row[:] = np.random.default_rng(cfg.rng_seed).random(width) < cfg.random_fill
    elif args.seed in ("glider", "center"):
        # 'glider' is only the 2D default the parser injects; 1D's
        # canonical single-cell seed takes its place (rule 90 from one
        # cell -> the Sierpinski triangle)
        row[width // 2] = 1
    elif args.seed != "empty":
        raise SystemExit(
            f"--seed {args.seed!r} is a 2D seed; 1D W-rules accept "
            "'center' (default), 'random', or 'empty'")

    st = evolve_spacetime(
        bitpack.pack(jnp.asarray(row[None])), cfg.steps, rule=rule,
        topology=Topology(cfg.topology))
    image = np.asarray(bitpack.unpack(st[:, 0, :]))  # (steps+1, W), row=time

    if args.render in ("final", "live"):
        for line in image:
            print("".join(".#"[v] for v in line))
    if cfg.track_population:
        print(f"gen {cfg.steps}  pop {int(image[-1].sum())}")
    if cfg.ppm:
        from .utils.render import save_ppm

        save_ppm(image, cfg.ppm)
        print(f"spacetime diagram written: {cfg.ppm}", file=sys.stderr)
    return 0


def _list_registries() -> int:
    """``--list``: what names ``--seed`` and ``--rule`` accept (plus the
    notation forms each family parses)."""
    from .models import seeds
    from .models.elementary import parse_elementary
    from .models.generations import GENERATIONS_REGISTRY
    from .models.ltl import LTL_REGISTRY
    from .models.rules import RULE_REGISTRY

    print("seed patterns (--seed NAME, or @file.rle / random / empty):")
    for name in sorted(seeds.PATTERNS):
        h, w = seeds.PATTERNS[name].shape
        print(f"  {name:16} {h}x{w}")
    print("\nlife-like rules (--rule, also any 'B…/S…' or classic 'S/B'):")
    for name, r in sorted(RULE_REGISTRY.items()):
        print(f"  {name:16} {r.notation}")
    print("\nGenerations rules (also 'B…/S…/C<n>' or Golly 'S/B/C'):")
    for name, r in sorted(GENERATIONS_REGISTRY.items()):
        print(f"  {name:16} {r.notation}")
    print("\nLarger-than-Life rules (also 'R,C,M,S..,B..[,NN]' HROT form):")
    for name, r in sorted(LTL_REGISTRY.items()):
        print(f"  {name:16} {r.notation}")
    print("\nelementary (1D): W0..W255, e.g. "
          f"{parse_elementary('W110').notation}")
    return 0


def _report_cmd(argv: Sequence[str]) -> int:
    """``python -m gameoflifewithactors_tpu report run.json``: the human
    face of a RunReport written by ``--telemetry-out`` (or bench.py) —
    phases, compiles, rates, stalls, device duty cycle. Pure file
    reading: builds no engine and never touches the device."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="gameoflifewithactors_tpu report",
        description="summarize a RunReport JSON (--telemetry-out artifact)")
    ap.add_argument("path", help="RunReport JSON file (the baseline in "
                                 "--diff mode)")
    ap.add_argument("--json", action="store_true",
                    help="re-emit the raw JSON (validated) instead")
    ap.add_argument("--diff", default=None, metavar="OTHER.json",
                    help="instead of a summary, print the per-phase / "
                         "per-metric delta table PATH -> OTHER (thin "
                         "wrapper over obs.diff; OTHER is the newer run)")
    args = ap.parse_args(argv)
    from .obs.report import RunReport

    if args.diff:
        # raw-JSON loads: either side may be a bench record, not a
        # RunReport — the differ speaks both shapes
        import json as json_lib

        from .obs import diff as diff_lib

        with open(args.path) as f:
            base = json_lib.load(f)
        with open(args.diff) as f:
            other = json_lib.load(f)
        rows = diff_lib.diff_records(base, other)
        if args.json:
            print(json_lib.dumps([r.to_dict() for r in rows], indent=1))
        else:
            print(f"delta {args.path} -> {args.diff} "
                  "(ratio = other / baseline):")
            print("\n".join(diff_lib.format_rows(rows)))
        return 0
    rep = RunReport.load(args.path)
    if args.json:
        print(rep.to_json())
    else:
        print("\n".join(rep.summary_lines()))
    return 0


def _warmup_cmd(argv: Sequence[str]) -> int:
    """``python -m gameoflifewithactors_tpu warmup``: the precompile
    pipeline (README "Warm start") — populate the persistent compilation
    cache and the AOT executable registry for a manifest of engine specs
    ahead of serving, so the serving processes pay ~zero compile time.

    ``--manifest specs.json`` warms a JSON list of EngineSpec dicts;
    ``--from-config`` warms the single spec the remaining (normal CLI)
    flags describe, e.g.::

        python -m gameoflifewithactors_tpu warmup --from-config \\
            --grid 4096x4096 --rule B3/S23 --backend packed
    """
    import argparse

    ap = argparse.ArgumentParser(
        prog="gameoflifewithactors_tpu warmup",
        description="precompile engine specs into the warm-start caches")
    ap.add_argument("--manifest", metavar="PATH",
                    help="JSON list of spec objects: {rule, shape|height/"
                         "width, backend, topology, mesh, gens_per_exchange}")
    ap.add_argument("--from-config", action="store_true",
                    help="derive one spec from the remaining normal CLI "
                         "flags (--grid/--rule/--backend/...)")
    ap.add_argument("--cache-dir", default=None, metavar="PATH",
                    help="cache root override (default: $GOLTPU_CACHE_DIR "
                         "or ~/.cache/gameoflifewithactors_tpu)")
    ap.add_argument("--no-aot", action="store_true",
                    help="populate the compilation cache only; skip "
                         "serializing AOT executables")
    ap.add_argument("--json", action="store_true",
                    help="emit the warmup report as one JSON line")
    args, rest = ap.parse_known_args(argv)
    if bool(args.manifest) == bool(args.from_config):
        ap.error("exactly one of --manifest / --from-config is required")
    if rest and not args.from_config:
        ap.error(f"unrecognized arguments: {' '.join(rest)}")

    from .utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    from .aot import EngineSpec, warmup_specs
    from .aot.warmup import load_manifest_entries

    if args.manifest:
        # (spec, extras) pairs: entries carrying a "lanes" ladder also
        # warm the serve layer's masked batched runners (README "Serving")
        specs = load_manifest_entries(args.manifest)
    else:
        cfg, _ = from_args(rest)
        specs = [EngineSpec.from_config(cfg)]
    rows = warmup_specs(
        specs, aot=not args.no_aot, cache_dir=args.cache_dir,
        verbose=None if args.json else
        (lambda line: print(line, file=sys.stderr)))
    if args.json:
        import json

        print(json.dumps({"warmup": True, "specs": rows}))
    else:
        total = sum(r["wall_seconds"] for r in rows)
        compiling = sum(r["compile_seconds"] for r in rows)
        print(f"warmed {len(rows)} spec(s) in {total:.2f}s "
              f"({compiling:.2f}s compiling); next process warm-starts")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(argv) if argv is not None else sys.argv[1:]
    if argv and argv[0] == "report":
        return _report_cmd(argv[1:])
    if argv and argv[0] == "warmup":
        return _warmup_cmd(argv[1:])
    if argv and argv[0] == "serve":
        # multi-tenant session service (README "Serving"): packs live
        # grid sessions onto batched lanes behind an HTTP/JSON API
        from .serve.frontend import main as serve_main

        return serve_main(list(argv[1:]))

    from .utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    cfg, args = from_args(argv)
    if args.list:
        return _list_registries()

    from .models.elementary import ElementaryRule
    from .models.generations import parse_any

    # --resume wins over --rule (documented in the flag's help): a W-rule
    # left on the command line must not silently replace a resumed 2D run
    if cfg.resume is None and isinstance(parse_any(cfg.rule), ElementaryRule):
        return _run_elementary(cfg, args, parse_any(cfg.rule))

    coordinator, scheduler = cfg.build()

    # continuous telemetry: live Prometheus exposition + device sampler.
    # Started BEFORE the run loop (the whole point is scraping while
    # stepping); daemon threads, explicitly stopped at the end.
    import os

    exporter = sampler = None
    serve_port = cfg.serve_metrics
    if serve_port is None and os.environ.get("GOLTPU_METRICS_PORT"):
        serve_port = int(os.environ["GOLTPU_METRICS_PORT"])
    if serve_port is not None:
        from .obs.device import DeviceSampler
        from .obs.exporter import serve_metrics

        exporter = serve_metrics(serve_port)
        sampler = DeviceSampler(cfg.device_poll).start()
        print(f"serving metrics: http://0.0.0.0:{exporter.port}/metrics",
              file=sys.stderr)

    # flight recorder: armed for any telemetry run (default path rides
    # next to the RunReport), or standalone via --flight-dump
    flight_path = cfg.flight_dump or (
        cfg.telemetry_out + ".flight.jsonl" if cfg.telemetry_out else None)
    # sampling profiler (obs/profiler.py): off by default, armed by
    # --profile-sample or $GOLTPU_PROFILE_SAMPLE_S
    profile_sample = cfg.profile_sample
    if profile_sample is None and os.environ.get("GOLTPU_PROFILE_SAMPLE_S"):
        profile_sample = float(os.environ["GOLTPU_PROFILE_SAMPLE_S"])
    standalone_profiler = None
    telem = None
    if cfg.telemetry_out:
        from .obs import begin_run_telemetry

        # session starts AFTER build: construction-time compiles (e.g. a
        # resume) would be attributed to no tick, but the watchdog must
        # not watch interactive seed parsing either — run time only
        telem = begin_run_telemetry(
            stall_deadline=cfg.stall_deadline or 60.0,
            flight_path=flight_path,
            profile_sample=profile_sample)
        telem.attach(coordinator)
    elif profile_sample:
        # no report to fold into, but the profile_* gauges still feed
        # --serve-metrics scrapes
        from .obs import profiler as profiler_lib

        standalone_profiler = profiler_lib.arm(
            profiler_lib.ProfileSampler(profile_sample))
    if telem is None and flight_path:
        from .obs import flight as flight_lib

        fr = flight_lib.arm(flight_lib.FlightRecorder(flight_path))
        if coordinator.metrics is not None:
            # tape before user-facing sinks (see RunTelemetry.attach)
            coordinator.metrics.sinks.insert(0, fr.on_step)

    if args.render == "live":
        coordinator.subscribe(ConsoleRenderer())
    seq = None
    if cfg.ppm_every:
        if not cfg.ppm:
            raise SystemExit("--ppm-every needs --ppm PATH as the "
                             "filename stem for the frame sequence")
        import numpy as np

        from .utils.render import PpmSequenceWriter

        seq = PpmSequenceWriter(cfg.ppm)
        # full-resolution snapshots, not the console's downsampled view
        # (the user controls cost via grid size and cadence); the initial
        # state is frame 0 so a movie starts from the seed
        coordinator.subscribe(
            lambda frame: seq.write(np.asarray(coordinator.engine.snapshot()),
                                    frame.generation))
        seq.write(np.asarray(coordinator.engine.snapshot()),
                  coordinator.generation)
    # Pacing (rate limit / periodic metrics / live frames) needs the tick
    # loop; otherwise the whole run is one device dispatch.
    needs_pacing = args.render == "live" or cfg.rate_hz or cfg.metrics
    if cfg.supervise:
        if not cfg.checkpoint:
            raise SystemExit(
                "--supervise needs --checkpoint PATH: the restart policy "
                "restores from the checkpoint it maintains there")
        if needs_pacing:
            raise SystemExit(
                "--supervise owns the tick loop; it is incompatible with "
                "--render live, --rate, and --metrics pacing (run the "
                "supervised process under --serve-metrics instead)")
        from .resilience import RestartPolicy, Supervisor

        supervisor = Supervisor(
            coordinator, checkpoint_path=cfg.checkpoint,
            checkpoint_every=cfg.checkpoint_every,
            policy=RestartPolicy(max_restarts=cfg.max_restarts))
        stats = supervisor.run(cfg.steps)
        if stats["restarts"]:
            print(f"supervisor: recovered from {stats['restarts']} "
                  f"failure(s) {stats['restarts_by_cause']}",
                  file=sys.stderr)
    elif needs_pacing:
        scheduler.run(max_generations=cfg.steps)
    elif seq is not None:
        # surface a frame to the sequence every N generations
        coordinator.run(cfg.steps, render_every=cfg.ppm_every)
    else:
        coordinator.run(cfg.steps)

    if args.render == "final":
        ConsoleRenderer(ansi=False)(coordinator.current_frame())
    elif args.render == "off" and cfg.track_population:
        # --population with --render off still reports the number (live and
        # final rendering already show it in the status line)
        frame = coordinator.current_frame()
        print(f"gen {frame.generation}  pop {frame.population}")

    if seq is not None:
        print(f"{len(seq.paths)} frames written: {seq.paths[0]} .. "
              f"{seq.paths[-1]}", file=sys.stderr)
    elif cfg.ppm:
        import numpy as np

        from .utils.render import save_ppm

        save_ppm(np.asarray(coordinator.engine.snapshot()), cfg.ppm)
        print(f"final frame written: {cfg.ppm}", file=sys.stderr)

    if cfg.save_rle:
        import numpy as np

        from .models import seeds as seeds_lib

        # binary rules write legacy b/o tokens; multi-state universes get
        # Golly's extended encoding, with the rule in the header so
        # decoders pick the extended reading
        grid = np.asarray(coordinator.engine.snapshot())
        with open(cfg.save_rle, "w") as f:
            f.write(seeds_lib.to_rle(grid, rule=cfg.rule))
        print(f"RLE written: {cfg.save_rle}", file=sys.stderr)

    if cfg.checkpoint:
        from .utils import checkpoint as ckpt_lib

        path = ckpt_lib.save(coordinator.engine, cfg.checkpoint)
        print(f"checkpoint written: {path}", file=sys.stderr)

    if telem is not None:
        report = telem.finish(
            engine=coordinator.engine,
            config={"steps": cfg.steps, "argv": list(argv)})
        report.save(cfg.telemetry_out)
        print(f"telemetry report written: {cfg.telemetry_out}",
              file=sys.stderr)
        if report.profile is not None:
            # the standalone attribution artifact (CI uploads it; bench
            # records point at its sibling) — same content as the
            # report's profile section, greppable without the report
            import json as _json

            from .obs.profiler import attribution_path_for

            apath = attribution_path_for(cfg.telemetry_out)
            with open(apath, "w") as f:
                _json.dump(report.profile, f, indent=1)
                f.write("\n")
            print(f"profile attribution written: {apath}", file=sys.stderr)
    else:
        if standalone_profiler is not None:
            from .obs import profiler as profiler_lib

            if standalone_profiler is profiler_lib.active_sampler():
                profiler_lib.disarm()
            else:
                standalone_profiler.stop()
        if flight_path:
            from .obs import flight as flight_lib

            flight_lib.disarm()  # clean exit: no crash report to leave

    if sampler is not None:
        sampler.stop()
    if exporter is not None:
        exporter.stop()

    coordinator.engine.block_until_ready()
    return 0


if __name__ == "__main__":
    sys.exit(main())
