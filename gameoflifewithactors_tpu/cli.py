"""Command-line entry point — the reference's ``Program`` role.

``python -m gameoflifewithactors_tpu --grid 1024x1024 --seed random --steps
1000 --metrics jsonl`` runs the full stack: config → coordinator → tick
scheduler → renderer/metrics → optional checkpoint, mirroring the
reference's Program.main → ActorSystem → GridCoordinator startup
(SURVEY.md §4a) as one construction path.
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from .config import from_args
from .utils.render import ConsoleRenderer


def main(argv: Optional[Sequence[str]] = None) -> int:
    from .utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    cfg, args = from_args(argv)
    coordinator, scheduler = cfg.build()

    if args.render == "live":
        coordinator.subscribe(ConsoleRenderer())
    # Pacing (rate limit / periodic metrics / live frames) needs the tick
    # loop; otherwise the whole run is one device dispatch.
    needs_pacing = args.render == "live" or cfg.rate_hz or cfg.metrics
    if needs_pacing:
        scheduler.run(max_generations=cfg.steps)
    else:
        coordinator.run(cfg.steps)

    if args.render == "final":
        ConsoleRenderer(ansi=False)(coordinator.current_frame())
    elif args.render == "off" and cfg.track_population:
        # --population with --render off still reports the number (live and
        # final rendering already show it in the status line)
        frame = coordinator.current_frame()
        print(f"gen {frame.generation}  pop {frame.population}")

    if cfg.checkpoint:
        from .utils import checkpoint as ckpt_lib

        path = ckpt_lib.save(coordinator.engine, cfg.checkpoint)
        print(f"checkpoint written: {path}", file=sys.stderr)

    coordinator.engine.block_until_ready()
    return 0


if __name__ == "__main__":
    sys.exit(main())
