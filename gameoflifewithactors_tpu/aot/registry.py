"""AOT executable registry (warm-start layer 2): ship compiled runners.

The persistent compilation cache (layer 1, :mod:`.cache`) removes the
XLA ``backend_compile`` from a warm process but still pays trace +
lowering of the Python runner every time. This layer removes that too:
a spec's multi-step runner is lowered once (``jit.lower().compile()``
semantics via ``jax.export``, which serializes the lowered StableHLO
module plus calling convention), written under the spec's
:meth:`~.spec.EngineSpec.cache_key`, and a fresh process deserializes
and calls it directly — no Python re-trace, and the tiny wrapper module
that is still XLA-compiled on load rides the layer-1 disk cache.

Registry layout (``<cache_root>/aot/``)::

    <key>.jaxexport   the jax.export blob
    <key>.json        meta: canonical spec, environment fingerprint,
                      runner name, state aval, created_at

The key hashes spec + jax/jaxlib version + platform fingerprint, so an
artifact from another environment is simply not found; when an artifact
for the same spec exists under a *different* environment, the loader
names it in a warning and falls back to JIT. Any load failure —
corrupt blob, deserialization error, changed calling convention — is a
warning + JIT fallback, never an error: AOT is an optimization, not a
correctness layer.

Scope: single-device engines on the XLA paths (packed / dense /
bit-plane / bit-sliced — every family). Sharded engines and the sparse
backend keep their JIT path (layer 1 still serves them); the Pallas
kernels are Mosaic-compiled inside XLA and likewise covered by layer 1.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import warnings
from typing import Callable, Optional, Tuple

from . import cache as cache_lib
from .spec import EngineSpec, environment_fingerprint

ENV_AOT = "GOLTPU_AOT"
_FORMAT_VERSION = 1


def aot_enabled() -> bool:
    return os.environ.get(ENV_AOT, "1").strip().lower() \
        not in cache_lib._DISABLED_VALUES


class AotUnsupported(ValueError):
    """This engine configuration has no serializable AOT runner."""


def _exportable_runner(engine) -> Tuple[Callable, str]:
    """The ``(state, n) -> state`` jitted callable behind ``engine._run``
    and its name — the ``optionally_donated`` wrappers expose their
    underlying jit as ``.jitted`` precisely for this kind of
    introspection. Raises AotUnsupported for configurations whose runner
    is not one plain jitted XLA function."""
    if engine.mesh is not None:
        raise AotUnsupported(
            "sharded engines keep the JIT path (the persistent "
            "compilation cache still warm-starts them)")
    if engine._sparse is not None:
        raise AotUnsupported(
            "the sparse backend's stepper is stateful (activity map + "
            "overflow handling), not one exportable (state, n) runner")
    if engine.backend == "pallas":
        raise AotUnsupported(
            "pallas runners are Mosaic kernels compiled inside XLA; "
            "they warm-start through the persistent compilation cache")
    if engine._ltl_packed:
        from ..ops.packed_ltl import multi_step_ltl_packed as fn
    elif engine._ltl_planes:
        from ..ops.packed_ltl import multi_step_ltl_planes as fn
    elif engine._ltl:
        from ..ops.ltl import multi_step_ltl as fn
    elif engine._gen_packed:
        from ..ops.packed_generations import multi_step_packed_generations as fn
    elif engine._generations:
        from ..ops.generations import multi_step_generations as fn
    elif engine._packed:
        from ..ops.packed import multi_step_packed as fn
    else:
        from ..ops.stencil import multi_step as fn
    return fn.jitted, fn.__name__


def _paths(key: str, registry_dir: str) -> Tuple[str, str]:
    return (os.path.join(registry_dir, key + ".jaxexport"),
            os.path.join(registry_dir, key + ".json"))


def serialize_engine(engine, registry_dir: Optional[str] = None) -> str:
    """Lower + export the engine's multi-step runner and write it under
    the spec's cache key; returns the blob path. The engine's own state
    array provides the aval, so the exported module steps exactly the
    layout the engine runs (packed words / plane stacks / bytes)."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    registry_dir = registry_dir if registry_dir is not None \
        else cache_lib.aot_registry_dir()
    if registry_dir is None:
        raise ValueError("AOT registry disabled (GOLTPU_CACHE_DIR off)")
    jitted, runner_name = _exportable_runner(engine)
    spec = EngineSpec.from_engine(engine)
    env = environment_fingerprint()
    key = spec.cache_key(env)
    state = engine.state
    exp = jax_export.export(jitted)(
        jax.ShapeDtypeStruct(state.shape, state.dtype),
        jax.ShapeDtypeStruct((), jnp.int32),
        rule=engine.rule, topology=engine.topology)
    blob = exp.serialize()
    # execute the EXPORTED form once: a loaded artifact is re-wrapped as
    # a call_exported module whose persistent-cache key differs from the
    # original jit's, so without this the first warm process would pay
    # the whole XLA compile again (measured: the R2 LtL spec's 48 s came
    # right back). One extra compile here, at warmup time, buys the
    # ~zero-compile load everywhere else.
    # goltpu: ignore[GOL006] -- warmup-time priming execution; AOT loads have their own attribution (record_aot_load)
    jax.jit(exp.call)(jnp.zeros_like(state),
                      jnp.int32(1)).block_until_ready()
    os.makedirs(registry_dir, exist_ok=True)
    blob_path, meta_path = _paths(key, registry_dir)
    meta = {
        "format_version": _FORMAT_VERSION,
        "spec": spec.canonical(),
        "env": env,
        "runner": runner_name,
        "state_shape": list(state.shape),
        "state_dtype": str(state.dtype),
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    # blob first, meta last: a meta file is the commit record — a crash
    # between the writes leaves an orphan blob, never a dangling meta
    tmp = blob_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, blob_path)
    with open(meta_path + ".tmp", "w") as f:
        json.dump(meta, f, indent=1)
        f.write("\n")
    os.replace(meta_path + ".tmp", meta_path)
    return blob_path


def _mismatch_candidates(spec: EngineSpec, registry_dir: str) -> list:
    """Meta records in the registry for this spec under OTHER
    environments (the version-mismatch warning's evidence)."""
    want = spec.canonical()
    out = []
    try:
        names = os.listdir(registry_dir)
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(registry_dir, name)) as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if meta.get("spec") == want:
            out.append(meta)
    return out


def load_runner(spec_or_engine, registry_dir: Optional[str] = None,
                ) -> Optional[Callable]:
    """Load the AOT runner for a spec/engine; None (after at most one
    warning) when no loadable artifact exists. The returned callable is
    ``(state, n) -> state``, jit-wrapped so repeated calls reuse one
    loaded executable."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    from ..obs import compile as obs_compile

    registry_dir = registry_dir if registry_dir is not None \
        else cache_lib.aot_registry_dir()
    if registry_dir is None or not os.path.isdir(registry_dir):
        return None
    spec = (spec_or_engine if isinstance(spec_or_engine, EngineSpec)
            else EngineSpec.from_engine(spec_or_engine))
    if spec.backend == "auto":
        # artifacts are filed under the RESOLVED backend (serialize_engine
        # works from a live engine); resolving costs one engine build
        spec = spec.resolve()
    env = environment_fingerprint()
    key = spec.cache_key(env)
    blob_path, meta_path = _paths(key, registry_dir)
    if not os.path.exists(meta_path) or not os.path.exists(blob_path):
        for meta in _mismatch_candidates(spec, registry_dir):
            got = meta.get("env", {})
            if got != env:
                diff = ", ".join(
                    f"{k}: {got.get(k)!r} != {env.get(k)!r}"
                    for k in sorted(set(got) | set(env))
                    if got.get(k) != env.get(k))
                warnings.warn(
                    f"AOT artifact for {spec.describe()} exists but was "
                    f"built for a different environment ({diff}); "
                    "falling back to JIT (re-run warmup to refresh)",
                    RuntimeWarning, stacklevel=3)
                break
        return None
    t0 = time.perf_counter()
    try:
        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("format_version") != _FORMAT_VERSION:
            raise ValueError(
                f"registry format {meta.get('format_version')} != "
                f"{_FORMAT_VERSION}")
        with open(blob_path, "rb") as f:
            exp = jax_export.deserialize(f.read())
        # goltpu: ignore[GOL006] -- the load path is attributed via record_aot_load below; the wrapper compile rides the persistent cache
        call = jax.jit(exp.call)
    except Exception as exc:
        warnings.warn(
            f"AOT artifact for {spec.describe()} failed to load "
            f"({type(exc).__name__}: {exc}); falling back to JIT",
            RuntimeWarning, stacklevel=3)
        return None
    obs_compile.record_aot_load(
        meta.get("runner", "aot"),
        f"{meta.get('state_dtype')}[{','.join(map(str, meta.get('state_shape', [])))}]",
        time.perf_counter() - t0)

    def run(state, n):
        return call(state, jnp.int32(int(n)))

    run.aot_key = key  # introspection: which artifact serves this engine
    return run


def maybe_load_for_engine(engine) -> Optional[Callable]:
    """Engine-constructor hook: the AOT runner when one is registered for
    this exact configuration + environment, else None — cheap (one hash
    + one stat) on the miss path, silent unless an artifact exists but
    cannot serve."""
    if not aot_enabled():
        return None
    try:
        _exportable_runner(engine)  # cheap support gate, no tracing
    except AotUnsupported:
        return None
    return load_runner(engine)
