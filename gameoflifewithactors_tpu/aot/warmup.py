"""Warmup precompile pipeline (layer 3): populate the caches before serving.

``python -m gameoflifewithactors_tpu warmup --manifest specs.json`` (or
``--from-config`` + the normal CLI flags) builds each spec's engine and
steps it through every runner signature the serving process will hit —
the single-generation call and the bulk chunk call — so the persistent
compilation cache holds all of them; with ``--aot`` (default) it also
serializes the runner into the AOT registry. A fleet rollout runs this
once per (jax version × platform) before taking traffic; CI runs it
implicitly by caching the cache dir across runs (tier1.yml).

The manifest is a JSON list of EngineSpec dicts::

    [{"rule": "B3/S23", "shape": [4096, 4096], "backend": "packed"},
     {"rule": "brain", "shape": [1024, 1024], "backend": "packed"},
     {"rule": "R2,C0,M1,S2..6,B3..5,NM", "shape": [512, 512],
      "backend": "packed", "topology": "dead"}]
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

from . import cache as cache_lib
from . import registry as registry_lib
from .spec import EngineSpec


def load_manifest(path: str) -> List[EngineSpec]:
    with open(path) as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(
            f"manifest {path} must be a JSON list of spec objects")
    return [EngineSpec.from_dict(e) for e in entries]


def warmup_spec(spec: EngineSpec, *, aot: bool = True) -> dict:
    """Precompile one spec: build its engine, exercise the per-generation
    and bulk runner signatures, optionally serialize the AOT runner.
    Returns a report row (wall/compile seconds, event kinds, aot status).
    """
    from ..obs import compile as obs_compile

    log = obs_compile.COMPILE_LOG
    n_before = len(log.events())
    t0 = time.perf_counter()
    engine = spec.build_engine()
    # both signatures the serving process uses: one generation (the
    # remainder path) and a bulk chunk (> gens_per_exchange, so chunked
    # runners compile their deep runner too)
    engine.step(1)
    bulk = max(2, engine.gens_per_exchange + 1)
    engine.step(bulk)
    engine.block_until_ready()
    aot_status: Optional[str] = None
    if aot:
        try:
            registry_lib.serialize_engine(engine)
            aot_status = "serialized"
        except registry_lib.AotUnsupported as exc:
            aot_status = f"unsupported: {exc}"
        except Exception as exc:  # pragma: no cover - env-dependent
            aot_status = f"failed: {type(exc).__name__}: {exc}"
    wall = time.perf_counter() - t0
    events = log.events()[n_before:]
    kinds: dict = {}
    for e in events:
        kinds[e.kind] = kinds.get(e.kind, 0) + 1
    return {
        "spec": spec.canonical(),
        "resolved_backend": engine.backend,
        "wall_seconds": wall,
        "compile_seconds": sum(e.wall_seconds for e in events
                               if e.kind == "cache_miss"),
        "events": kinds,
        "aot": aot_status,
    }


def warmup_specs(specs, *, aot: bool = True, cache_dir: Optional[str] = None,
                 verbose=None) -> List[dict]:
    """The pipeline: enable the persistent cache, then warm every spec.
    ``verbose`` is a print-like callable for progress lines (or None)."""
    enabled = cache_lib.ensure_persistent_cache(cache_dir)
    if verbose:
        verbose(f"persistent compilation cache: {enabled or 'DISABLED'}")
    rows = []
    for spec in specs:
        if verbose:
            verbose(f"warming {spec.describe()} ...")
        row = warmup_spec(spec, aot=aot)
        rows.append(row)
        if verbose:
            verbose(
                f"  {row['wall_seconds']:.2f}s wall, "
                f"{row['compile_seconds']:.2f}s compiling, "
                f"events {row['events'] or '{}'}"
                + (f", aot: {row['aot']}" if row["aot"] else ""))
    return rows
