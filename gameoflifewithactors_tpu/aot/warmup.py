"""Warmup precompile pipeline (layer 3): populate the caches before serving.

``python -m gameoflifewithactors_tpu warmup --manifest specs.json`` (or
``--from-config`` + the normal CLI flags) builds each spec's engine and
steps it through every runner signature the serving process will hit —
the single-generation call and the bulk chunk call — so the persistent
compilation cache holds all of them; with ``--aot`` (default) it also
serializes the runner into the AOT registry. A fleet rollout runs this
once per (jax version × platform) before taking traffic; CI runs it
implicitly by caching the cache dir across runs (tier1.yml).

The manifest is a JSON list of EngineSpec dicts::

    [{"rule": "B3/S23", "shape": [4096, 4096], "backend": "packed"},
     {"rule": "brain", "shape": [1024, 1024], "backend": "packed"},
     {"rule": "R2,C0,M1,S2..6,B3..5,NM", "shape": [512, 512],
      "backend": "packed", "topology": "dead"}]

An entry may additionally carry a **lane ladder** — the batch
capacities the session service (serve/lanes.py) will dispatch this rule
family at::

    [{"rule": "B3/S23", "shape": [256, 256], "backend": "packed",
      "lanes": [1, 8, 64, 256]}]

Lane entries trace the *masked batched* runner at every listed capacity
(``serve.lanes.warm_family``), so a fresh server process warm-starts
every lane shape it will ever use — placement, growth, and compaction
across the ladder then cause zero post-warm ``cache_miss`` events.
``results/serve_manifest.json`` is the shipped example. Extras such as
``lanes`` are manifest-level vocabulary: they are peeled off before
``EngineSpec.from_dict`` (which by design rejects unknown fields).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional, Sequence, Tuple

from . import cache as cache_lib
from . import registry as registry_lib
from .spec import EngineSpec

# manifest keys that configure warmup itself rather than the engine;
# EngineSpec.from_dict stays strict about everything else
MANIFEST_EXTRAS = ("lanes",)


def load_manifest_entries(path: str) -> List[Tuple[EngineSpec, dict]]:
    """Parse a manifest into (spec, extras) pairs, where ``extras`` holds
    the warmup-level keys (:data:`MANIFEST_EXTRAS`) the entry carried."""
    with open(path) as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(
            f"manifest {path} must be a JSON list of spec objects")
    out: List[Tuple[EngineSpec, dict]] = []
    for e in entries:
        e = dict(e)
        extras = {k: e.pop(k) for k in MANIFEST_EXTRAS if k in e}
        if "lanes" in extras:
            lanes = extras["lanes"]
            if (not isinstance(lanes, list) or not lanes
                    or not all(isinstance(c, int) and c > 0 for c in lanes)):
                raise ValueError(
                    f"manifest {path}: 'lanes' must be a non-empty list "
                    f"of positive batch capacities, got {lanes!r}")
        out.append((EngineSpec.from_dict(e), extras))
    return out


def load_manifest(path: str) -> List[EngineSpec]:
    return [spec for spec, _extras in load_manifest_entries(path)]


def _warm_lanes(spec: EngineSpec, lanes: Sequence[int]) -> str:
    """Trace the masked batched lane runner at each ladder capacity.
    Imported lazily — aot/ must not pull the serve layer (and its jax
    surface) in for manifest-only consumers."""
    from ..serve import lanes as lanes_lib

    d = spec.canonical()
    d["mesh"] = None  # lanes are single-device by contract (serve/lanes.py)
    family = lanes_lib.SpecFamily.from_spec(d)
    lanes_lib.warm_family(family, tuple(int(c) for c in lanes))
    return f"warmed {len(lanes)} capacities for {family.key}"


def warmup_spec(spec: EngineSpec, *, aot: bool = True,
                lanes: Optional[Sequence[int]] = None) -> dict:
    """Precompile one spec: build its engine, exercise the per-generation
    and bulk runner signatures, optionally serialize the AOT runner and
    trace the lane-ladder batch shapes. Returns a report row (wall/
    compile seconds, event kinds, aot + lane status).
    """
    from ..obs import compile as obs_compile

    log = obs_compile.COMPILE_LOG
    n_before = len(log.events())
    t0 = time.perf_counter()
    engine = spec.build_engine()
    # both signatures the serving process uses: one generation (the
    # remainder path) and a bulk chunk (> gens_per_exchange, so chunked
    # runners compile their deep runner too)
    engine.step(1)
    bulk = max(2, engine.gens_per_exchange + 1)
    engine.step(bulk)
    engine.block_until_ready()
    aot_status: Optional[str] = None
    if aot:
        try:
            registry_lib.serialize_engine(engine)
            aot_status = "serialized"
        except registry_lib.AotUnsupported as exc:
            aot_status = f"unsupported: {exc}"
        except Exception as exc:  # pragma: no cover - env-dependent
            aot_status = f"failed: {type(exc).__name__}: {exc}"
    lanes_status: Optional[str] = None
    if lanes:
        try:
            lanes_status = _warm_lanes(spec, lanes)
        except ValueError as exc:
            # a family the lane layer refuses (multi-state rule, sharded
            # mesh, unpackable width) is a manifest authoring error the
            # report must surface, not a warmup crash
            lanes_status = f"unsupported: {exc}"
    wall = time.perf_counter() - t0
    events = log.events()[n_before:]
    kinds: dict = {}
    for e in events:
        kinds[e.kind] = kinds.get(e.kind, 0) + 1
    row = {
        "spec": spec.canonical(),
        "resolved_backend": engine.backend,
        "wall_seconds": wall,
        "compile_seconds": sum(e.wall_seconds for e in events
                               if e.kind == "cache_miss"),
        "events": kinds,
        "aot": aot_status,
    }
    if lanes:
        row["lanes"] = {"capacities": list(lanes), "status": lanes_status}
    return row


def warmup_specs(specs, *, aot: bool = True, cache_dir: Optional[str] = None,
                 verbose=None) -> List[dict]:
    """The pipeline: enable the persistent cache, then warm every spec.
    ``specs`` is a list of EngineSpec or (EngineSpec, extras) pairs (the
    :func:`load_manifest_entries` shape — extras may carry ``lanes``).
    ``verbose`` is a print-like callable for progress lines (or None)."""
    enabled = cache_lib.ensure_persistent_cache(cache_dir)
    if verbose:
        verbose(f"persistent compilation cache: {enabled or 'DISABLED'}")
    rows = []
    for item in specs:
        spec, extras = item if isinstance(item, tuple) else (item, {})
        if verbose:
            verbose(f"warming {spec.describe()} ...")
        row = warmup_spec(spec, aot=aot, lanes=extras.get("lanes"))
        rows.append(row)
        if verbose:
            verbose(
                f"  {row['wall_seconds']:.2f}s wall, "
                f"{row['compile_seconds']:.2f}s compiling, "
                f"events {row['events'] or '{}'}"
                + (f", aot: {row['aot']}" if row["aot"] else "")
                + (f", lanes: {row['lanes']['status']}"
                   if row.get("lanes") else ""))
    return rows
