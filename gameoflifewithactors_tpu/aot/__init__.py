"""Warm-start subsystem: make the second process pay ~zero compile time.

Three layers over one motivation (ISSUE 2 / PR 1's telemetry: first-tick
wall time is compilation, 71 s for one Pallas kernel, >10 min for one
CPU LtL compile):

- :mod:`.cache` — the **persistent XLA compilation cache**, on by
  default under ``~/.cache/gameoflifewithactors_tpu/`` (``GOLTPU_CACHE_DIR``
  env / ``--cache-dir`` to move or disable), thresholds zeroed so every
  jitted runner round-trips through disk;
- :mod:`.spec` + :mod:`.registry` — **EngineSpec** canonically hashes a
  runner configuration with the jax/jaxlib/platform fingerprint, and the
  **AOT registry** serializes lowered multi-step runners (``jax.export``)
  so a fresh process loads instead of re-tracing, falling back to JIT
  (with a warning) on any mismatch;
- :mod:`.warmup` — the **precompile pipeline** behind the ``warmup`` CLI
  subcommand: walk a manifest of specs, populate both caches ahead of
  serving.

Attribution lands in :mod:`..obs.compile`: every compile event carries
``kind`` ∈ {``cache_miss``, ``cache_hit``, ``aot_loaded``}, and only real
misses count as compile seconds — a warm RunReport shows
``compile_seconds`` ≈ 0.
"""

from .cache import (  # noqa: F401
    ENV_CACHE_DIR,
    current_cache_dir,
    default_cache_root,
    ensure_persistent_cache,
    resolve_cache_root,
)
from .spec import EngineSpec, environment_fingerprint  # noqa: F401
from .registry import (  # noqa: F401
    AotUnsupported,
    ENV_AOT,
    aot_enabled,
    load_runner,
    maybe_load_for_engine,
    serialize_engine,
)
from .warmup import load_manifest, warmup_spec, warmup_specs  # noqa: F401

__all__ = [
    "ENV_CACHE_DIR", "ENV_AOT",
    "current_cache_dir", "default_cache_root", "ensure_persistent_cache",
    "resolve_cache_root",
    "EngineSpec", "environment_fingerprint",
    "AotUnsupported", "aot_enabled", "load_runner", "maybe_load_for_engine",
    "serialize_engine",
    "load_manifest", "warmup_spec", "warmup_specs",
]
