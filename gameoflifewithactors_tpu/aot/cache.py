"""Persistent XLA compilation cache wiring (warm-start layer 1).

PR 1's telemetry made first-tick cost visible: the wall time of a cold
process is dominated by XLA/Mosaic compilation (71 s for the first native
``pallas_generations`` compile; one CPU ``backend_compile`` of the R4
diamond LtL kernel exceeds 10 minutes), and every fresh process pays it
again for programs that have not changed. JAX ships the fix — a
disk-backed compilation cache keyed on the serialized computation +
jaxlib version + compile options — but it is off by default and its
default thresholds (1 s compile time / 32 KiB entries) skip exactly the
long tail of small runners this framework compiles. This module turns it
on, everywhere, with thresholds at zero, so **the second process to
compile any runner pays a disk read instead of a compile**.

Resolution order for the cache root:

1. an explicit path (``SimulationConfig.cache_dir`` / ``--cache-dir``);
2. the ``GOLTPU_CACHE_DIR`` environment variable — a path, or one of
   ``""``/``0``/``off``/``none`` to disable caching entirely;
3. the default ``~/.cache/gameoflifewithactors_tpu/``.

The XLA cache lives under ``<root>/xla``; the AOT executable registry
(:mod:`.registry`, layer 2) under ``<root>/aot``. A pre-existing
user-level ``jax_compilation_cache_dir`` config (or
``JAX_COMPILATION_CACHE_DIR`` env) is respected and never overridden —
the user already chose a cache.

``ensure_persistent_cache`` is idempotent and thread-safe; it is called
from ``Engine.__init__``, the CLI, ``bench.py`` and the ``warmup``
pipeline, so library users get the warm path without any setup. It also
registers a ``jax.monitoring`` listener that forwards the cache's
hit/miss events to :mod:`..obs.compile`, which is what lets a RunReport
attribute each compile event as ``cache_hit`` vs ``cache_miss``.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Optional

ENV_CACHE_DIR = "GOLTPU_CACHE_DIR"
_DISABLED_VALUES = ("", "0", "off", "none", "disabled")

_lock = threading.Lock()
_state = {
    "enabled_dir": None,     # the XLA cache dir we configured, or None
    "attempted": False,      # ensure_persistent_cache ran at least once
    "listener_installed": False,
}


def default_cache_root() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "gameoflifewithactors_tpu")


def resolve_cache_root(explicit: Optional[str] = None) -> Optional[str]:
    """The cache root directory, or None when caching is disabled."""
    if explicit is not None:
        return explicit or None
    env = os.environ.get(ENV_CACHE_DIR)
    if env is not None:
        if env.strip().lower() in _DISABLED_VALUES:
            return None
        return env
    return default_cache_root()


def xla_cache_dir(root: str) -> str:
    return os.path.join(root, "xla")


def aot_registry_dir(root: Optional[str] = None) -> Optional[str]:
    root = resolve_cache_root() if root is None else root
    return None if root is None else os.path.join(root, "aot")


def _install_listener() -> None:
    """Forward jax's compilation-cache monitoring events to obs.compile.

    The events fire inside ``backend_compile``: ``cache_hits`` when a
    compiled executable was served from disk, ``cache_misses`` when a
    real compile ran (and its result was written back). obs.compile
    snapshots the counters around each tracked jit call to attribute the
    call's CompileEvent. Installed once per process."""
    if _state["listener_installed"]:
        return
    from jax._src import monitoring

    from ..obs import compile as obs_compile

    def _on_event(event: str, **kwargs) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            obs_compile.note_persistent_cache_event("hit")
        elif event == "/jax/compilation_cache/cache_misses":
            obs_compile.note_persistent_cache_event("miss")

    monitoring.register_event_listener(_on_event)
    _state["listener_installed"] = True


def ensure_persistent_cache(explicit: Optional[str] = None) -> Optional[str]:
    """Enable jax's persistent compilation cache; returns the XLA cache
    dir in effect, or None when disabled.

    Idempotent: the first call wins (an explicit path on a later call
    re-points the cache — the CLI parses flags after the first Engine
    may exist). Never overrides a cache dir the user already configured
    through jax itself. Failures are a warning, not an error — a
    read-only home directory must not take an engine down."""
    import jax

    with _lock:
        root = resolve_cache_root(explicit)
        if root is None:
            _state["attempted"] = True
            return (jax.config.jax_compilation_cache_dir
                    if jax.config.jax_compilation_cache_dir else None)
        pre_existing = jax.config.jax_compilation_cache_dir
        if pre_existing and pre_existing != _state["enabled_dir"]:
            # the user (or another library) already chose a cache dir:
            # respect it, but still lower the thresholds and listen —
            # warm-start semantics apply to whichever cache is active
            target = pre_existing
        else:
            target = xla_cache_dir(root)
        if _state["attempted"] and _state["enabled_dir"] == target \
                and explicit is None:
            return target
        try:
            os.makedirs(target, exist_ok=True)
            repointing = (jax.config.jax_compilation_cache_dir or "") != target
            jax.config.update("jax_compilation_cache_dir", target)
            if repointing:
                # jax binds its cache handle to the dir at first use and
                # ignores later config updates; drop the handle so the
                # new dir actually takes effect (tests re-point per case)
                try:
                    from jax._src import compilation_cache as _cc

                    _cc.reset_cache()
                except Exception:
                    pass
            # cache EVERYTHING: the default 1 s / 32 KiB thresholds skip
            # the long tail of small runners (dozens per engine) whose
            # re-trace+compile still dominates a cold tick
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            _install_listener()
            _state["enabled_dir"] = target
            _state["attempted"] = True
            return target
        except Exception as exc:
            _state["attempted"] = True
            warnings.warn(
                f"persistent compilation cache unavailable at {target} "
                f"({type(exc).__name__}: {exc}); compiles will not be "
                "cached across processes", RuntimeWarning, stacklevel=2)
            return None


def current_cache_dir() -> Optional[str]:
    """The XLA cache dir this process configured (None when disabled or
    not yet enabled)."""
    return _state["enabled_dir"]
