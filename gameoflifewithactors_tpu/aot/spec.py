"""EngineSpec: the canonical identity of a compiled runner (layer 2 keys).

A warm-start artifact — a persistent-cache entry or a serialized AOT
executable — is only valid for the exact configuration that produced it:
the rule, grid shape, backend, topology, mesh decomposition and exchange
depth shape the lowered program, and the jax/jaxlib version plus platform
fingerprint shape the compiled artifact. ``EngineSpec`` pins the first
group as one hashable value; :func:`environment_fingerprint` pins the
second; :meth:`EngineSpec.cache_key` folds both into the content hash the
AOT registry files executables under, so a stale artifact can never be
served to a mismatched process — it simply hashes elsewhere.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional, Tuple


def environment_fingerprint() -> dict:
    """What must match for a compiled artifact to be loadable here:
    jax + jaxlib versions and the backend platform/device kind/count."""
    import jax
    import jaxlib

    try:
        devs = jax.devices()
        platform = devs[0].platform
        device_kind = devs[0].device_kind
        device_count = len(devs)
    except Exception:  # no backend (wedged tunnel): still hashable
        platform, device_kind, device_count = "unknown", "unknown", 0
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
        "device_kind": device_kind,
        "device_count": device_count,
    }


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """One runner configuration, in engine-constructor vocabulary.

    ``backend`` may be ``"auto"``; hashing canonicalizes through an
    actual Engine construction (:meth:`resolve`) so two specs that
    resolve to the same runner share cache entries.
    """

    height: int
    width: int
    rule: str = "B3/S23"
    backend: str = "auto"
    topology: str = "torus"
    mesh: Optional[Tuple[int, int]] = None   # (nx, ny) device mesh, or None
    gens_per_exchange: int = 1

    # -- construction --------------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "EngineSpec":
        d = dict(d)
        if "shape" in d:  # manifest convenience: "shape": [H, W]
            d["height"], d["width"] = d.pop("shape")
        mesh = d.get("mesh")
        if mesh is not None:
            d["mesh"] = tuple(int(x) for x in mesh)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown EngineSpec fields {sorted(unknown)}; "
                f"expected a subset of {sorted(known)} (or 'shape')")
        return cls(**d)

    @classmethod
    def from_config(cls, cfg) -> "EngineSpec":
        """From a SimulationConfig (the CLI's ``warmup --from-config``)."""
        mesh = None
        m = cfg.build_mesh()
        if m is not None:
            from ..parallel import mesh as mesh_lib

            mesh = (m.shape[mesh_lib.ROW_AXIS], m.shape[mesh_lib.COL_AXIS])
        return cls(height=cfg.height, width=cfg.width, rule=cfg.rule,
                   backend=cfg.backend, topology=cfg.topology, mesh=mesh,
                   gens_per_exchange=cfg.gens_per_exchange)

    @classmethod
    def from_engine(cls, engine) -> "EngineSpec":
        """From a live Engine — ``backend`` is the RESOLVED one, so the
        spec round-trips to the same runner the engine actually built."""
        from ..parallel import mesh as mesh_lib

        mesh = None
        if engine.mesh is not None:
            mesh = (engine.mesh.shape[mesh_lib.ROW_AXIS],
                    engine.mesh.shape[mesh_lib.COL_AXIS])
        return cls(height=engine.shape[0], width=engine.shape[1],
                   rule=engine.rule.notation, backend=engine.backend,
                   topology=engine.topology.value, mesh=mesh,
                   gens_per_exchange=engine.gens_per_exchange)

    # -- engine assembly -----------------------------------------------------

    def build_engine(self, grid=None):
        """Construct the Engine this spec names (all-dead universe by
        default — compilation depends on shapes/dtypes, never on cell
        values, so warmup and AOT serialization need no seed)."""
        import numpy as np

        from ..engine import Engine
        from ..ops.stencil import Topology
        from ..parallel import mesh as mesh_lib

        if grid is None:
            grid = np.zeros((self.height, self.width), dtype=np.uint8)
        mesh = mesh_lib.make_mesh(self.mesh) if self.mesh else None
        return Engine(grid, self.rule, topology=Topology(self.topology),
                      mesh=mesh, backend=self.backend,
                      gens_per_exchange=self.gens_per_exchange)

    def resolve(self) -> "EngineSpec":
        """The spec with ``backend`` (and ``gens_per_exchange``, which
        the band runners normalize) resolved through a real Engine
        construction — the canonical form the registry hashes."""
        if self.backend != "auto":
            return self
        return EngineSpec.from_engine(self.build_engine())

    # -- identity ------------------------------------------------------------

    def canonical(self) -> dict:
        """Canonical rule notation + sorted fields, environment excluded."""
        from ..models.generations import parse_any

        d = dataclasses.asdict(self)
        d["rule"] = parse_any(self.rule).notation
        if d["mesh"] is not None:
            d["mesh"] = list(d["mesh"])
        return d

    def cache_key(self, fingerprint: Optional[dict] = None) -> str:
        """Content hash naming this spec's artifacts: canonical spec +
        environment fingerprint, sha256-hex (first 24 chars — plenty
        against collision across a registry of hand-counted specs)."""
        payload = {
            "spec": self.canonical(),
            "env": fingerprint if fingerprint is not None
            else environment_fingerprint(),
        }
        blob = json.dumps(payload, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:24]

    def describe(self) -> str:
        mesh = f" mesh={self.mesh[0]}x{self.mesh[1]}" if self.mesh else ""
        g = (f" G={self.gens_per_exchange}"
             if self.gens_per_exchange != 1 else "")
        return (f"{self.rule} {self.height}x{self.width} "
                f"[{self.backend}/{self.topology}{mesh}{g}]")
