"""GridCoordinator — the actor-shaped façade over the stencil engine.

The reference's ``GridCoordinator`` actor spawns an N×M grid of
``CellActor``s, wires each to its 8 Moore neighbors, broadcasts Tick,
barriers on N·M replies, and hands each finished generation to a renderer
(BASELINE.json north_star; SURVEY.md §2/§4 — reference mount empty, names
from driver metadata). This class preserves that *surface* — construct,
tick, run, snapshot, subscribe — while deleting the machinery:

- spawn/wire  → array allocation (the neighbor graph is implicit in the
  stencil's index arithmetic);
- Tick broadcast + reply barrier → one fused jit step (SPMD dataflow *is*
  the barrier);
- per-cell mailbox update → one VPU lane of the bit-packed kernel. A
  ``CellActor`` survives as this documented equivalence, not as an object:
  cell (r, c)'s "mailbox" is bit (32·j+i) of word (r, j); its "receive" is
  the carry-save neighbor sum; its "Tell" is the halo/shift data movement.

Subscribers play the reference's Renderer role: callables invoked after
each tick (or every ``render_every`` ticks) with a RenderFrame.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from .engine import Engine
from .models import seeds as seeds_lib
from .models.rules import Rule, parse_rule
from .obs import compile as obs_compile
from .obs import flight as obs_flight
from .obs import spans as obs_spans
from .obs import watchdog as obs_watchdog
from .ops.stencil import Topology
from .utils.metrics import MetricsLogger, StepMetrics


@dataclasses.dataclass(frozen=True)
class RenderFrame:
    """What a subscriber sees after a tick — the analogue of the grid the
    reference's coordinator hands its Renderer each generation."""

    grid: np.ndarray            # possibly downsampled uint8 view
    generation: int
    population: Optional[int]   # None unless track_population is on
    full_shape: Tuple[int, int]


Subscriber = Callable[[RenderFrame], None]


class GridCoordinator:
    """Facade: construct(grid, rule, seed) / tick() / run(n) / snapshot()."""

    def __init__(
        self,
        shape: Tuple[int, int],
        rule: "Rule | str" = "B3/S23",
        *,
        seed: "str | np.ndarray | None" = None,
        seed_origin: Optional[Tuple[int, int]] = None,
        random_fill: Optional[float] = None,
        rng_seed: int = 0,
        topology: Topology = Topology.TORUS,
        mesh: Optional[Mesh] = None,
        backend: str = "auto",
        sparse_opts: Optional[dict] = None,
        gens_per_exchange: int = 1,
        track_population: bool = False,
        metrics: Optional[MetricsLogger] = None,
        view_shape: Optional[Tuple[int, int]] = None,
    ):
        grid = self._build_seed(shape, seed, seed_origin, random_fill, rng_seed)
        engine = Engine(grid, rule, topology=topology, mesh=mesh, backend=backend,
                        sparse_opts=sparse_opts,
                        gens_per_exchange=gens_per_exchange)
        self._init_from_engine(engine, track_population, metrics, view_shape)

    def _init_from_engine(self, engine, track_population, metrics, view_shape) -> None:
        self.engine = engine
        self.track_population = track_population
        self.metrics = metrics
        self.view_shape = view_shape
        self._subscribers: List[Subscriber] = []

    @classmethod
    def from_engine(
        cls,
        engine: Engine,
        *,
        track_population: bool = False,
        metrics: Optional[MetricsLogger] = None,
        view_shape: Optional[Tuple[int, int]] = None,
    ) -> "GridCoordinator":
        """Wrap an existing Engine (e.g. one rebuilt from a checkpoint)."""
        self = cls.__new__(cls)
        self._init_from_engine(engine, track_population, metrics, view_shape)
        return self

    @staticmethod
    def _build_seed(shape, seed, seed_origin, random_fill, rng_seed) -> np.ndarray:
        import jax

        if random_fill is not None:
            if seed is not None:
                raise ValueError("give either `seed` or `random_fill`, not both")
            # copy while `filled` is still referenced: np.asarray of a CPU
            # jax.Array is a zero-copy view, and once the device array is
            # collected the view dangles — the engine would be seeded from
            # freed memory (nondeterministic grids, heap corruption under
            # the 8-fake-device test config)
            filled = seeds_lib.bernoulli(jax.random.key(rng_seed), shape,
                                         random_fill)
            return np.array(filled, copy=True)
        if seed is None:
            return seeds_lib.empty(shape)
        if isinstance(seed, str):
            pat = seeds_lib.pattern(seed)
        else:
            pat = np.asarray(seed, dtype=np.uint8)
        if seed_origin is None:
            # center the pattern, like dropping a glider into the middle
            seed_origin = (
                (shape[0] - pat.shape[0]) // 2,
                (shape[1] - pat.shape[1]) // 2,
            )
        return seeds_lib.seeded(shape, pat, *seed_origin)

    # -- reference surface ---------------------------------------------------

    @property
    def rule(self) -> Rule:
        return self.engine.rule

    @property
    def generation(self) -> int:
        return self.engine.generation

    @property
    def shape(self) -> Tuple[int, int]:
        return self.engine.shape

    def subscribe(self, fn: Subscriber) -> Callable[[], None]:
        """Register a per-tick observer; returns an unsubscribe handle."""
        self._subscribers.append(fn)
        return lambda: self._subscribers.remove(fn)

    def tick(self, n: int = 1) -> None:
        """Advance n generations and notify subscribers once (the reference
        notifies its renderer per generation; batching is the knob that
        keeps readback off the device hot loop).

        When a stall watchdog is armed (obs.watchdog.arm), the whole tick
        runs under its watch so a wedged dispatch/sync is flagged — with
        the last-completed span named — while still stuck. When a flight
        recorder is armed (obs.flight.arm), an exception escaping the
        tick leaves a crash dump before propagating — the post-mortem a
        dead coordinator loop otherwise has none of."""
        wd = obs_watchdog.active_watchdog()
        try:
            if wd is not None:
                with wd.watch(f"tick@gen{self.generation}+{n}"):
                    self._tick(n)
            else:
                self._tick(n)
        except BaseException as exc:
            fr = obs_flight.active_flight_recorder()
            if fr is not None:
                fr.dump("exception in coordinator loop: "
                        f"{type(exc).__name__}: {exc}")
            raise

    def _tick(self, n: int) -> None:
        t0 = time.perf_counter()
        with obs_spans.span("coordinator.tick", generations=n):
            self.engine.step(n)
            if self.metrics is not None:
                self.engine.block_until_ready()
                t1 = time.perf_counter()
                # compiles that completed inside this tick (ops/_jit.py
                # tracking): reported separately so wall_seconds — and the
                # rate derived from it — describe *stepping*, not the
                # one-off XLA compile the first tick happens to pay
                compile_s = obs_compile.COMPILE_LOG.compile_seconds_between(
                    t0, t1)
                dt = max(t1 - t0 - compile_s, 1e-9)
                cells = self.shape[0] * self.shape[1] * n
                self.metrics.log(
                    StepMetrics(
                        generation=self.generation,
                        generations_stepped=n,
                        wall_seconds=dt,
                        cell_updates_per_sec=cells / dt,
                        population=self.population() if self.track_population else None,
                        # the arithmetic model (pinned == the HLO figure in
                        # tests/test_halo_bytes.py): the default 'auto' source
                        # compiles a one-generation step on first use, which
                        # would stall a live render/metrics loop's first tick
                        halo_bytes=self.engine.halo_bytes_per_gen(
                            source="model") * n or None,
                        active_tiles=self.engine.active_tiles(),
                        compile_seconds=compile_s or None,
                    )
                )
            self._notify()

    def run(self, generations: int, *, render_every: int = 0) -> None:
        """Run ``generations`` generations; if render_every > 0, surface a
        frame to subscribers every that many generations."""
        if render_every and render_every > 0:
            done = 0
            while done < generations:
                chunk = min(render_every, generations - done)
                self.tick(chunk)
                done += chunk
        else:
            self.tick(generations)

    def notify_now(self) -> None:
        """Surface the current state to subscribers outside a tick — the
        supervisor calls this after a checkpoint restore so renderers see
        the rolled-back generation instead of a silent jump."""
        self._notify()

    def snapshot(self) -> np.ndarray:
        return self.engine.snapshot()

    def population(self) -> int:
        return self.engine.population()

    # -- internals -----------------------------------------------------------

    def current_frame(self) -> RenderFrame:
        """The frame a subscriber would see right now (downsampled view)."""
        return RenderFrame(
            grid=self.engine.snapshot(max_shape=self.view_shape),
            generation=self.generation,
            population=self.population() if self.track_population else None,
            full_shape=self.shape,
        )

    def _notify(self) -> None:
        if not self._subscribers:
            return
        # subscriber time (renderers, PPM writers) is host time the tick
        # pays; its own span keeps it separable from dispatch/sync
        with obs_spans.span("coordinator.notify",
                            subscribers=len(self._subscribers)):
            frame = self.current_frame()
            for fn in list(self._subscribers):
                fn(frame)
