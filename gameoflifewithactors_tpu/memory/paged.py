"""Page-table grids over the tile pool: logical universes, physical slots.

A :class:`PagedGrid` is one logical universe — bounded (TORUS or DEAD)
or an unbounded plane (``bounds=None``) — expressed as a sparse map
``tile coord -> pool slot``. Pages exist only where the universe is
interesting; everywhere else *is* the pool's canonical dead tile, by
aliasing. The host keeps the coordinate map; the device sees only the
pool's ``(B, 8)`` neighbor matrix, which this module maintains
incrementally as pages come and go (allocation rewires int32 rows — it
never reshapes an array, so it never retraces).

Activation/retirement rides ops/sparse.py's changed-last-generation wake
machinery, generalized from a dense activity map to the sparse
coordinate set (:func:`~gameoflifewithactors_tpu.ops.sparse.dilate_coords`):

- before a chunk of ``g`` generations, every page within
  ``wake_dilation(rule, ·, ·, g)`` tile rings of a changed page is
  ensured — influence travels ``r`` cells/generation, so by induction a
  would-birth front never abuts an unallocated page;
- after the chunk, pages that hold no live bit AND sit outside the wake
  ring of any changed page retire back to the free list. A still life
  keeps exactly its own page; a glider drags a moving window of pages
  across an infinite plane.

:func:`step_grids` is the multi-tenant pump: one pool dispatch advances
every prepared grid's pages together, whatever session owns them — the
"one batch of physical tiles per generation" contract that gives every
tenant the same warm executable.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..obs.registry import REGISTRY, MetricsRegistry
from ..ops import bitpack
from ..ops import sparse as _sparse
from ..ops.stencil import Topology
from ..parallel.batched import PAGED_NEIGHBORS
from .pool import DEAD_SLOT, PoolExhausted, TilePool

Coord = Tuple[int, int]


def default_chunk_gens(rule, tile_rows: int, tile_words: int) -> int:
    """The deepest chunk whose wake ring is one tile thick: g·r bounded
    by the smaller tile extent. Deeper chunks amortize the per-chunk flag
    readback without widening the allocation front past one ring."""
    r, _ = _sparse.rule_halo(rule)
    return max(1, min(tile_rows, tile_words * bitpack.WORD) // r)


class PagedGrid:
    """One logical universe mapped onto pool pages.

    ``bounds`` is the logical extent in TILE units, ``(nty, ntx)``;
    ``None`` is the unbounded plane (DEAD closure at infinity). TORUS
    needs bounds — page-table wraparound is how the torus closes, so an
    endless torus is a contradiction.
    """

    def __init__(self, pool: TilePool, *,
                 topology: Topology = Topology.DEAD,
                 bounds: Optional[Tuple[int, int]] = None):
        if topology is Topology.TORUS and bounds is None:
            raise ValueError("a TORUS universe needs bounds: the wrap IS "
                             "the page table's edge closure")
        if bounds is not None and (bounds[0] < 1 or bounds[1] < 1):
            raise ValueError(f"bounds must be positive tile counts, "
                             f"got {bounds}")
        self.pool = pool
        self.topology = topology
        self.bounds = tuple(bounds) if bounds is not None else None
        self.pages: Dict[Coord, int] = {}
        self.active: Set[Coord] = set()
        self.generation = 0

    # -- page-table maintenance ----------------------------------------------

    def _neighbor_coord(self, c: Coord, off: Coord) -> Optional[Coord]:
        y, x = c[0] + off[0], c[1] + off[1]
        if self.bounds is not None:
            nty, ntx = self.bounds
            if self.topology is Topology.TORUS:
                return (y % nty, x % ntx)
            if not (0 <= y < nty and 0 <= x < ntx):
                return None  # beyond the DEAD edge
        return (y, x)

    def _link(self, c: Coord, slot: int) -> None:
        nbr = self.pool.neighbors
        for i, off in enumerate(PAGED_NEIGHBORS):
            c2 = self._neighbor_coord(c, off)
            s2 = DEAD_SLOT if c2 is None else self.pages.get(c2, DEAD_SLOT)
            nbr[slot, i] = s2
            if s2 != DEAD_SLOT:
                nbr[s2, 7 - i] = slot  # reciprocal direction

    def _unlink(self, c: Coord) -> None:
        # incoming edges only; pool.release zeroes the outgoing row
        nbr = self.pool.neighbors
        for i, off in enumerate(PAGED_NEIGHBORS):
            c2 = self._neighbor_coord(c, off)
            s2 = None if c2 is None else self.pages.get(c2)
            if s2 is not None:
                nbr[s2, 7 - i] = DEAD_SLOT

    def ensure(self, coords: Iterable[Coord]) -> None:
        """Allocate any missing pages (zero content — free of device
        work). Raises :class:`PoolExhausted` mid-way on an empty free
        list; pages already bound stay bound (they are dead tiles, and
        the next retirement pass reclaims any outside the wake ring)."""
        for c in coords:
            if c in self.pages:
                continue
            slot = self.pool.alloc()
            self.pages[c] = slot
            self._link(c, slot)

    def _wrap(self) -> bool:
        return self.topology is Topology.TORUS

    def prepare(self, gens: int) -> None:
        """Pre-chunk soundness: bind every page influence could reach
        within ``gens`` generations of the changed set."""
        dy, dx = _sparse.wake_dilation(
            self.pool.rule, self.pool.tile_rows, self.pool.tile_words, gens)
        need = _sparse.dilate_coords(self.active, dy, dx,
                                     bounds=self.bounds, wrap=self._wrap())
        self.ensure(need)

    def apply_flags(self, changed: np.ndarray, occupied: np.ndarray) -> None:
        """Post-chunk bookkeeping from the dispatch's flag vectors: the
        changed pages become the new wake set; pages with no live bit
        outside the wake ring retire to the free list."""
        self.active = {c for c, s in self.pages.items() if changed[s]}
        dy, dx = _sparse.wake_dilation(
            self.pool.rule, self.pool.tile_rows, self.pool.tile_words, 1)
        keep = _sparse.dilate_coords(self.active, dy, dx,
                                     bounds=self.bounds, wrap=self._wrap())
        dead = [c for c, s in self.pages.items()
                if not occupied[s] and c not in keep]
        for c in dead:
            slot = self.pages.pop(c)
            self._unlink(c)
            self.pool.release(slot)

    # -- content --------------------------------------------------------------

    def seed_words(self, words: np.ndarray, origin: Coord = (0, 0)) -> None:
        """Place packed content: ``words`` is ``(planes, H, Wq)`` uint32
        (binary universes pass planes == 1), tile-divisible, laid down
        with its (0, 0) tile at tile coord ``origin``. Only nonzero tiles
        bind pages — the dead majority of a sparse seed stays aliased."""
        pool = self.pool
        words = np.asarray(words, np.uint32)
        if words.ndim != 3 or words.shape[0] != pool.planes:
            raise ValueError(
                f"seed words must be (planes={pool.planes}, H, Wq), "
                f"got shape {words.shape}")
        _, H, Wq = words.shape
        tr, tw = pool.tile_rows, pool.tile_words
        if H % tr or Wq % tw:
            raise ValueError(
                f"seed of {H} x {Wq} words does not divide into "
                f"{tr} x {tw}-word tiles")
        nty, ntx = H // tr, Wq // tw
        if self.bounds is not None:
            bty, btx = self.bounds
            oy, ox = origin
            if oy < 0 or ox < 0 or oy + nty > bty or ox + ntx > btx:
                raise ValueError(
                    f"seed of {nty} x {ntx} tiles at {origin} exceeds "
                    f"bounds {self.bounds}")
        placed: List[Tuple[Coord, np.ndarray]] = []
        for ty in range(nty):
            for tx in range(ntx):
                block = words[:, ty * tr:(ty + 1) * tr, tx * tw:(tx + 1) * tw]
                if block.any():
                    placed.append(((origin[0] + ty, origin[1] + tx), block))
        self.ensure(c for c, _ in placed)
        for c, block in placed:
            pool.write(self.pages[c], block)
        self.active |= {c for c, _ in placed}

    def to_words(self, origin: Optional[Coord] = None,
                 shape: Optional[Tuple[int, int]] = None,
                 host: Optional[np.ndarray] = None) -> np.ndarray:
        """Dense ``(planes, H, Wq)`` reconstruction of the tile window
        ``shape`` (tile units) at ``origin`` — defaults to the full
        bounds for a bounded grid. ``host`` reuses a prior
        :meth:`TilePool.tiles_host` fetch (checkpoint batches one)."""
        if shape is None:
            if self.bounds is None:
                raise ValueError("an unbounded grid has no default window; "
                                 "pass origin and shape in tile units")
            origin, shape = (0, 0), self.bounds
        origin = origin or (0, 0)
        pool = self.pool
        tr, tw = pool.tile_rows, pool.tile_words
        nty, ntx = shape
        if host is None:
            host = pool.tiles_host()
        out = np.zeros((pool.planes, nty * tr, ntx * tw), np.uint32)
        for (ty, tx), s in self.pages.items():
            oy, ox = ty - origin[0], tx - origin[1]
            if 0 <= oy < nty and 0 <= ox < ntx:
                out[:, oy * tr:(oy + 1) * tr, ox * tw:(ox + 1) * tw] = host[s]
        return out

    def live_tile_bbox(self, host: Optional[np.ndarray] = None
                       ) -> Optional[Tuple[Coord, Coord]]:
        """((ty0, tx0), (ty1, tx1)) inclusive over pages holding any live
        bit, or None for an all-dead universe."""
        if host is None:
            host = self.pool.tiles_host()
        live = [c for c, s in self.pages.items() if host[s].any()]
        if not live:
            return None
        ys = [c[0] for c in live]
        xs = [c[1] for c in live]
        return (min(ys), min(xs)), (max(ys), max(xs))

    def population(self, host: Optional[np.ndarray] = None) -> int:
        """Live cells (cells of nonzero state for plane stacks)."""
        if host is None:
            host = self.pool.tiles_host()
        total = 0
        for _, s in self.pages.items():
            tile = host[s]
            nonzero = np.bitwise_or.reduce(tile, axis=0)
            total += int(np.unpackbits(nonzero.view(np.uint8)).sum())
        return total

    def drop(self) -> None:
        """Release every page (session close / reseed)."""
        for c in list(self.pages):
            slot = self.pages.pop(c)
            self._unlink(c)
            self.pool.release(slot)
        self.active = set()


def step_grids(pool: TilePool, grids: Sequence[PagedGrid], n: int,
               chunk_gens: Optional[int] = None) -> np.ndarray:
    """Advance every grid ``n`` generations in shared chunks: ONE pool
    dispatch per chunk steps the union of all grids' pages, whichever
    session owns them. Returns per-grid generations completed (int64) —
    short of ``n`` only for grids the pool could not provision
    (:class:`PoolExhausted` stalls that grid for the rest of the call;
    co-tenants keep stepping)."""
    if chunk_gens is None:
        chunk_gens = default_chunk_gens(pool.rule, pool.tile_rows,
                                        pool.tile_words)
    done = np.zeros(len(grids), np.int64)
    stalled = [False] * len(grids)
    remaining = int(n)
    while remaining > 0:
        g = min(int(chunk_gens), remaining)
        ready: List[int] = []
        for i, grid in enumerate(grids):
            if stalled[i]:
                continue
            try:
                grid.prepare(g)
                ready.append(i)
            except PoolExhausted:
                stalled[i] = True
        if not ready:
            break
        mask = np.zeros((pool.capacity,), np.uint32)
        for i in ready:
            for s in grids[i].pages.values():
                mask[s] = 1
        mask[DEAD_SLOT] = 0
        if mask.any():
            changed, occupied = pool.dispatch(g, mask)
        else:
            # every ready universe is empty: dead stays dead, free of
            # device work
            changed = np.zeros((pool.capacity,), bool)
            occupied = changed
        for i in ready:
            grids[i].apply_flags(changed, occupied)
            grids[i].generation += g
            done[i] += g
        remaining -= g
    return done


# -- packing helpers ----------------------------------------------------------


def pack_state(rule, grid: np.ndarray) -> np.ndarray:
    """(H, W) uint8 cells -> (planes, H, W/32) uint32 words for ``rule``
    (binary rules get a single plane; Generations / C >= 3 LtL the
    bit-plane stack)."""
    import jax.numpy as jnp

    planes, _ = _sparse.rule_layout(rule)
    if planes == 1:
        return np.asarray(bitpack.pack(jnp.asarray(grid)))[None]
    from ..ops.packed_generations import pack_generations_for

    return np.asarray(pack_generations_for(jnp.asarray(grid), rule))


def unpack_state(words: np.ndarray) -> np.ndarray:
    """(planes, H, Wq) words -> (H, W) uint8 cells (host-side)."""
    planes, H, Wq = words.shape
    bits = np.zeros((planes, H, Wq * bitpack.WORD), np.uint8)
    for p in range(planes):
        for b in range(bitpack.WORD):
            bits[p, :, b::bitpack.WORD] = (words[p] >> b) & 1
    out = np.zeros((H, Wq * bitpack.WORD), np.uint8)
    for p in range(planes):
        out |= bits[p] << p
    return out


class PagedUniverse:
    """An unbounded plane over a (private or shared) tile pool: the
    paged subsystem's payoff workload. Seed anywhere, step forever —
    pages allocate at the advancing front and retire behind it, so a
    glider's footprint is a constant handful of tiles however far it
    flies."""

    def __init__(self, rule, capacity: int = 1024, *,
                 tile_rows: Optional[int] = None,
                 tile_words: Optional[int] = None,
                 pool: Optional[TilePool] = None,
                 chunk_gens: Optional[int] = None,
                 name: str = "universe",
                 registry: MetricsRegistry = REGISTRY):
        self.pool = pool if pool is not None else TilePool(
            rule, capacity, tile_rows=tile_rows, tile_words=tile_words,
            name=name, registry=registry)
        self.grid = PagedGrid(self.pool, topology=Topology.DEAD, bounds=None)
        self.chunk_gens = chunk_gens

    @property
    def generation(self) -> int:
        return self.grid.generation

    def seed_cells(self, cells: np.ndarray, origin: Tuple[int, int] = (0, 0)
                   ) -> None:
        """Place an (h, w) uint8 patch with its top-left at TILE coord
        ``origin`` (cell-exact placement: pad your patch). The patch is
        padded up to tile multiples internally."""
        tr, tcols = self.pool.tile_cells()
        cells = np.asarray(cells, np.uint8)
        h, w = cells.shape
        ph = -h % tr
        pw = -w % tcols
        if ph or pw:
            cells = np.pad(cells, ((0, ph), (0, pw)))
        self.grid.seed_words(pack_state(self.pool.rule, cells), origin)

    def step(self, n: int) -> None:
        done = step_grids(self.pool, [self.grid], n, self.chunk_gens)
        if int(done[0]) != int(n):
            raise PoolExhausted(
                f"universe stalled at generation {self.grid.generation} "
                f"({int(done[0])}/{n} requested gens): pool "
                f"{self.pool.name!r} has no free tiles")

    def population(self) -> int:
        return self.grid.population()

    def snapshot_cells(self) -> Tuple[Tuple[int, int], np.ndarray]:
        """((y0, x0) global CELL coord of the window origin, cells) over
        the live tile bbox; a dead universe returns ((0, 0), empty)."""
        host = self.pool.tiles_host()
        bbox = self.grid.live_tile_bbox(host)
        tr, tcols = self.pool.tile_cells()
        if bbox is None:
            return (0, 0), np.zeros((0, 0), np.uint8)
        (ty0, tx0), (ty1, tx1) = bbox
        words = self.grid.to_words(
            (ty0, tx0), (ty1 - ty0 + 1, tx1 - tx0 + 1), host=host)
        return (ty0 * tr, tx0 * tcols), unpack_state(words)

    def live_bbox_cells(self) -> Optional[Tuple[int, int, int, int]]:
        """(y0, x0, y1, x1) inclusive global cell bbox of live cells."""
        origin, cells = self.snapshot_cells()
        if cells.size == 0 or not cells.any():
            return None
        ys, xs = np.nonzero(cells)
        return (origin[0] + int(ys.min()), origin[1] + int(xs.min()),
                origin[0] + int(ys.max()), origin[1] + int(xs.max()))


class PagedEngineState:
    """The Engine-facing face of a paged bounded universe — duck-types
    ops/sparse.SparseEngineState (.step/.packed/.padded/.reseed/
    .active_tiles), so the Engine's sparse seams serve both backends
    unchanged. Default pool capacity is the dense tile count + the dead
    slot: a private paged engine can always fall back to fully dense, so
    it never sees :class:`PoolExhausted`; pass ``capacity`` (or a shared
    ``pool``) to cap it and let the exception surface."""

    def __init__(self, packed, rule, *,
                 topology: Topology = Topology.DEAD,
                 tile_rows: Optional[int] = None,
                 tile_words: Optional[int] = None,
                 capacity: Optional[int] = None,
                 chunk_gens: Optional[int] = None,
                 pool: Optional[TilePool] = None,
                 registry: MetricsRegistry = REGISTRY):
        words = np.asarray(packed, np.uint32)
        self._flat_packed = words.ndim == 2
        if self._flat_packed:
            words = words[None]
        planes, _ = _sparse.rule_layout(rule)
        if words.ndim != 3 or words.shape[0] != planes:
            raise ValueError(
                f"paged state for {rule.notation} must be "
                f"(planes={planes}, H, W/32) words (or 2D for one plane), "
                f"got shape {np.asarray(packed).shape}")
        _, H, Wq = words.shape
        tr = int(tile_rows or min(_sparse.DEFAULT_TILE_ROWS, H))
        tw = int(tile_words or min(_sparse.DEFAULT_TILE_WORDS, Wq))
        if H % tr or Wq % tw:
            raise ValueError(
                f"grid of {H} x {Wq} words does not divide into "
                f"{tr} x {tw}-word tiles; pass tile_rows/tile_words "
                "that divide it")
        nty, ntx = H // tr, Wq // tw
        if pool is None:
            pool = TilePool(rule, int(capacity or nty * ntx + 1),
                            tile_rows=tr, tile_words=tw, registry=registry)
        elif (pool.tile_rows != tr or pool.tile_words != tw
                or pool.planes != planes):
            raise ValueError(
                f"shared pool slab ({pool.planes}, {pool.tile_rows}, "
                f"{pool.tile_words}) does not match this grid's "
                f"({planes}, {tr}, {tw})")
        self.rule = rule
        self.pool = pool
        self.topology = topology
        self.chunk_gens = chunk_gens
        self.grid = PagedGrid(pool, topology=topology, bounds=(nty, ntx))
        self.grid.seed_words(words)

    def step(self, n: int = 1) -> None:
        done = step_grids(self.pool, [self.grid], int(n), self.chunk_gens)
        if int(done[0]) != int(n):
            raise PoolExhausted(
                f"paged engine stalled at generation {self.grid.generation}"
                f" ({int(done[0])}/{n} requested gens): no free tiles")

    @property
    def padded(self):
        # the device-resident state IS the pool slab (Engine's
        # block_until_ready seam)
        return self.pool.tiles

    @property
    def packed(self):
        import jax.numpy as jnp

        words = jnp.asarray(self.grid.to_words())
        return words[0] if self._flat_packed else words

    def active_tiles(self) -> int:
        return len(self.grid.pages)

    def reseed(self, packed) -> "PagedEngineState":
        """Fresh state over ``packed`` reusing this state's pool and
        configuration (Engine.set_grid's seam)."""
        self.grid.drop()
        return PagedEngineState(
            packed, self.rule, topology=self.topology,
            tile_rows=self.pool.tile_rows, tile_words=self.pool.tile_words,
            chunk_gens=self.chunk_gens, pool=self.pool)
