"""Paged tile-pool grid memory (ROADMAP item 3).

The paged-KV-cache idea applied to CA grids: a fixed slab of physical
tiles (:class:`TilePool`) backs any number of logical universes
(:class:`PagedGrid`) through per-session page tables. Missing pages alias
one canonical dead tile, so a 4096² universe with 2% live cells costs a
few dozen physical tiles instead of 4096 dense ones — and a universe with
no bounds at all (:class:`PagedUniverse`) costs only its live front.

Everything the pool steps goes through ONE warm executable
(parallel/batched.make_multi_step_paged): geometry, topology, and
occupancy are runtime operands (page table + mask), so page allocation,
retirement, and tenants of different logical shapes never retrace.
"""

from .pool import DEAD_SLOT, PoolExhausted, TilePool
from .paged import (
    PagedEngineState,
    PagedGrid,
    PagedUniverse,
    default_chunk_gens,
    step_grids,
)

__all__ = [
    "DEAD_SLOT",
    "PoolExhausted",
    "TilePool",
    "PagedEngineState",
    "PagedGrid",
    "PagedUniverse",
    "default_chunk_gens",
    "step_grids",
]
