"""The fixed-slab tile pool: B physical tiles, one device array, one
free list, one warm executable.

A :class:`TilePool` owns a ``(B, planes, tile_rows, tile_words)`` uint32
slab (slab geometry = ops/sparse.py's Pallas-validated tile sizes, word
layout = ops/bitpack.py) plus the host mirror of the on-device page
tables: a ``(B, 8)`` int32 neighbor matrix in
:data:`~gameoflifewithactors_tpu.parallel.batched.PAGED_NEIGHBORS` order.
Slot :data:`DEAD_SLOT` is reserved as the canonical dead tile — every
unallocated page of every tenant aliases it, which is what makes a
sparse region cost *nothing* rather than one-tile-per-page.

Invariants the allocator maintains:

- free slots are all-zero ON DEVICE (zeroed at release, zeros at init),
  so :meth:`alloc` is pure host bookkeeping — no device work, no
  retrace, which is what lets the wake front of a glider allocate pages
  mid-flight under ``retrace_budget(0)``;
- slot surgery (seed writes, release zeroing) goes through module-level
  tracked_jit kernels with *traced* slot indices, so a thousand
  different slots share one compiled program;
- pool exhaustion raises :class:`PoolExhausted` here and is a
  *scheduling* event upstream (serve/admission.py queues or rejects;
  the paged step loop excludes the starved grid) — never a crash of
  co-tenants.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import sanitizers as _sanitizers
from ..obs.registry import REGISTRY, MetricsRegistry
from ..ops import sparse as _sparse
from ..ops._jit import BuiltRunner, register_builder, tracked_jit
from ..parallel.batched import make_multi_step_paged

DEAD_SLOT = 0


class PoolExhausted(RuntimeError):
    """The free list is empty. Catchers decide policy: serve queues the
    session through admission, step_grids stalls the starved grid, the
    Engine path (which sizes its private pool to the dense tile count)
    never sees it."""


# -- slot surgery -------------------------------------------------------------
#
# One compiled program per operation, slot index traced: writing slot 3
# and slot 900 are the same executable. Donation is safe — the pool owns
# its slab and rebinds it from each call's return.

_DONATE_SURGERY = True


@tracked_jit(runner="memory.pool_write_slot",
             donate_argnums=(0,) if _DONATE_SURGERY else ())
def _write_slot(tiles, slot, content):
    return jax.lax.dynamic_update_index_in_dim(
        tiles, content.astype(tiles.dtype), slot, 0)


@tracked_jit(runner="memory.pool_zero_slot",
             donate_argnums=(0,) if _DONATE_SURGERY else ())
def _zero_slot(tiles, slot):
    return jax.lax.dynamic_update_index_in_dim(
        tiles, jnp.zeros(tiles.shape[1:], tiles.dtype), slot, 0)


class TilePool:
    """B physical tiles for one rule family, shared by any number of
    logical grids (see memory/paged.py for the page-table layer)."""

    def __init__(self, rule, capacity: int, *,
                 tile_rows: Optional[int] = None,
                 tile_words: Optional[int] = None,
                 name: str = "pool",
                 registry: MetricsRegistry = REGISTRY,
                 donate: bool = True,
                 runner=None):
        from ..models.generations import parse_any

        rule = parse_any(rule)
        if _sparse.births_from_nothing(rule):
            raise ValueError(
                f"paged memory cannot serve birth-from-nothing rules "
                f"({rule.notation}): the canonical dead tile would birth "
                "cells, so 'missing page = dead' stops being a closure — "
                "use the packed backend")
        if capacity < 2:
            raise ValueError(
                f"pool capacity must be >= 2 (slot {DEAD_SLOT} is the "
                f"reserved dead tile), got {capacity}")
        self.rule = rule
        self.capacity = int(capacity)
        self.tile_rows = int(tile_rows or _sparse.DEFAULT_TILE_ROWS)
        self.tile_words = int(tile_words or _sparse.DEFAULT_TILE_WORDS)
        self.planes, _ = _sparse.rule_layout(rule)
        self.name = name
        self.tiles = jnp.zeros(
            (self.capacity, self.planes, self.tile_rows, self.tile_words),
            jnp.uint32)
        # host mirror of the page tables; row DEAD_SLOT stays self-dead
        self.neighbors = np.zeros((self.capacity, 8), np.int32)
        self._free: List[int] = list(range(self.capacity - 1, 0, -1))
        # pass a shared runner (serve/lanes.paged_lane_runner) so pools of
        # one geometry share warm executables process-wide
        self._runner = runner if runner is not None else make_multi_step_paged(
            rule, self.tile_rows, self.tile_words, donate=donate)
        self._in_use_g = registry.gauge(
            "pool_tiles_in_use", "physical tiles allocated to pages")
        self._free_g = registry.gauge(
            "pool_tiles_free", "physical tiles on the free list")
        self._alloc_c = registry.counter(
            "pool_alloc_total", "page-to-tile allocations")
        self._reclaim_c = registry.counter(
            "pool_reclaim_total", "dead pages reclaimed to the free list")
        self._oom_c = registry.counter(
            "pool_oom_total", "allocations refused on an empty free list")
        self._set_gauges()

    # -- accounting -----------------------------------------------------------

    def _set_gauges(self) -> None:
        self._in_use_g.set(self.in_use(), pool=self.name)
        self._free_g.set(self.free_count(), pool=self.name)

    def free_count(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        """Tiles bound to pages (the dead slot is neither free nor in use)."""
        return self.capacity - 1 - len(self._free)

    def tile_bytes(self) -> int:
        return self.planes * self.tile_rows * self.tile_words * 4

    def tile_cells(self) -> Tuple[int, int]:
        """(rows, cols) of one tile in cell units."""
        from ..ops import bitpack

        return self.tile_rows, self.tile_words * bitpack.WORD

    def stats(self) -> dict:
        return {
            "capacity": self.capacity,
            "in_use": self.in_use(),
            "free": self.free_count(),
            "tile_bytes": self.tile_bytes(),
            "planes": self.planes,
            "tile_rows": self.tile_rows,
            "tile_words": self.tile_words,
        }

    # -- allocator ------------------------------------------------------------

    def alloc(self) -> int:
        """Bind a free slot: host bookkeeping only — the slot is already
        zero on device, so a page of empty space costs no device work."""
        if not self._free:
            self._oom_c.inc(pool=self.name)
            raise PoolExhausted(
                f"pool {self.name!r} exhausted: {self.capacity - 1} tiles "
                "all bound")
        slot = self._free.pop()
        self._alloc_c.inc(pool=self.name)
        self._set_gauges()
        return slot

    def release(self, slot: int) -> None:
        """Return a slot to the free list, re-establishing the
        free-slots-are-zero invariant on device and severing its page-table
        row. Callers (PagedGrid._unlink) sever the *incoming* edges."""
        if slot == DEAD_SLOT:
            raise ValueError("the dead slot is not allocatable or freeable")
        self.tiles = _zero_slot(self.tiles, slot)
        self.neighbors[slot] = DEAD_SLOT
        self._free.append(slot)
        self._reclaim_c.inc(pool=self.name)
        self._set_gauges()

    # -- slab access ----------------------------------------------------------

    def write(self, slot: int, content: np.ndarray) -> None:
        """Seed one tile's (planes, tile_rows, tile_words) words."""
        self.tiles = _write_slot(self.tiles, slot,
                                 jnp.asarray(content, jnp.uint32))

    def tiles_host(self) -> np.ndarray:
        """The whole slab on host — checkpoint/readback granularity; the
        step path never calls this."""
        with _sanitizers.allow_host_transfers(
                "pool slab readback: checkpoint/snapshot reconstruction "
                "is host-side by design"):
            return np.asarray(self.tiles)

    # -- stepping -------------------------------------------------------------

    def dispatch(self, n: int, mask: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Advance every masked slot ``n`` generations through the one
        warm executable; returns host (changed, occupied) bool vectors —
        the per-chunk wake/retire evidence (the paged analogue of the
        sparse engine's generations-completed scalar)."""
        self.tiles, changed, occupied = self._runner(
            self.tiles, int(n), jnp.asarray(self.neighbors),
            jnp.asarray(mask, dtype=jnp.uint32))
        with _sanitizers.allow_host_transfers(
                "paged pool reads per-slot changed/occupied flags between "
                "chunks — page activation/retirement is host bookkeeping"):
            return np.asarray(changed), np.asarray(occupied)

    def warm(self) -> None:
        """Compile every program the pool will ever run — the step
        executable (one all-dead-mask dispatch at the pool's only shape)
        and the slot-surgery pair (no-op writes on a free slot, which is
        zero and stays zero) — so allocation churn after warm is pure
        host bookkeeping under ``retrace_budget(0)``."""
        self.dispatch(1, np.zeros((self.capacity,), np.uint32))
        if self._free:
            spare = self._free[-1]
            self.tiles = _write_slot(
                self.tiles, spare,
                jnp.zeros(self.tiles.shape[1:], jnp.uint32))
            self.tiles = _zero_slot(self.tiles, spare)


# -- contract-gate registrations (ops/_jit.py BUILDERS) -----------------------


def _contract_pool_slab(B=16, planes=1, tr=32, tw=4, seed=3):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, 1 << 32, size=(B, planes, tr, tw), dtype=np.uint64)
        .astype(np.uint32))


@register_builder("memory.pool_write_slot", tags=("memory", "paged"))
def _contract_pool_write_slot():
    tiles = _contract_pool_slab()
    content = jnp.ones(tiles.shape[1:], jnp.uint32)
    return BuiltRunner(lowerable=_write_slot, example_args=(tiles, 3, content),
                       donated_argnums=(0,))


@register_builder("memory.pool_zero_slot", tags=("memory", "paged"))
def _contract_pool_zero_slot():
    tiles = _contract_pool_slab()
    return BuiltRunner(lowerable=_zero_slot, example_args=(tiles, 3),
                       donated_argnums=(0,))
