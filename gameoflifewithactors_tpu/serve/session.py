"""Sessions: the logical actors the serving layer multiplexes.

The scheduling model is the PGAS-actor one (PAPERS.md arXiv:2107.05516):
many logical actors — here, live grid universes owned by tenants — are
mapped onto few physical executors (batched lanes, serve/lanes.py). A
``Session`` is the unit a tenant sees: a spec, a generation cursor, and
a lifecycle; where its bits physically live (which lane, which slot) is
the lane layer's business and changes under compaction without the
session noticing.

Lifecycle::

    pending --admit--> packed --step--> running --close--> closed
       |                                   |
       +------------- evict ---------------+--> evicted

``pending`` — created but queued by admission control (no slot yet);
``packed`` — admitted into a lane slot, not yet stepped;
``running`` — stepped at least once;
``closed`` — tenant-requested delete (slot reclaimed);
``evicted`` — server-initiated removal (admission pressure or a lane
that exhausted its restart budget).

Stdlib + numpy only; no jax at module scope (the store must be
constructible and checkpoint-restorable while the backend is wedged).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Dict, List, Optional

import numpy as np

PENDING = "pending"
PACKED = "packed"
RUNNING = "running"
CLOSED = "closed"
EVICTED = "evicted"

LIVE_STATES = (PACKED, RUNNING)
DEAD_STATES = (CLOSED, EVICTED)

# state -> states it may move to; anything else is a lifecycle bug
_TRANSITIONS = {
    PENDING: (PACKED, EVICTED, CLOSED),
    PACKED: (RUNNING, CLOSED, EVICTED),
    RUNNING: (RUNNING, CLOSED, EVICTED),
    CLOSED: (),
    EVICTED: (),
}


@dataclasses.dataclass
class Session:
    """One tenant-owned universe: identity + cursor, never bits.

    The packed grid words live in the owning lane's batch array (or in
    the admission queue's parking buffer while ``pending``); the session
    records only where to find them.
    """

    sid: str
    tenant: str
    family_key: str            # lanes.SpecFamily.key — which lanes can host it
    spec: dict                 # canonical EngineSpec dict (JSON-able)
    state: str = PENDING
    generation: int = 0
    pending_steps: int = 0     # requested, not yet applied
    lane_id: Optional[str] = None
    slot: Optional[int] = None
    # parking buffer for a not-yet-packed grid: (H, W/32) uint32
    parked: Optional[np.ndarray] = None

    def transition(self, new_state: str) -> None:
        if new_state not in _TRANSITIONS[self.state]:
            raise ValueError(
                f"session {self.sid}: illegal transition "
                f"{self.state} -> {new_state}")
        self.state = new_state

    @property
    def live(self) -> bool:
        return self.state in LIVE_STATES

    def placement(self) -> Optional[tuple]:
        """(lane_id, slot) when packed into a lane, else None."""
        if self.lane_id is None or self.slot is None:
            return None
        return (self.lane_id, self.slot)

    def to_meta(self) -> dict:
        """The JSON-able identity a checkpoint manifest records (bits —
        ``parked`` and the lane words — travel separately as arrays)."""
        return {"sid": self.sid, "tenant": self.tenant,
                "family_key": self.family_key, "spec": self.spec,
                "state": self.state, "generation": self.generation,
                "pending_steps": self.pending_steps}


class SessionStore:
    """sid -> Session, with the counts /healthz and the gauges read.

    Thread-safe for the frontend's request threads; the service layer
    holds its own coarser lock around anything that touches lanes, so
    the store lock only guards the map itself.
    """

    def __init__(self):
        self._sessions: Dict[str, Session] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)

    def new_sid(self, tenant: str) -> str:
        return f"s{next(self._ids):06d}-{tenant}"

    def add(self, session: Session) -> Session:
        with self._lock:
            if session.sid in self._sessions:
                raise ValueError(f"duplicate session id {session.sid}")
            self._sessions[session.sid] = session
        return session

    def get(self, sid: str) -> Session:
        with self._lock:
            try:
                return self._sessions[sid]
            except KeyError:
                raise KeyError(f"no such session {sid!r}") from None

    def maybe(self, sid: str) -> Optional[Session]:
        with self._lock:
            return self._sessions.get(sid)

    def drop(self, sid: str) -> None:
        """Forget a dead session entirely (post-close GC)."""
        with self._lock:
            self._sessions.pop(sid, None)

    def all(self) -> List[Session]:
        with self._lock:
            return list(self._sessions.values())

    def live(self) -> List[Session]:
        with self._lock:
            return [s for s in self._sessions.values() if s.live]

    def by_state(self, state: str) -> List[Session]:
        with self._lock:
            return [s for s in self._sessions.values() if s.state == state]

    def counts(self) -> dict:
        """{state: n} plus totals — the /healthz body's session block."""
        out = {st: 0 for st in _TRANSITIONS}
        with self._lock:
            for s in self._sessions.values():
                out[s.state] = out.get(s.state, 0) + 1
        out["total"] = sum(out.values())
        out["live"] = out[PACKED] + out[RUNNING]
        return out

    def tenants(self) -> Dict[str, int]:
        """tenant -> live session count (the per-tenant gauge feed)."""
        out: Dict[str, int] = {}
        with self._lock:
            for s in self._sessions.values():
                if s.live:
                    out[s.tenant] = out.get(s.tenant, 0) + 1
        return out
