"""Lanes: few physical executors for many logical sessions.

A lane is one batched dispatch surface: a ``(capacity, H, W/32)`` packed
batch driven by the masked DP runner
(``parallel.batched.make_multi_step_packed_batched(masked=True)``) on a
single-device (1, 1, 1) batch mesh. Sessions of the same
:class:`SpecFamily` (rule × shape × topology × backend) share lanes;
each owns one batch slot. The occupancy mask is a *runtime operand*, so
slots can be claimed, freed, and frozen without ever changing the jit
signature — the lever every serving decision here leans on:

- **Fixed capacity ladder** (:data:`LANE_LADDER`, default 1/8/64/256):
  lane batch shapes are drawn from a small closed set, so the warmup
  pass (aot/warmup.py lane entries) can pre-trace every executable the
  server will ever dispatch. Growth, shrink, and compaction move
  sessions *between* ladder shapes — they never mint a new one.
- **Host-side state**: lane words live in writable numpy; slot surgery
  (place/release/repack) is array copying on the host, invisible to the
  compiled runner. Device-side scatter by slot index would compile one
  executable per slot constant — the exact retrace storm the
  RetraceSentinel exists to catch.
- **Dynamic compaction**: after closes, live sessions are repacked into
  the smallest ladder multiset that holds them (greedy from the largest
  rung). Every target shape is pre-warmed, so compaction is free of
  ``cache_miss`` events by construction — asserted by the retrace-budget
  test, not just promised.

The per-lane HBM-cost model admission control prices against is
:meth:`SpecFamily.slot_bytes` × capacity (double-buffered packed words —
the runner's donated/undonated in+out pair).
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.generations import parse_any
from ..models.rules import Rule
from ..obs import spans as obs_spans
from ..ops import bitpack
from ..ops.stencil import Topology
from ..parallel import batched

# capacities a lane may have — the closed set of batch shapes the server
# ever traces. Must be sorted ascending; 1 keeps singleton tenants cheap,
# the top rung bounds lanes-per-family at ~N/256.
LANE_LADDER = (1, 8, 64, 256)


class SpecFamily:
    """The lane-sharing equivalence class of an EngineSpec.

    Two sessions share lanes iff rule notation, grid shape, topology and
    lane backend all match — exactly the parameters that shape the
    runner's lowered program (batch capacity is the one shape axis the
    ladder varies).
    """

    def __init__(self, rule: str, height: int, width: int,
                 topology: str = "torus", backend: str = "packed"):
        parsed = parse_any(rule)
        if not isinstance(parsed, Rule):
            raise ValueError(
                f"lanes serve binary life-like rules only, got {rule!r} "
                f"({type(parsed).__name__}); multi-state families need "
                "their own engine")
        if backend not in ("packed", "pallas"):
            raise ValueError(
                f"lane backend must be 'packed' or 'pallas', got {backend!r}")
        self.rule = parsed
        self.height = int(height)
        self.width = int(width)
        self.wq = bitpack.packed_width(self.width)  # validates width % 32
        self.topology = Topology(topology)
        self.backend = backend
        self.key = (f"{self.rule.notation}|{self.height}x{self.width}"
                    f"|{self.topology.value}|{self.backend}")

    @classmethod
    def from_spec(cls, spec: dict) -> "SpecFamily":
        """From an EngineSpec-shaped dict (the create-request body).
        ``backend`` 'auto' resolves to the packed lane runner; sharded
        meshes are a per-engine concern the lane layer refuses."""
        d = dict(spec)
        if d.get("mesh"):
            raise ValueError(
                "lane sessions are single-device (the batch axis IS the "
                "parallelism); drop 'mesh' from the session spec")
        backend = d.get("backend", "auto")
        if backend == "auto":
            backend = "packed"
        if "shape" in d:
            height, width = d["shape"]
        else:
            height, width = d["height"], d["width"]
        return cls(d.get("rule", "B3/S23"), height, width,
                   d.get("topology", "torus"), backend)

    def canonical_spec(self) -> dict:
        """The JSON-able spec stored on sessions and in checkpoints."""
        return {"rule": self.rule.notation, "height": self.height,
                "width": self.width, "topology": self.topology.value,
                "backend": self.backend}

    def slot_bytes(self) -> int:
        """Modelled HBM cost of one occupied batch slot: packed words,
        double-buffered (the runner's input + output live together at
        dispatch)."""
        return 2 * self.height * self.wq * 4

    def describe(self) -> str:
        return self.key


# -- the runner cache ---------------------------------------------------------
#
# One masked runner per (rule, topology, backend) — and one paged runner
# per (rule, tile geometry): tracked_jit caches compiled executables per
# batch shape inside it, so every lane of a family — and every test in
# the process — shares warm executables. Every cache key MUST carry every
# trace-constant baked into the program: the paged runner's key includes
# the slab geometry precisely because a test that resizes the pool's
# tile shape would otherwise be handed a stale executable traced for the
# old one (pool *capacity* is a runtime shape axis and needs no key).

_RUNNERS: Dict[tuple, object] = {}
_MESH = None
_RUNNER_LOCK = threading.Lock()


def _lane_mesh():
    """The (1, 1, 1) single-device batch mesh every lane dispatches on.
    Lanes are deliberately single-device: the batch axis is the
    parallelism, and one mesh means one executable per ladder shape."""
    global _MESH
    with _RUNNER_LOCK:
        if _MESH is None:
            import jax

            _MESH = batched.make_batch_mesh((1, 1, 1),
                                            devices=jax.devices()[:1])
        return _MESH


def lane_runner(family: SpecFamily):
    """The masked batched runner for a family (get-or-create)."""
    key = (family.rule.notation, family.topology.value, family.backend)
    mesh = _lane_mesh()
    with _RUNNER_LOCK:
        runner = _RUNNERS.get(key)
        if runner is None:
            if family.backend == "pallas":
                runner = batched.make_multi_step_pallas_batched(
                    mesh, family.rule, family.topology, masked=True)
            else:
                runner = batched.make_multi_step_packed_batched(
                    mesh, family.rule, family.topology, masked=True)
            _RUNNERS[key] = runner
        return runner


def paged_lane_runner(rule, tile_rows: int, tile_words: int):
    """The paged pool runner for a rule at a slab geometry
    (get-or-create). Keyed on (rule, tile_rows, tile_words): the
    geometry is a trace constant of the program, so two pools of
    different tile shapes must never alias one cache entry."""
    key = ("paged", rule.notation, int(tile_rows), int(tile_words))
    with _RUNNER_LOCK:
        runner = _RUNNERS.get(key)
        if runner is None:
            runner = batched.make_multi_step_paged(
                rule, int(tile_rows), int(tile_words))
            _RUNNERS[key] = runner
        return runner


def warm_family(family: SpecFamily,
                ladder: Tuple[int, ...] = LANE_LADDER) -> int:
    """Trace/compile the family's runner at every ladder capacity, so no
    serving-path dispatch ever compiles. Returns the number of shapes
    exercised. (This is the lane half of the warm start: the engine-spec
    half — and the persistent-cache wiring — is aot/warmup.py.)"""
    runner = lane_runner(family)
    for cap in ladder:
        zeros = np.zeros((int(cap), family.height, family.wq),
                         dtype=np.uint32)
        mask = np.zeros((int(cap),), dtype=np.uint32)
        # n=1 with an all-dead mask: traces the full loop body, steps
        # nothing (mask-0 slots pass through bit-identical)
        runner(zeros, 1, mask)
    return len(ladder)


class Lane:
    """One batch executor: capacity slots of a family + occupancy."""

    def __init__(self, lane_id: str, family: SpecFamily, capacity: int):
        self.lane_id = lane_id
        self.family = family
        self.capacity = int(capacity)
        self.slots: List[Optional[str]] = [None] * self.capacity
        self.state = np.zeros((self.capacity, family.height, family.wq),
                              dtype=np.uint32)
        self._runner = lane_runner(family)
        self.steps_dispatched = 0
        self.fail_next = False  # test seam: inject one lane crash

    # -- slot surgery (host numpy, never a device dispatch) ------------------

    def live_count(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def place(self, sid: str, words: np.ndarray) -> int:
        slot = self.free_slot()
        if slot is None:
            raise ValueError(f"lane {self.lane_id} is full")
        self.slots[slot] = sid
        self.state[slot] = words
        return slot

    def release(self, slot: int) -> None:
        self.slots[slot] = None
        self.state[slot] = 0  # freed slots must not leak grids into dumps

    def read(self, slot: int) -> np.ndarray:
        return np.array(self.state[slot], copy=True)

    def write(self, slot: int, words: np.ndarray) -> None:
        self.state[slot] = words

    def occupancy_mask(self, live_sids=None) -> np.ndarray:
        """(capacity,) uint32 — 1 where a slot is occupied (and, when
        ``live_sids`` is given, a member of it)."""
        mask = np.zeros((self.capacity,), dtype=np.uint32)
        for i, sid in enumerate(self.slots):
            if sid is not None and (live_sids is None or sid in live_sids):
                mask[i] = 1
        return mask

    # -- the dispatch --------------------------------------------------------

    def step(self, n: int, mask: np.ndarray) -> None:
        """Advance masked slots ``n`` generations in one dispatch."""
        if self.fail_next:
            self.fail_next = False
            raise RuntimeError(
                f"injected lane fault ({self.lane_id})")
        # same span name as Engine.step: a lane batch IS the engine step
        # of its slots, and the end-to-end request trace must bottom out
        # at the same leaf either way
        with obs_spans.span("engine.step", generations=int(n),
                            lane=self.lane_id, capacity=self.capacity):
            out = self._runner(self.state, int(n),
                               np.ascontiguousarray(mask, dtype=np.uint32))
            # copy=True: np.asarray of a CPU jax.Array is a read-only
            # zero-copy view that dangles once the device buffer is freed —
            # slot surgery needs an owned, writable buffer
            self.state = np.array(out, dtype=np.uint32, copy=True)
        self.steps_dispatched += int(n)

    def stats(self) -> dict:
        return {"lane": self.lane_id, "family": self.family.key,
                "capacity": self.capacity, "live": self.live_count()}


class LanePool:
    """All lanes of one family + the ladder placement/compaction policy.

    Placement: first free slot in lane-creation order. Growth and
    compaction both route through :meth:`repack` — compute the ideal
    ladder multiset for the live-session count (greedy from the largest
    rung), rebuild lanes at those capacities, and re-place every live
    session. The pool returns the new ``sid -> (lane_id, slot)`` map;
    the caller (serve/service.py) owns updating Session records.
    """

    def __init__(self, family: SpecFamily,
                 ladder: Tuple[int, ...] = LANE_LADDER):
        if not ladder:
            raise ValueError("lane ladder cannot be empty")
        self.family = family
        self.ladder = tuple(sorted(set(int(c) for c in ladder)))
        self.lanes: Dict[str, Lane] = {}
        self._seq = itertools.count(1)
        self.compactions = 0
        self.warmed = False

    # -- policy --------------------------------------------------------------

    def plan(self, count: int) -> List[int]:
        """The ideal capacity multiset for ``count`` live sessions:
        largest rungs first, one smallest-fitting rung for the tail."""
        caps: List[int] = []
        top = self.ladder[-1]
        remaining = int(count)
        while remaining >= top:
            caps.append(top)
            remaining -= top
        if remaining > 0:
            caps.append(min(c for c in self.ladder if c >= remaining))
        return caps

    def total_capacity(self) -> int:
        return sum(lane.capacity for lane in self.lanes.values())

    def live_count(self) -> int:
        return sum(lane.live_count() for lane in self.lanes.values())

    def warm(self) -> None:
        if not self.warmed:
            warm_family(self.family, self.ladder)
            self.warmed = True

    # -- placement -----------------------------------------------------------

    def _new_lane(self, capacity: int) -> Lane:
        lane_id = f"{self.family.key}#{next(self._seq)}"
        lane = Lane(lane_id, self.family, capacity)
        self.lanes[lane_id] = lane
        return lane

    def place(self, sid: str, words: np.ndarray) -> Tuple[str, int, dict]:
        """Claim a slot for ``sid``; returns (lane_id, slot, moves) where
        ``moves`` maps any *other* sessions a growth-repack relocated to
        their new (lane_id, slot)."""
        for lane in self.lanes.values():
            slot = lane.free_slot()
            if slot is not None:
                lane.slots[slot] = sid
                lane.state[slot] = words
                return lane.lane_id, slot, {}
        # no free slot anywhere: grow through a repack sized for +1 so
        # growth reuses the same warm shapes compaction does
        moves = self.repack(self.live_count() + 1)
        for lane in self.lanes.values():
            slot = lane.free_slot()
            if slot is not None:
                lane.slots[slot] = sid
                lane.state[slot] = words
                return lane.lane_id, slot, moves
        raise RuntimeError(
            f"repack for {self.live_count() + 1} sessions left no free "
            f"slot (ladder {self.ladder})")

    def release(self, lane_id: str, slot: int) -> None:
        self.lanes[lane_id].release(slot)

    def compact(self) -> dict:
        """Repack iff the ideal multiset is strictly smaller than what
        is allocated. Returns the relocation map (empty = no-op)."""
        live = self.live_count()
        ideal = self.plan(live)
        if sum(ideal) >= self.total_capacity() and len(
                ideal) >= len(self.lanes):
            return {}
        return self.repack(live)

    def repack(self, target_count: int) -> dict:
        """Rebuild lanes at ``plan(target_count)`` capacities and re-place
        every live session (deterministic order: old lane creation order,
        then slot order). Host-side copies only — the new shapes come
        from the ladder, so every executable is already warm."""
        entries: List[Tuple[str, np.ndarray]] = []
        for lane in self.lanes.values():
            for slot, sid in enumerate(lane.slots):
                if sid is not None:
                    entries.append((sid, lane.read(slot)))
        if target_count < len(entries):
            target_count = len(entries)
        self.lanes.clear()
        moves: Dict[str, Tuple[str, int]] = {}
        for cap in self.plan(target_count):
            self._new_lane(cap)
        lanes = list(self.lanes.values())
        li = 0
        for sid, words in entries:
            while lanes[li].free_slot() is None:
                li += 1
            slot = lanes[li].place(sid, words)
            moves[sid] = (lanes[li].lane_id, slot)
        self.compactions += 1
        return moves

    def stats(self) -> List[dict]:
        return [lane.stats() for lane in self.lanes.values()]

    # -- admission pricing ----------------------------------------------------

    def admission_cost(self, words=None) -> int:
        """Modelled bytes one create claims (the ladder model: a full
        dense slot, whatever the seed looks like)."""
        return self.family.slot_bytes()

    def pool_pressure(self, words=None):
        """(tiles needed, tiles free) for pool-backed placement; None
        for the ladder, which has no fixed physical budget to starve."""
        return None

    def bytes_held(self) -> int:
        """Modelled HBM bytes this family's lanes hold."""
        return self.total_capacity() * self.family.slot_bytes()


# -- paged lanes: the ladder, collapsed ---------------------------------------
#
# A PagedLanePool keeps the LanePool surface the service drives (place /
# release / compact / warm / lanes) but drops the capacity ladder
# entirely: sessions become page-table grids over ONE shared
# memory.TilePool, admission is priced in *tiles the seed actually
# occupies* instead of worst-case dense slots, and every family of the
# same rule — whatever its logical geometry — dispatches through the one
# warm paged executable. Growth and compaction stop being events (the
# free list is always compact); pool pressure replaces them as the
# scheduling signal (serve/admission.py queues on it, step_grids stalls
# on it).


class PagedLane:
    """One dispatch surface of page-table grids — the paged duck-type of
    :class:`Lane`. Slots grow on demand (occupancy is a runtime mask and
    per-grid page tables, so there is no batch shape to ladder);
    :meth:`step` returns per-slot generations completed, short of ``n``
    only for slots the tile pool could not provision mid-flight."""

    def __init__(self, lane_id: str, family: SpecFamily, tile_pool,
                 chunk_gens: Optional[int] = None):
        from ..memory import PagedGrid  # noqa: F401 — validated below

        trc, _ = tile_pool.tile_cells()
        if family.height % trc or family.wq % tile_pool.tile_words:
            raise ValueError(
                f"family {family.key} ({family.height} x {family.wq} words) "
                f"does not divide into the pool's {trc}-row x "
                f"{tile_pool.tile_words}-word tiles")
        self.lane_id = lane_id
        self.family = family
        self.pool = tile_pool
        self.bounds = (family.height // trc,
                       family.wq // tile_pool.tile_words)
        self.chunk_gens = chunk_gens
        self.slots: List[Optional[str]] = []
        self.grids: List[Optional[object]] = []
        self.steps_dispatched = 0
        self.fail_next = False  # same injected-crash seam as Lane

    @property
    def capacity(self) -> int:
        return len(self.slots)

    def live_count(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def place(self, sid: str, words: np.ndarray) -> int:
        from ..memory import PagedGrid

        grid = PagedGrid(self.pool, topology=self.family.topology,
                         bounds=self.bounds)
        try:
            grid.seed_words(np.asarray(words, np.uint32)[None])
        except Exception:
            grid.drop()  # release any pages bound before exhaustion
            raise
        slot = self.free_slot()
        if slot is None:
            self.slots.append(sid)
            self.grids.append(grid)
            return len(self.slots) - 1
        self.slots[slot] = sid
        self.grids[slot] = grid
        return slot

    def release(self, slot: int) -> None:
        grid = self.grids[slot]
        if grid is not None:
            grid.drop()
        self.slots[slot] = None
        self.grids[slot] = None

    def read(self, slot: int) -> np.ndarray:
        return self.grids[slot].to_words()[0]

    def write(self, slot: int, words: np.ndarray) -> None:
        grid = self.grids[slot]
        grid.drop()
        grid.seed_words(np.asarray(words, np.uint32)[None])

    def occupancy_mask(self, live_sids=None) -> np.ndarray:
        mask = np.zeros((self.capacity,), dtype=np.uint32)
        for i, sid in enumerate(self.slots):
            if sid is not None and (live_sids is None or sid in live_sids):
                mask[i] = 1
        return mask

    def step(self, n: int, mask: np.ndarray) -> np.ndarray:
        """Advance masked slots ``n`` generations; returns (capacity,)
        int64 generations completed per slot (pool exhaustion stalls a
        slot partway; co-tenants still finish)."""
        from ..memory import step_grids

        if self.fail_next:
            self.fail_next = False
            raise RuntimeError(f"injected lane fault ({self.lane_id})")
        done = np.zeros((self.capacity,), np.int64)
        idx = [i for i, sid in enumerate(self.slots)
               if sid is not None and i < len(mask) and mask[i]]
        with obs_spans.span("engine.step", generations=int(n),
                            lane=self.lane_id, capacity=self.capacity):
            if idx:
                out = step_grids(self.pool, [self.grids[i] for i in idx],
                                 int(n), self.chunk_gens)
                for j, i in enumerate(idx):
                    done[i] = out[j]
        self.steps_dispatched += int(n)
        return done

    def stats(self) -> dict:
        return {"lane": self.lane_id, "family": self.family.key,
                "capacity": self.capacity, "live": self.live_count(),
                "paged": True, "tiles": sum(
                    len(g.pages) for g in self.grids if g is not None)}


# a nominal average session footprint (tiles) for mapping legacy ladder
# configs onto pool capacity — see pool_capacity_for_ladder
TILES_PER_SLOT = 8


def pool_capacity_for_ladder(ladder: Tuple[int, ...] = LANE_LADDER,
                             tiles_per_slot: int = TILES_PER_SLOT) -> int:
    """Map an old lane-ladder config onto tile-pool capacity, so configs
    written for the ladder keep working after the collapse: the ladder's
    nominal fleet (8 top-rung lanes) times a nominal per-session
    footprint of ``tiles_per_slot`` tiles, plus the reserved dead slot.
    Explicit ``paged_opts['capacity']`` overrides this entirely."""
    top = max(int(c) for c in ladder)
    return 1 + 8 * int(tiles_per_slot) * top


class PagedLanePool:
    """All paged sessions of one family over the shared tile pool — the
    :class:`LanePool` duck-type with the ladder collapsed to a single
    elastic lane. ``compact``/``repack`` are no-ops (a free-list pool is
    always compact; nothing ever moves), and ``warm`` warms the ONE
    executable every geometry of this rule shares."""

    def __init__(self, family: SpecFamily,
                 ladder: Tuple[int, ...] = LANE_LADDER, *,
                 tile_pool, chunk_gens: Optional[int] = None):
        self.family = family
        self.ladder = tuple(sorted(set(int(c) for c in ladder)))
        self.tile_pool = tile_pool
        self.chunk_gens = chunk_gens
        self.lanes: Dict[str, PagedLane] = {}
        self.compactions = 0
        self.warmed = False

    def _lane(self) -> PagedLane:
        if not self.lanes:
            lane = PagedLane(f"{self.family.key}#paged", self.family,
                             self.tile_pool, self.chunk_gens)
            self.lanes[lane.lane_id] = lane
        return next(iter(self.lanes.values()))

    def plan(self, count: int) -> List[int]:
        return [int(count)] if count else []

    def total_capacity(self) -> int:
        return sum(lane.capacity for lane in self.lanes.values())

    def live_count(self) -> int:
        return sum(lane.live_count() for lane in self.lanes.values())

    def warm(self) -> None:
        if not self.warmed:
            self.tile_pool.warm()
            self.warmed = True

    def place(self, sid: str, words: np.ndarray) -> Tuple[str, int, dict]:
        lane = self._lane()
        slot = lane.place(sid, words)  # PoolExhausted propagates
        return lane.lane_id, slot, {}

    def release(self, lane_id: str, slot: int) -> None:
        self.lanes[lane_id].release(slot)

    def compact(self) -> dict:
        return {}

    def repack(self, target_count: int) -> dict:
        return {}

    def stats(self) -> List[dict]:
        return [lane.stats() for lane in self.lanes.values()]

    # -- admission pricing ----------------------------------------------------

    def tiles_needed(self, words: Optional[np.ndarray]) -> int:
        """Tiles a seed binds NOW: its nonzero tiles (the dead majority
        stays aliased to the pool's dead slot; wake rings bind lazily at
        the first step and retire behind the front)."""
        if words is None:
            return 0
        trc, _ = self.tile_pool.tile_cells()
        tw = self.tile_pool.tile_words
        nty, ntx = self.family.height // trc, self.family.wq // tw
        w = np.asarray(words).reshape(nty, trc, ntx, tw)
        return int(w.any(axis=(1, 3)).sum())

    def admission_cost(self, words=None) -> int:
        return self.tiles_needed(words) * self.tile_pool.tile_bytes()

    def pool_pressure(self, words=None):
        return (self.tiles_needed(words), self.tile_pool.free_count())

    def bytes_held(self) -> int:
        tiles = sum(len(g.pages) for lane in self.lanes.values()
                    for g in lane.grids if g is not None)
        return tiles * self.tile_pool.tile_bytes()
