"""HTTP/JSON front-end + the ``serve`` CLI subcommand.

Same stdlib ``ThreadingHTTPServer`` idiom as obs/exporter.py — request
threads translate JSON to :class:`SessionService` calls (the service
lock serializes anything that touches lanes), and the metrics/health
endpoints are the exporter's own rendering, so one port serves both the
session API and the Prometheus scrape.

API (README "Serving" has the full table)::

    POST   /sessions                   create {tenant, spec, fill|cells_hex,
                                       rng_seed} -> session info (202 when
                                       queued by admission, 429 on reject)
    GET    /sessions/<sid>             session info
    POST   /sessions/<sid>/step        {"n": int} -> info after the pump
    GET    /sessions/<sid>/grid        packed grid hex + shape
    DELETE /sessions/<sid>             close (frees the slot, compacts)
    POST   /admin/checkpoint           write the atomic checkpoint now
    GET    /metrics                    Prometheus exposition (goltpu_*)
    GET    /healthz                    JSON: ok + session/lane/queue counts

Process shape (``python -m gameoflifewithactors_tpu serve``): warm the
lane ladder from the manifest, arm the flight recorder, start the HTTP
server, announce ``SERVE_PORT <port>`` on stdout (the driver protocol —
scripts/serve_load.py and the CI smoke parse it), then sit in the
checkpoint loop until SIGTERM/SIGINT. Signal discipline: the graceful
handler is installed FIRST and the flight recorder chains onto it
(obs/flight.py ``install``), so one SIGTERM yields both the crash dump
and a final checkpoint + clean exit — neither installer drops the other
(the regression the chaining test pins).
"""

from __future__ import annotations

import argparse
import json
import re
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..obs import exporter as obs_exporter
from ..obs import flight as obs_flight
from ..obs import spans as obs_spans
from ..obs.registry import REGISTRY
from .admission import AdmissionController, AdmissionRejected
from .service import SessionService

_SID = re.compile(r"^/sessions/([^/]+)(/grid|/step)?$")

#: Request header carrying a caller's trace context
#: (``<32-hex trace id>[:<16-hex parent span id>]``); absent, the
#: frontend mints a fresh trace id. Echoed on every response.
TRACE_HEADER = "X-Goltpu-Trace"


class SessionFrontend:
    """HTTP surface over one SessionService (start()/stop(), port 0 OK)."""

    def __init__(self, service: SessionService, port: int = 0, *,
                 host: str = "127.0.0.1"):
        self.service = service
        self.requested_port = int(port)
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "SessionFrontend":
        if self._httpd is not None:
            return self
        service = self.service

        class Handler(BaseHTTPRequestHandler):
            trace_id: Optional[str] = None  # this request's trace

            def _send(self, code: int, payload: dict,
                      ctype: str = "application/json") -> None:
                if self.trace_id is not None and "trace_id" not in payload:
                    payload = {**payload, "trace_id": self.trace_id}
                body = (json.dumps(payload) + "\n").encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if self.trace_id is not None:
                    self.send_header(TRACE_HEADER, self.trace_id)
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str, ctype: str) -> None:
                body = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if self.trace_id is not None:
                    self.send_header(TRACE_HEADER, self.trace_id)
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length") or 0)
                if not length:
                    return {}
                return json.loads(self.rfile.read(length) or b"{}")

            def _dispatch(self, method: str) -> None:
                path = self.path.split("?")[0]
                # one request = one trace: accept the caller's context
                # (continuing their trace under their parent span) or
                # mint a fresh 128-bit id. Binding is thread-local and
                # request threads are per-connection, so two concurrent
                # requests cannot cross-contaminate.
                header = self.headers.get(TRACE_HEADER)
                try:
                    caller = (obs_spans.parse_trace_header(header)
                              if header else None)
                except ValueError as exc:
                    self._send(400, {"error": str(exc)})
                    return
                with obs_spans.bind_trace(
                        caller.trace_id if caller else None,
                        caller.span_id if caller else None) as ctx:
                    self.trace_id = ctx.trace_id
                    with obs_spans.span("serve.request", method=method,
                                        path=path):
                        try:
                            self._route(method, path)
                        except (KeyError, FileNotFoundError) as exc:
                            self._send(404, {"error": str(exc)})
                        except AdmissionRejected as exc:
                            self._send(429, {"error": str(exc)})
                        except (ValueError, json.JSONDecodeError) as exc:
                            self._send(400, {"error": str(exc)})
                        except Exception as exc:  # noqa: BLE001 — HTTP boundary
                            self._send(500, {"error":
                                             f"{type(exc).__name__}: {exc}"})

            def _route(self, method: str, path: str) -> None:
                if method == "GET" and path in ("/metrics", "/"):
                    self._send_text(
                        200,
                        obs_exporter.render_prometheus(
                            service.registry.snapshot()),
                        obs_exporter.CONTENT_TYPE)
                    return
                if method == "GET" and path == "/healthz":
                    self._send(200, {"ok": True, **service.counts()})
                    return
                if method == "POST" and path == "/sessions":
                    body = self._body()
                    info = service.create(
                        str(body.get("tenant", "default")),
                        body.get("spec") or {},
                        fill=body.get("fill"),
                        rng_seed=int(body.get("rng_seed", 0)),
                        cells_hex=body.get("cells_hex"))
                    self._send(202 if info["state"] == "pending" else 201,
                               info)
                    return
                if method == "POST" and path == "/admin/checkpoint":
                    self._send(200, {"path": service.checkpoint()})
                    return
                m = _SID.match(path)
                if m is None:
                    self._send(404, {"error": f"no route {method} {path}"})
                    return
                sid, tail = m.group(1), m.group(2)
                if method == "GET" and tail == "/grid":
                    self._send(200, service.grid_hex(sid))
                elif method == "POST" and tail == "/step":
                    self._send(200, service.step(
                        sid, int(self._body().get("n", 1))))
                elif method == "GET" and tail is None:
                    self._send(200, service.info(sid))
                elif method == "DELETE" and tail is None:
                    self._send(200, service.close(sid))
                else:
                    self._send(404, {"error": f"no route {method} {path}"})

            def do_GET(self) -> None:    # noqa: N802 (http.server API)
                self._dispatch("GET")

            def do_POST(self) -> None:   # noqa: N802
                self._dispatch("POST")

            def do_DELETE(self) -> None:  # noqa: N802
                self._dispatch("DELETE")

            def log_message(self, *args) -> None:
                pass  # per-request stderr noise defeats the step-rate

        self._httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-frontend",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "SessionFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m gameoflifewithactors_tpu serve ...`` — the server
    process (see module docstring for the driver protocol)."""
    ap = argparse.ArgumentParser(
        prog="gameoflifewithactors_tpu serve",
        description="multi-tenant session service over batched lanes")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port (0 = ephemeral; announced on stdout)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--manifest", default=None, metavar="PATH",
                    help="warmup manifest (aot/warmup.py format; entries "
                         "may carry a 'lanes' capacity list)")
    ap.add_argument("--ladder", default=None, metavar="C1,C2,...",
                    help="lane capacity ladder (default 1,8,64,256)")
    ap.add_argument("--checkpoint", default=None, metavar="PATH.npz",
                    help="atomic session checkpoint path (enables "
                         "/admin/checkpoint, --resume, periodic saves)")
    ap.add_argument("--checkpoint-every", type=float, default=30.0,
                    metavar="SECONDS", help="periodic checkpoint interval")
    ap.add_argument("--resume", action="store_true",
                    help="restore sessions from --checkpoint at boot")
    ap.add_argument("--queue-limit", type=int, default=64,
                    help="admission backpressure queue bound")
    ap.add_argument("--headroom", type=float, default=0.85,
                    help="admit while modelled usage stays under this "
                         "fraction of the HBM limit")
    ap.add_argument("--hbm-limit-bytes", type=int, default=None,
                    help="static memory budget override (CPU has no "
                         "device limit gauge; set this to make admission "
                         "control binding)")
    ap.add_argument("--max-restarts", type=int, default=5,
                    help="per-lane consecutive-crash budget before its "
                         "sessions are evicted")
    ap.add_argument("--flight-dump", default=None, metavar="PATH",
                    help="flight recorder dump path (default: next to "
                         "the checkpoint, or serve.flight.jsonl)")
    ap.add_argument("--device-poll", type=float, default=1.0,
                    help="DeviceSampler interval feeding the HBM gauges")
    args = ap.parse_args(argv)

    from ..utils.platform import honor_jax_platforms_env

    honor_jax_platforms_env()

    from ..obs.device import DeviceSampler
    from ..resilience.supervisor import RestartPolicy
    from .lanes import LANE_LADDER

    ladder = (tuple(int(c) for c in args.ladder.split(","))
              if args.ladder else LANE_LADDER)
    admission = AdmissionController(
        registry=REGISTRY, headroom_fraction=args.headroom,
        queue_limit=args.queue_limit,
        static_limit_bytes=args.hbm_limit_bytes)
    service = SessionService(
        ladder=ladder, admission=admission,
        checkpoint_path=args.checkpoint,
        policy=RestartPolicy(max_restarts=args.max_restarts))

    stop = threading.Event()

    def graceful(signum, frame) -> None:
        stop.set()

    # graceful handler FIRST, flight recorder second: the recorder's
    # install() chains onto whatever is there, so one SIGTERM dumps the
    # tape AND requests the clean shutdown — see the chaining regression
    # in tests/test_flight.py
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, graceful)
        except (ValueError, OSError):  # not the main thread
            pass
    flight_path = args.flight_dump or (
        f"{args.checkpoint}.flight.jsonl" if args.checkpoint
        else "serve.flight.jsonl")
    fr = obs_flight.arm(obs_flight.FlightRecorder(flight_path))

    if args.resume and args.checkpoint:
        import os

        if os.path.exists(args.checkpoint):
            n = service.resume()
            print(f"resumed {n} session(s) from {args.checkpoint}",
                  file=sys.stderr)

    if args.manifest:
        from ..aot import warmup as warmup_lib

        entries = warmup_lib.load_manifest_entries(args.manifest)
        for spec, extras in entries:
            if not extras.get("lanes"):
                continue  # engine-only entry; `warmup` precompiles those
            d = spec.canonical()
            d["mesh"] = None  # lanes are single-device by contract
            key = service.warm(d)
            print(f"warmed lane ladder {service.ladder} for {key}",
                  file=sys.stderr)

    sampler = DeviceSampler(args.device_poll, registry=REGISTRY).start()
    frontend = SessionFrontend(service, args.port, host=args.host).start()
    print(f"SERVE_PORT {frontend.port}", flush=True)
    print(f"serving sessions: http://{args.host}:{frontend.port}/ "
          f"(ladder {','.join(str(c) for c in service.ladder)})",
          file=sys.stderr)

    try:
        while not stop.is_set():
            stop.wait(max(0.1, args.checkpoint_every))
            if args.checkpoint and not stop.is_set():
                service.checkpoint()
    finally:
        if args.checkpoint:
            try:
                service.checkpoint()
                print(f"final checkpoint: {args.checkpoint}",
                      file=sys.stderr)
            except Exception as exc:  # noqa: BLE001 — dying anyway
                print(f"final checkpoint failed: {exc}", file=sys.stderr)
        frontend.stop()
        sampler.stop()
        obs_flight.disarm()
    return 0


if __name__ == "__main__":
    sys.exit(main())
