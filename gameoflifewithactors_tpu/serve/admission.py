"""Admission control: price a create against live HBM gauges, then
admit, queue, or reject.

The cost model is the lane layer's own (:meth:`SpecFamily.slot_bytes` —
double-buffered packed words per occupied slot, plus the headroom a
growth-repack to the next ladder rung would claim). The *budget* comes
from the metrics registry: ``hbm_bytes_in_use`` / ``hbm_bytes_limit``
gauges that :class:`obs.device.DeviceSampler` maintains — the same
injectable-backend seam the sampler tests use lets the admission tests
fake an exhausted device without owning one. On CPU the sampler's
host-RSS fallback publishes no ``hbm_bytes_limit`` series, so with no
``static_limit_bytes`` configured the controller is deliberately
permissive (a gauge that does not exist must not reject traffic).

Decisions:

- ``admit`` — modelled usage after the create stays under
  ``headroom_fraction`` × limit;
- ``queue`` — over budget but the bounded backpressure queue has room;
  the create parks (session state ``pending``) until closes/compaction
  free memory, and its queue-wait lands in the
  ``session_queue_wait_seconds`` histogram (custom buckets — the
  registry's step-latency decades are wrong for multi-second waits);
- ``reject`` — over budget and the queue is full: fail fast with 429
  semantics rather than building an unbounded promise backlog.

Stdlib + registry only; no jax — admission must answer while the
backend is wedged (that is precisely when it must say no).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional, Tuple

from ..obs.registry import REGISTRY, MetricsRegistry

ADMIT = "admit"
QUEUE = "queue"
REJECT = "reject"

# queue waits run seconds-to-minutes, not the registry's default
# 100µs..100s step-latency decades
QUEUE_WAIT_BUCKETS = (0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0)

DEFAULT_HEADROOM = 0.85
DEFAULT_QUEUE_LIMIT = 64


class AdmissionRejected(Exception):
    """Raised to the frontend when a create is refused outright."""


class AdmissionController:
    """decide() + the bounded backpressure queue bookkeeping."""

    def __init__(self, *, registry: MetricsRegistry = REGISTRY,
                 headroom_fraction: float = DEFAULT_HEADROOM,
                 queue_limit: int = DEFAULT_QUEUE_LIMIT,
                 static_limit_bytes: Optional[int] = None):
        if not 0.0 < headroom_fraction <= 1.0:
            raise ValueError(
                f"headroom_fraction must be in (0, 1], got "
                f"{headroom_fraction}")
        self.registry = registry
        self.headroom_fraction = float(headroom_fraction)
        self.queue_limit = int(queue_limit)
        self.static_limit_bytes = static_limit_bytes
        self._queue: Deque = deque()
        self._lock = threading.Lock()
        self._decisions = registry.counter(
            "session_admission_total",
            "admission decisions by verdict (admit/queue/reject)")
        self._depth = registry.gauge(
            "session_queue_depth", "creates parked by admission control")
        self._wait = registry.histogram(
            "session_queue_wait_seconds",
            "time creates spent parked in the admission queue",
            buckets=QUEUE_WAIT_BUCKETS)
        self._depth.set(0)

    # -- the budget ----------------------------------------------------------

    def hbm_usage(self) -> Optional[Tuple[float, float]]:
        """(bytes_in_use, bytes_limit) summed over devices from the live
        gauges, or None when no limit is known (no sampler running, or a
        backend — CPU host-RSS — that publishes no capacity)."""
        snap = self.registry.snapshot()
        limit_series = (snap.get("hbm_bytes_limit") or {}).get("series", [])
        limit = sum(s.get("value", 0.0) for s in limit_series)
        if self.static_limit_bytes is not None:
            limit = float(self.static_limit_bytes)
        if not limit:
            return None
        use_series = (snap.get("hbm_bytes_in_use") or {}).get("series", [])
        in_use = sum(s.get("value", 0.0) for s in use_series)
        return in_use, limit

    def decide(self, cost_bytes: int, *, tenant: str = "?",
               pool_needed: int = 0,
               pool_free: Optional[int] = None) -> str:
        """One verdict for a create whose modelled lane cost is
        ``cost_bytes``; records the decision counter.

        ``pool_needed``/``pool_free`` add the tile-pool budget (paged
        lanes price in physical tiles, serve/lanes.PagedLanePool
        .pool_pressure): a create whose seed needs more tiles than the
        pool has free queues or rejects exactly like an HBM overdraft —
        pool exhaustion is a scheduling verdict here, never a raise on
        the placement path."""
        verdict = ADMIT
        usage = self.hbm_usage()
        if usage is not None:
            in_use, limit = usage
            if in_use + cost_bytes > self.headroom_fraction * limit:
                verdict = self._queue_or_reject()
        if verdict == ADMIT and pool_free is not None \
                and pool_needed > pool_free:
            verdict = self._queue_or_reject()
        self._decisions.inc(decision=verdict, tenant=tenant)
        return verdict

    def _queue_or_reject(self) -> str:
        with self._lock:
            depth = len(self._queue)
        return QUEUE if depth < self.queue_limit else REJECT

    # -- the queue -----------------------------------------------------------

    def enqueue(self, item, enqueued_at: float) -> None:
        with self._lock:
            if len(self._queue) >= self.queue_limit:
                raise AdmissionRejected(
                    f"admission queue full ({self.queue_limit})")
            self._queue.append((item, enqueued_at))
            self._depth.set(len(self._queue))

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def drain(self, cost_fn, now: float, fit_fn=None):
        """Pop every queued create that fits the *current* budget (FIFO —
        a big head request blocks smaller ones behind it; fairness over
        utilization). ``cost_fn(item) -> bytes``; optional
        ``fit_fn(item) -> bool`` adds a non-byte budget (tile-pool
        pressure) — a head that does not fit stays at the head, keeping
        its place for the next drain. Yields items and observes their
        queue wait."""
        out = []
        while True:
            with self._lock:
                if not self._queue:
                    break
                item, t0 = self._queue[0]
            usage = self.hbm_usage()
            if usage is not None:
                in_use, limit = usage
                if in_use + cost_fn(item) > self.headroom_fraction * limit:
                    break
            if fit_fn is not None and not fit_fn(item):
                break
            with self._lock:
                # re-check the head: a concurrent drain may have won
                if not self._queue or self._queue[0][0] is not item:
                    continue
                self._queue.popleft()
                self._depth.set(len(self._queue))
            self._wait.observe(max(0.0, now - t0))
            out.append(item)
        return out
