"""serve/: the multi-tenant session service (ROADMAP item 1).

Thousands of logical sessions — tenant-owned live grids — multiplexed
onto few physical executors:

- :mod:`.session` — Session/SessionStore (identity, lifecycle, cursor);
- :mod:`.lanes` — ladder-capacity batched lanes on the masked DP
  runners, with retrace-free dynamic compaction;
- :mod:`.admission` — HBM-gauge-priced admission control with a bounded
  backpressure queue;
- :mod:`.service` — the orchestrator (pump, checkpoint/resume, lane
  crash recovery);
- :mod:`.frontend` — the stdlib HTTP/JSON surface and the ``serve``
  CLI subcommand.
"""

from .admission import (ADMIT, QUEUE, REJECT, AdmissionController,
                        AdmissionRejected)
from .lanes import LANE_LADDER, Lane, LanePool, SpecFamily
from .service import SessionService, decode_words, encode_words
from .session import Session, SessionStore

__all__ = [
    "ADMIT", "QUEUE", "REJECT", "AdmissionController", "AdmissionRejected",
    "LANE_LADDER", "Lane", "LanePool", "SpecFamily",
    "SessionService", "decode_words", "encode_words",
    "Session", "SessionStore",
]
